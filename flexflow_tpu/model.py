"""FFModel: the model-building and training API.

Reference parity: ``FFModel`` (``include/flexflow/model.h:326-958``,
``src/runtime/model.cc``) — layer builder methods (dense/conv2d/embedding/
multihead_attention/moe/...), ``compile`` (graph lowering + strategy
search + executable build), ``fit``/``forward``/``backward``/``update``
training drivers, and ``eval``.

TPU-native differences:
  - ``compile`` lowers the lazy Layer graph to a jitted SPMD step over a
    device mesh instead of Legion index-space task launches;
  - the parallelization strategy is a per-op PartitionSpec assignment found
    by the search (search/), or canonical data-parallel with
    ``--only-data-parallel``;
  - backward is jax.grad; gradient sync is XLA collectives implied by
    weight shardings (reference: per-view NCCL cliques, model.cc:3129).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .config import FFConfig, FFIterationConfig
from .core.layer import Layer
from .core.tensor import Tensor, WeightSpec
from .dtypes import from_numpy_dtype, to_jnp
from .executor import Executor, GraphProgram
from .ffconst import (ActiMode, AggrMode, CompMode, DataType, InitializerType,
                      LossType, MetricsType, OperatorType, ParameterSyncType,
                      PoolType)
from .ops import get_op_def
from .parallel.machine import DeviceMesh, MachineSpec
from .parallel.strategy import ShardingStrategy
from .runtime.dataloader import SingleDataLoader
from .runtime.metrics import PerfMetrics
from .runtime.metrics_buffer import MetricsBuffer
from .runtime.optimizers import AdamOptimizer, Optimizer, SGDOptimizer

_LOSS_NAMES = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "identity": LossType.LOSS_IDENTITY,
}

_METRIC_NAMES = {
    "accuracy": MetricsType.METRICS_ACCURACY,
    "categorical_crossentropy": MetricsType.METRICS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
}


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.layers: List[Layer] = []
        self.input_tensors: List[Tensor] = []
        self.graph_inputs: List[Tensor] = []
        self.optimizer: Optional[Optimizer] = None
        self.loss_type: Optional[LossType] = None
        self.metrics: List[MetricsType] = []
        self.label_tensor: Optional[Tensor] = None
        self.executor: Optional[Executor] = None
        self.dmesh: Optional[DeviceMesh] = None
        self.strategy: Optional[ShardingStrategy] = None
        self.params = None
        self.state = None
        self.opt_state = None
        self.iter_config = FFIterationConfig()
        self._step = 0
        self._output_tensor: Optional[Tensor] = None
        self._dataloaders: List[Tuple[Tensor, np.ndarray]] = []
        self._current_metrics: Optional[Dict[str, float]] = None
        # live deferred-metrics accumulator while a training driver
        # (fit / resilience supervisor) is running — checkpoint saves
        # flush + NaN-screen through it (runtime/metrics_buffer.py)
        self._metrics_buffer: Optional[MetricsBuffer] = None

    # ==================================================================
    # graph construction helpers
    # ==================================================================
    def _add_layer(self, op_type: OperatorType, inputs: Sequence[Tensor],
                   params: Dict[str, Any], name: Optional[str] = None
                   ) -> Layer:
        if name is None:
            # deterministic per-model naming (layer index, not a global
            # counter) so params/checkpoints from two identically-built
            # models share keys — required for checkpoint restore
            name = f"{OperatorType(op_type).name.lower()}_{len(self.layers)}"
        # params/strategy dicts are name-keyed: uniquify collisions
        used = {l.name for l in self.layers}
        base, k = name, 1
        while name in used:
            name = f"{base}_{k}"
            k += 1
        layer = Layer(op_type, name, list(inputs), params)
        op = get_op_def(op_type)
        in_shapes = [t.shape for t in inputs]
        in_dtypes = [t.dtype for t in inputs]
        out_specs = op.infer(layer.params, in_shapes, in_dtypes)
        for i, (shape, dt) in enumerate(out_specs):
            layer.outputs.append(Tensor(shape, dt, layer, i,
                                        name=f"{layer.name}:out{i}"))
        # resolve weight specs now so the search's cost model sees
        # weight memory + gradient-sync volumes (executor reuses these)
        layer.weights = op.weights(layer.params, in_shapes, in_dtypes)
        self.layers.append(layer)
        return layer

    def _unary(self, op_type: OperatorType, x: Tensor, name=None, **params
               ) -> Tensor:
        return self._add_layer(op_type, [x], params, name).outputs[0]

    def _binary(self, op_type: OperatorType, a: Tensor, b: Tensor, name=None
                ) -> Tensor:
        return self._add_layer(op_type, [a, b], {}, name).outputs[0]

    # ==================================================================
    # tensor creation (reference FFModel::create_tensor)
    # ==================================================================
    def create_tensor(self, dims: Sequence[int],
                      dtype: DataType = DataType.DT_FLOAT,
                      create_grad: bool = True, name: Optional[str] = None
                      ) -> Tensor:
        t = Tensor(dims, dtype, None, 0, name=name, create_grad=create_grad)
        self.input_tensors.append(t)
        return t

    def create_constant(self, dims: Sequence[int], value: float,
                        dtype: DataType = DataType.DT_FLOAT) -> Tensor:
        t = self.create_tensor(dims, dtype, create_grad=False)
        t.set_tensor(np.full(dims, value, dtype=np.dtype(to_jnp(dtype))))
        return t

    # ==================================================================
    # layer builders (reference model.h:326-958)
    # ==================================================================
    def dense(self, input: Tensor, out_dim: int,
              activation: ActiMode = ActiMode.AC_MODE_NONE,
              use_bias: bool = True,
              datatype: Optional[DataType] = None,
              kernel_initializer=None, bias_initializer=None,
              kernel_regularizer=None, name: Optional[str] = None) -> Tensor:
        params = {"out_dim": out_dim, "activation": ActiMode(activation),
                  "use_bias": use_bias}
        if datatype is not None:
            params["dtype"] = DataType(datatype)
        if kernel_initializer is not None:
            params["kernel_initializer"] = kernel_initializer
        return self._add_layer(OperatorType.OP_LINEAR, [input], params,
                               name).outputs[0]

    def conv2d(self, input: Tensor, out_channels: int,
               kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
               padding_h: int, padding_w: int,
               activation: ActiMode = ActiMode.AC_MODE_NONE,
               groups: int = 1, use_bias: bool = True,
               kernel_initializer=None, name: Optional[str] = None) -> Tensor:
        params = {"out_channels": out_channels, "kernel_h": kernel_h,
                  "kernel_w": kernel_w, "stride_h": stride_h,
                  "stride_w": stride_w, "padding_h": padding_h,
                  "padding_w": padding_w, "activation": ActiMode(activation),
                  "groups": groups, "use_bias": use_bias}
        if kernel_initializer is not None:
            params["kernel_initializer"] = kernel_initializer
        return self._add_layer(OperatorType.OP_CONV2D, [input], params,
                               name).outputs[0]

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               pool_type: PoolType = PoolType.POOL_MAX,
               activation: ActiMode = ActiMode.AC_MODE_NONE,
               name: Optional[str] = None) -> Tensor:
        params = {"kernel_h": kernel_h, "kernel_w": kernel_w,
                  "stride_h": stride_h, "stride_w": stride_w,
                  "padding_h": padding_h, "padding_w": padding_w,
                  "pool_type": PoolType(pool_type),
                  "activation": ActiMode(activation)}
        return self._add_layer(OperatorType.OP_POOL2D, [input], params,
                               name).outputs[0]

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
                  dtype: DataType = DataType.DT_FLOAT,
                  shared_op=None, kernel_initializer=None,
                  name: Optional[str] = None) -> Tensor:
        params = {"num_entries": num_entries, "out_dim": out_dim,
                  "aggr": AggrMode(aggr), "dtype": DataType(dtype)}
        if kernel_initializer is not None:
            params["kernel_initializer"] = kernel_initializer
        return self._add_layer(OperatorType.OP_EMBEDDING, [input], params,
                               name).outputs[0]

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int,
                            kdim: int = 0, vdim: int = 0, dropout: float = 0.0,
                            bias: bool = True, add_bias_kv: bool = False,
                            add_zero_attn: bool = False, causal: bool = False,
                            rope: bool = False, rope_theta: float = 10000.0,
                            num_kv_heads: int = 0,
                            sliding_window: int = 0,
                            kernel_initializer=None,
                            name: Optional[str] = None) -> Tensor:
        params = {"embed_dim": embed_dim, "num_heads": num_heads,
                  "kdim": kdim, "vdim": vdim, "dropout": dropout,
                  "bias": bias, "add_bias_kv": add_bias_kv,
                  "add_zero_attn": add_zero_attn, "causal": causal}
        if num_kv_heads and num_kv_heads != num_heads:
            # grouped-query attention (LLaMA-2/3 family): kv projections
            # and the KV cache carry num_kv_heads head groups
            if num_heads % num_kv_heads != 0:
                raise ValueError(
                    f"num_kv_heads {num_kv_heads} must divide "
                    f"num_heads {num_heads}")
            params["num_kv_heads"] = int(num_kv_heads)
        if sliding_window:
            # Mistral-family local attention: queries see the last
            # `sliding_window` positions only (requires causal)
            if not causal:
                raise ValueError("sliding_window requires causal "
                                 "attention")
            if sliding_window <= 0:
                raise ValueError(
                    f"sliding_window must be positive, "
                    f"got {sliding_window}")
            params["sliding_window"] = int(sliding_window)
        if rope:
            # in-op rotary embeddings (LLaMA family; enables the fused
            # flash-attention and KV-decode paths for RoPE models)
            params["rope"] = True
            params["rope_theta"] = float(rope_theta)
        return self._add_layer(OperatorType.OP_MULTIHEAD_ATTENTION,
                               [query, key, value], params, name).outputs[0]

    def batch_norm(self, input: Tensor, relu: bool = True,
                   eps: float = 1e-5, momentum: float = 0.1,
                   name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_BATCHNORM, input, name, relu=relu,
                           eps=eps, momentum=momentum)

    def layer_norm(self, input: Tensor, axes: Sequence[int],
                   elementwise_affine: bool = True, eps: float = 1e-5,
                   name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_LAYERNORM, input, name,
                           axes=list(axes),
                           elementwise_affine=elementwise_affine, eps=eps)

    def rms_norm(self, input: Tensor, eps: float = 1e-6,
                 name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_RMSNORM, input, name, eps=eps)

    def lstm(self, input: Tensor, hidden_size: int, num_layers: int = 1,
             name: Optional[str] = None) -> Tensor:
        """Multi-layer LSTM over (batch, seq, features) — lax.scan
        recurrence (reference: legacy nmt/lstm.cu app)."""
        return self._unary(OperatorType.OP_LSTM, input, name,
                           hidden_size=hidden_size, num_layers=num_layers)

    def batch_matmul(self, a: Tensor, b: Tensor,
                     a_seq_length_dim: int = -1, b_seq_length_dim: int = -1,
                     name: Optional[str] = None) -> Tensor:
        return self._add_layer(OperatorType.OP_BATCHMATMUL, [a, b],
                               {"a_seq_length_dim": a_seq_length_dim,
                                "b_seq_length_dim": b_seq_length_dim},
                               name).outputs[0]

    def softmax(self, input: Tensor, axis: int = -1,
                name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_SOFTMAX, input, name, axis=axis)

    def dropout(self, input: Tensor, rate: float = 0.5, seed: int = 0,
                name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_DROPOUT, input, name, rate=rate,
                           seed=seed)

    def flat(self, input: Tensor, name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_FLAT, input, name)

    def concat(self, tensors: Sequence[Tensor], axis: int,
               name: Optional[str] = None) -> Tensor:
        return self._add_layer(OperatorType.OP_CONCAT, list(tensors),
                               {"axis": axis}, name).outputs[0]

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]],
              axis: int, name: Optional[str] = None) -> List[Tensor]:
        if isinstance(sizes, int):
            n = input.shape[axis % len(input.shape)] // sizes
            sizes = [n] * sizes
        return self._add_layer(OperatorType.OP_SPLIT, [input],
                               {"sizes": list(sizes), "axis": axis},
                               name).outputs

    def reshape(self, input: Tensor, shape: Sequence[int],
                name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_RESHAPE, input, name,
                           shape=list(shape))

    def transpose(self, input: Tensor, perm: Sequence[int],
                  name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_TRANSPOSE, input, name,
                           perm=list(perm))

    def reverse(self, input: Tensor, axis: int,
                name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_REVERSE, input, name, axis=axis)

    # ---- elementwise binary ----
    def add(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_ADD, x, y, name)

    def subtract(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_SUB, x, y, name)

    def multiply(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_MUL, x, y, name)

    def divide(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_DIV, x, y, name)

    def max(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_MAX, x, y, name)

    def equal(self, x, y, name=None):
        """Elementwise equality (DT_BOOLEAN output, broadcasting) —
        reference OP_EW_EQUAL (onnx Equal)."""
        return self._binary(OperatorType.OP_EW_EQUAL, x, y, name)

    def greater(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_GREATER, x, y, name)

    def less(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_LESS, x, y, name)

    def min(self, x, y, name=None):
        return self._binary(OperatorType.OP_EW_MIN, x, y, name)

    # ---- elementwise unary ----
    def relu(self, x, name=None):
        return self._unary(OperatorType.OP_RELU, x, name)

    def sigmoid(self, x, name=None):
        return self._unary(OperatorType.OP_SIGMOID, x, name)

    def tanh(self, x, name=None):
        return self._unary(OperatorType.OP_TANH, x, name)

    def elu(self, x, name=None):
        return self._unary(OperatorType.OP_ELU, x, name)

    def gelu(self, x, name=None):
        return self._unary(OperatorType.OP_GELU, x, name)

    def identity(self, x, name=None):
        return self._unary(OperatorType.OP_IDENTITY, x, name)

    def exp(self, x, name=None):
        return self._unary(OperatorType.OP_EXP, x, name)

    def log(self, x, name=None):
        return self._unary(OperatorType.OP_LOG, x, name)

    def sqrt(self, x, name=None):
        return self._unary(OperatorType.OP_SQRT, x, name)

    def rsqrt(self, x, name=None):
        return self._unary(OperatorType.OP_RSQRT, x, name)

    def sin(self, x, name=None):
        return self._unary(OperatorType.OP_SIN, x, name)

    def cos(self, x, name=None):
        return self._unary(OperatorType.OP_COS, x, name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(OperatorType.OP_POW, x, name, scalar=exponent)

    def scalar_multiply(self, x, scalar: float, inplace=False, name=None):
        return self._unary(OperatorType.OP_SCALAR_MULTIPLY, x, name,
                           scalar=scalar)

    def scalar_add(self, x, scalar: float, inplace=False, name=None):
        return self._unary(OperatorType.OP_SCALAR_ADD, x, name, scalar=scalar)

    def scalar_sub(self, x, scalar: float, inplace=False, name=None):
        return self._unary(OperatorType.OP_SCALAR_SUB, x, name, scalar=scalar)

    def scalar_true_divide(self, x, scalar: float, inplace=False, name=None):
        return self._unary(OperatorType.OP_SCALAR_TRUE_DIV, x, name,
                           scalar=scalar)

    def cast(self, x, dtype: DataType, name=None):
        return self._unary(OperatorType.OP_CAST, x, name,
                           dtype=DataType(dtype))

    def mean(self, x, dims: Sequence[int], keepdims: bool = False, name=None):
        return self._unary(OperatorType.OP_MEAN, x, name, axes=list(dims),
                           keepdims=keepdims)

    def reduce_sum(self, x, axes: Sequence[int], keepdims: bool = False,
                   name=None):
        return self._unary(OperatorType.OP_REDUCE_SUM, x, name,
                           axes=list(axes), keepdims=keepdims)

    def slice_tensor(self, x: Tensor, starts: Sequence[int],
                     ends: Sequence[int], axes: Optional[Sequence[int]] = None,
                     name=None):
        return self._unary(OperatorType.OP_SLICE, x, name,
                           starts=list(starts), ends=list(ends),
                           axes=list(axes) if axes is not None else
                           list(range(len(starts))))

    def squeeze(self, x: Tensor, axes: Sequence[int], name=None):
        return self._unary(OperatorType.OP_SQUEEZE, x, name, axes=list(axes))

    def unsqueeze(self, x: Tensor, axes: Sequence[int], name=None):
        return self._unary(OperatorType.OP_UNSQUEEZE, x, name,
                           axes=list(axes))

    def pad(self, x: Tensor, pads: Sequence[Tuple[int, int]],
            value: float = 0.0, name=None):
        return self._unary(OperatorType.OP_PAD, x, name,
                           pads=[tuple(p) for p in pads], value=value)

    def gather(self, x: Tensor, index: Tensor, dim: int = 0, name=None):
        return self._add_layer(OperatorType.OP_GATHER, [x, index],
                               {"dim": dim}, name).outputs[0]

    def top_k(self, input: Tensor, k: int, sorted: bool = False,
              name: Optional[str] = None) -> List[Tensor]:
        return self._add_layer(OperatorType.OP_TOPK, [input],
                               {"k": k, "sorted": sorted}, name).outputs

    # ---- MoE family (reference src/ops/moe.cc:20-44) ----
    def group_by(self, input: Tensor, assign: Tensor, n: int,
                 alpha: float = 1.0, name: Optional[str] = None
                 ) -> List[Tensor]:
        return self._add_layer(OperatorType.OP_GROUP_BY, [input, assign],
                               {"n": n, "alpha": alpha}, name).outputs

    def aggregate(self, inputs: Sequence[Tensor], n: int,
                  lambda_bal: float = 0.0, name: Optional[str] = None
                  ) -> Tensor:
        return self._add_layer(OperatorType.OP_AGGREGATE, list(inputs),
                               {"n": n, "lambda_bal": lambda_bal},
                               name).outputs[0]

    def aggregate_spec(self, inputs: Sequence[Tensor], n: int,
                       lambda_bal: float = 0.0, name: Optional[str] = None
                       ) -> Tensor:
        return self._add_layer(OperatorType.OP_AGG_SPEC, list(inputs),
                               {"n": n, "lambda_bal": lambda_bal},
                               name).outputs[0]

    def cache(self, input: Tensor, num_batches: int, score_fn=None,
              name: Optional[str] = None) -> Tensor:
        return self._unary(OperatorType.OP_CACHE, input, name,
                           num_batches=num_batches)

    def moe(self, input: Tensor, num_exp: int, num_select: int,
            expert_hidden_size: int, alpha: float = 1.0,
            lambda_bal: float = 0.0) -> Tensor:
        """MoE composite — same wiring as reference ``FFModel::moe``
        (``src/ops/moe.cc:20-44``)."""
        gate_preds = self.dense(input, num_exp, ActiMode.AC_MODE_RELU)
        topk_out = self.top_k(gate_preds, num_select, False)
        exp_tensors = self.group_by(input, topk_out[1], num_exp, alpha)
        agg_inputs = [self.softmax(topk_out[0]), topk_out[1], topk_out[1],
                      gate_preds]
        for i in range(num_exp):
            exp_pred = self.dense(exp_tensors[i], expert_hidden_size,
                                  ActiMode.AC_MODE_RELU)
            agg_inputs.append(self.softmax(exp_pred))
        return self.aggregate(agg_inputs, num_exp, lambda_bal)

    # ==================================================================
    # optimizer / compile / fit (reference model.cc:2803, cffi fit)
    # ==================================================================
    def set_optimizer(self, optimizer: Optimizer):
        self.optimizer = optimizer

    optimizer_prop = property(lambda s: s.optimizer, set_optimizer)

    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: Union[LossType, str, None] = None,
                metrics: Optional[Sequence[Union[MetricsType, str]]] = None,
                comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
                machine_spec: Optional[MachineSpec] = None,
                strategy: Optional[ShardingStrategy] = None,
                output_tensor: Optional[Tensor] = None,
                search_budget: Optional[int] = None):
        """Lower graph → (strategy, jitted step). Reference call stack:
        ``FFModel::compile`` → graph_optimize → convert_graph_to_operators
        → NCCL setup (``model.cc:2803-3168``)."""
        from .obs import events as obs_events
        obs_events.configure(self.config)
        _compile_t0 = time.perf_counter()
        if self.config.compilation_cache_dir \
                or os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            from .utils.compilation_cache import enable_compilation_cache
            enable_compilation_cache(
                self.config.compilation_cache_dir or None)
        if optimizer is not None:
            self.optimizer = optimizer
        if self.optimizer is None:
            self.optimizer = SGDOptimizer(lr=self.config.learning_rate)
        if isinstance(loss_type, str):
            loss_type = _LOSS_NAMES[loss_type.lower()]
        self.loss_type = LossType(loss_type) if loss_type is not None \
            else LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
        self.metrics = [
            _METRIC_NAMES[m.lower()] if isinstance(m, str) else MetricsType(m)
            for m in (metrics or [])]

        # output tensor = last layer's first output unless specified
        self._output_tensor = output_tensor or self.layers[-1].outputs[0]

        # Partition created tensors into graph inputs (consumed by a layer)
        # and the label tensor (created but unconsumed) — reference compile
        # creates the label tensor itself (model.cc:3086).
        consumed = {t.guid for l in self.layers for t in l.inputs}
        # constants (attached host values) are baked in at trace time, not
        # fed per batch
        self.graph_inputs = [t for t in self.input_tensors
                             if t.guid in consumed
                             and t.get_tensor() is None]
        self.const_inputs = [t for t in self.input_tensors
                             if t.guid in consumed
                             and t.get_tensor() is not None]
        unconsumed = [t for t in self.input_tensors
                      if t.guid not in consumed
                      and t.get_tensor() is None]
        if self.label_tensor is None and len(unconsumed) == 1:
            self.label_tensor = unconsumed[0]

        # join the multi-host world first (reference: GASNet launch +
        # control replication happen before graph_optimize) so that
        # MachineSpec.detect sees the GLOBAL device view
        from .parallel.distributed import maybe_initialize
        if maybe_initialize(self.config):
            # multi-process world: start the failure-detection layer
            # (per-rank heartbeats + bounded barriers) alongside it —
            # every later cross-rank wait goes through it
            from .resilience import coord
            c = coord.ensure_started(self.config)
            try:
                # clock handshake for cross-rank trace alignment
                # (tools/fftrace.py): one bounded barrier, every rank
                # anchors its monotonic clock at the release instant.
                # Unconditional — every rank reaches compile, so the
                # rendezvous can never depend on per-rank trace flags
                c.clock_sync("compile")
            except Exception:  # noqa: BLE001 — alignment is best-effort
                pass
        if machine_spec is not None:
            spec = machine_spec
        elif self.config.machine_model_file:
            # --machine-model-file: the described machine drives the cost
            # model / simulator / topology (reference machine_model.cc);
            # execution is clamped to the live devices
            spec = MachineSpec.from_file(self.config.machine_model_file)
            import jax
            spec.num_devices = min(spec.num_devices, len(jax.devices()))
        else:
            spec = MachineSpec.detect()
        mesh_shape = self.config.mesh_shape
        pp = self.config.pipeline_stages
        pp_tp = max(self.config.pipeline_tp, 1)
        if pp_tp > 1 and pp <= 1:
            raise ValueError(
                f"--pp-tp {pp_tp} requires --pp > 1 (stage-internal "
                f"tensor parallelism only exists inside a pipeline); "
                f"for tp without pipelining use a transformer_strategy "
                f"or the search")
        if mesh_shape is None and pp <= 1 and strategy is None \
                and self.config.machine_model_file \
                and not self.config.import_strategy_file \
                and getattr(spec, "ici_shape", None) \
                and int(np.prod(spec.ici_shape)) == spec.num_devices:
            # the described machine's ICI topology drives the mesh layout
            # (reference machine_model.cc: the machine file IS the view).
            # Strategy imports keep the default factorization — the
            # saved mesh_axes must keep matching what compile builds.
            mesh_shape = tuple(spec.ici_shape)
        if strategy is None and pp > 1 and mesh_shape is None:
            # dp × pp (× tp) mesh: middle axis carries the pipeline
            # stages, trailing axis the stage-internal tensor split
            nd = spec.num_devices
            if nd % (pp * pp_tp) != 0:
                raise ValueError(f"--pp {pp} x --pp-tp {pp_tp} does "
                                 f"not divide {nd} devices")
            mesh_shape = tuple(
                d for d in (nd // (pp * pp_tp), pp, pp_tp) if d > 1)
        seq_par = max(int(getattr(self.config, "seq_parallel_degree", 0)
                          or 0), 0)
        if seq_par > 1 and (pp > 1 or self.config.tensor_parallel > 1):
            raise ValueError(
                "--seq-parallel (the reserved ring-attention axis) does "
                "not compose with --pp/--tp presets; use the search")
        self.dmesh = DeviceMesh(spec, mesh_shape=mesh_shape,
                                seq=seq_par)
        if search_budget is not None:
            self.config.search_budget = search_budget

        exec_layers, exec_outputs = self.layers, [self._output_tensor]
        tp_deg = max(self.config.tensor_parallel, 1)
        if self.config.sequence_parallel and tp_deg <= 1:
            raise ValueError(
                "--sp requires --tp N (N > 1): the sequence dim is "
                "sharded over the tensor-parallel axes")
        if tp_deg > 1 and pp > 1:
            raise ValueError(
                "--tp does not compose with --pp directly; use --pp-tp "
                "for Megatron tp inside pipeline stages")
        if strategy is None and tp_deg > 1:
            # --tp/--sp: the Megatron dp x tp (x sp) preset directly,
            # no search (reference --enable-parameter-parallel analog
            # made a first-class mode). An existing mesh (explicit
            # --mesh-shape or the machine file's ICI shape) is kept and
            # validated; otherwise a (dp, tp) mesh is built.
            from .parallel.presets import transformer_strategy
            nd = self.dmesh.num_devices
            if nd % tp_deg != 0:
                raise ValueError(
                    f"--tp {tp_deg} does not divide {nd} devices")
            if mesh_shape is None:
                self.dmesh = DeviceMesh(
                    spec, mesh_shape=tuple(
                        d for d in (nd // tp_deg, tp_deg) if d > 1))
            axes = self.dmesh.axis_names
            # trailing axes must realize EXACTLY the requested degree
            tp_axes: list = []
            prod = 1
            for ax in reversed(axes):
                if prod == tp_deg:
                    break
                tp_axes.insert(0, ax)
                prod *= self.dmesh.axis_sizes[ax]
            if prod != tp_deg:
                raise ValueError(
                    f"--tp {tp_deg} not realizable from the trailing "
                    f"axes of mesh {dict(self.dmesh.axis_sizes)} "
                    f"(they give {prod}); pass a compatible --mesh-shape")
            dp_axes = tuple(a for a in axes if a not in tp_axes)
            strategy = transformer_strategy(
                self.layers, self.input_tensors, self.dmesh,
                dp_axes=dp_axes, tp_axes=tuple(tp_axes),
                sp=self.config.sequence_parallel)
        if strategy is None and pp > 1:
            # pipeline through the product path (reference reserves
            # OP_PIPELINE, ffconst.h:159, without implementing it);
            # axes resolved by position to keep dp/pp/tp unambiguous
            # when sizes coincide
            from .parallel.presets import pipeline_strategy
            kw = {}
            if self.config.mesh_shape is None:
                # we built the mesh as (dp, pp, tp) above — bind axes by
                # position (size-matching is ambiguous when sizes tie);
                # an explicit --mesh-shape keeps the size-match default
                nd = self.dmesh.num_devices
                sizes = (nd // (pp * pp_tp), pp, pp_tp)
                roles = [r for r, d in zip(("dp", "pp", "tp"), sizes)
                         if d > 1]
                by_role = dict(zip(roles, self.dmesh.axis_names))
                kw = dict(pp_axis=by_role["pp"],
                          tp_axis=by_role.get("tp"),
                          dp_axes=(by_role["dp"],) if "dp" in by_role
                          else ())
            strategy = pipeline_strategy(
                self.layers, self.graph_inputs, self.dmesh, n_stages=pp,
                n_microbatches=self.config.pipeline_microbatches,
                n_chunks=self.config.pipeline_chunks, tp=pp_tp,
                ragged=self.config.pipeline_ragged, **kw)
        if strategy is not None:
            self.strategy = strategy
        else:
            _t0 = time.perf_counter()
            self.strategy, program_info = self._optimize_strategy()
            self._compile_phases = {
                "search_s": round(time.perf_counter() - _t0, 3)}
            if self.strategy.dmesh is not self.dmesh:
                # the search chose a strategy on its own mesh layout
                # (e.g. a (dp, S) pipeline mesh) — adopt it
                self.dmesh = self.strategy.dmesh
            if program_info is not None:
                # search rewrote the graph (inserted parallel ops) —
                # reference convert_graph_to_operators (model.cc:2834)
                exec_layers = program_info.layers
                exec_outputs = program_info.output_tensors
                self._output_tensor = exec_outputs[0]

        # label tensor adopts the final op's batch sharding
        # (reference model.cc:3086-3124)
        prebuilt = getattr(self, "_prebuilt_executor", None)
        if prebuilt is not None and prebuilt[0] is self.strategy \
                and prebuilt[1] is not None:
            # the floor guard already compiled this exact program
            # (same strategy object, same metrics) — adopt its executor
            # so the jitted train step is not rebuilt; params/state are
            # re-initialized below
            self.executor = prebuilt[1]
            self._prebuilt_executor = None
        else:
            program = GraphProgram(exec_layers,
                                   self.graph_inputs + self.const_inputs,
                                   exec_outputs)
            self.executor = Executor(program, self.config, self.dmesh,
                                     self.strategy, self.optimizer,
                                     self.loss_type, self.metrics,
                                     seed=self.config.seed)
        # searched data movement: one reshard planner per strategy plans
        # every layout transition (bank boundaries, pipeline-region
        # entry/exit, layout-op output constraints) with scored explicit
        # collectives; chosen step sequences annotate the strategy audit
        from .parallel.reshard import ReshardPlanner
        pl = getattr(self.strategy, "resharder", None)
        if pl is None or pl.dmesh is not self.dmesh:
            pl = ReshardPlanner(self.dmesh)
            self.strategy.resharder = pl
        pl.audit_path = getattr(self, "_strategy_audit_path", None)
        # overlap (runtime/overlap.py): multi-leg tier-staged reshard
        # plans execute with their fabric legs pipelined when on
        from .runtime.overlap import overlap_enabled
        pl.overlap_on = overlap_enabled(self.config)
        if self.config.export_strategy_file \
                and getattr(self.strategy, "overlap", None):
            # the search exported before the executor built the bucket
            # schedule (same ordering as banks/zero): rewrite the
            # overlap section so --import round-trips the exact
            # schedule this compile audited and verified
            try:
                import json as _json
                with open(self.config.export_strategy_file) as f:
                    doc = _json.load(f)
                doc["overlap"] = dict(self.strategy.overlap)
                with open(self.config.export_strategy_file, "w") as f:
                    _json.dump(doc, f, indent=1)
            except Exception:  # noqa: BLE001 — export is best-effort
                pass
        # per-parameter ZeRO (search/zero_plan.py, arXiv 2004.13336):
        # score each parameter's update path (replicated all-reduce vs
        # reduce-scatter + sharded update + all-gather over the placed
        # tier path) and adopt an assignment under the device-memory
        # envelope. Runs BEFORE plan verification so the verifier's
        # memory envelope and zero-soundness checks bind on the
        # assignment the run will actually use. The uniform --zero flag
        # bypasses this entirely (pinned legacy behavior below).
        self._plan_zero()
        # quantized gradient collectives (ops/quantized_collectives.py,
        # arXiv 2506.17615): plan per-tensor/per-phase wire dtypes for
        # gradient sync, scored by the same calibrated cost model.
        # Runs BEFORE plan verification so the qsync check binds on the
        # plan the run will actually use.
        self._plan_qsync()
        # searchable kernel tier (kernels/registry.py): adopt a per-op
        # implementation assignment (attention xla/flash/ring, the
        # optimizer update fused/unfused) — searched by calibrated cost,
        # forced by --kernel-impl, imported verbatim. Runs BEFORE plan
        # verification so the kernel check and the seq-aware memory
        # envelope bind on the impls the run will actually execute.
        self._plan_kernels()
        # static plan verification (analysis/plan_verifier.py): prove
        # the adopted strategy executable — axis soundness, shard
        # divisibility, legal reshard lowerings at every seam, memory
        # envelope, collective-order consistency — BEFORE params
        # materialize; an unsound plan raises PlanVerificationError
        # with the op/seam attributed instead of miscompiling later
        if self.config.plan_verify \
                and os.environ.get("FF_PLAN_VERIFY", "") != "0":
            from .analysis.plan_verifier import verify_model
            _t0 = time.perf_counter()
            report = verify_model(self)
            self.__dict__.setdefault("_compile_phases", {})["verify_s"] \
                = round(time.perf_counter() - _t0, 6)
            self._plan_verify_report = report
        _t0 = time.perf_counter()
        self.params, self.state = self.executor.init_params_and_state()
        if hasattr(self, "_compile_phases"):
            # init/materialization separated from search: on a virtual
            # many-device CPU mesh the replicated-shard host copies
            # dominate, which would misattribute wall time to the search
            self._compile_phases["init_s"] = round(
                time.perf_counter() - _t0, 3)
        self.opt_state = self.optimizer.init_state(self.params)
        if self.config.shard_optimizer_states and self.opt_state:
            # ZeRO-1: moments sharded over the axes their weight is
            # replicated on (runtime/zero.py); the executor pins the
            # updated state to the same placement inside the step
            from .runtime.zero import (shard_optimizer_state,
                                       state_constraints)
            self.opt_state = shard_optimizer_state(self.opt_state,
                                                   self.dmesh)
            self.executor.opt_state_constraints = \
                state_constraints(self.opt_state)
        elif self.opt_state and getattr(self.strategy, "zero", None):
            # per-parameter searched assignment: only the leaves the
            # plan shards move; the executor pins the updated state to
            # the assigned specs in-jit so GSPMD lowers the update to
            # reduce-scatter + sharded math + all-gather per leaf
            from .runtime.zero import (shard_optimizer_state,
                                       state_constraints)
            self.opt_state = shard_optimizer_state(
                self.opt_state, self.dmesh, self.strategy.zero)
            self.executor.opt_state_constraints = \
                state_constraints(self.opt_state)
        if getattr(self.executor, "_qsync", None) is not None \
                and isinstance(self.opt_state, dict):
            # error-feedback residuals for the quantized grad sync:
            # sharding-aware runtime state seeded at zero, one
            # (degree,) + shape leaf per quantized tensor, riding the
            # optimizer-state tree (checkpointed with it; the executor
            # strips the slot before the optimizer update)
            from .ops import quantized_collectives as qsync_mod
            res = qsync_mod.init_residuals(
                self.executor._qsync, self.executor.program, self.dmesh)
            if res:
                self.opt_state[qsync_mod.RESIDUAL_SLOT] = res
        self._step = 0
        self.__dict__.setdefault("_compile_phases", {})["compile_s"] = \
            round(time.perf_counter() - _compile_t0, 6)
        # recompile observability for the warm-start path: every program
        # (re)build increments the per-model counter — a fleet whose
        # persistent compilation cache is actually warm shows this flat
        # across process restarts while compile_s collapses to the
        # cache-hit cost
        from .obs.metrics_registry import REGISTRY
        REGISTRY.counter(
            "ff_model_compiles_total",
            "Model program compiles (trace + XLA build events)").inc(
            model=getattr(self, "_model_name", "") or "<unnamed>")
        obs_events.record_span("model.compile", _compile_t0,
                               time.perf_counter() - _compile_t0,
                               n_devices=self.dmesh.num_devices,
                               n_layers=len(self.layers))

    def _optimize_strategy(self):
        """Strategy selection: search unless --only-data-parallel.
        Returns (strategy, program_info_or_None) — Unity search may rewrite
        the executable graph."""
        # On one device the search still matters when a budget is set
        # explicitly: algebraic substitutions (fusions/eliminations) can
        # rewrite the graph even without parallelism choices.
        single_no_budget = (self.dmesh.num_devices == 1
                            and self.config.search_budget <= 0)
        if self.config.only_data_parallel or single_no_budget \
                or self.config.search_algo == "dp":
            return ShardingStrategy.data_parallel(
                self.layers, self.graph_inputs, self.dmesh), None
        import importlib.util
        if importlib.util.find_spec("flexflow_tpu.search") is None:
            return ShardingStrategy.data_parallel(
                self.layers, self.graph_inputs, self.dmesh), None
        from .search.optimizer import optimize_strategy
        return optimize_strategy(self)

    def _plan_zero(self):
        """Adopt a per-parameter optimizer-state sharding assignment
        (``FFConfig.zero_policy``, search/zero_plan.py). An assignment
        already on the strategy (``--import`` round-trip) is honored
        as-is; the legacy uniform ``--zero`` flag bypasses planning
        entirely (its behavior is pinned bit-identical)."""
        cfg = self.config
        if self.strategy is None:
            return
        if self.config.shard_optimizer_states:
            self.strategy.zero = None
            return
        if getattr(self.strategy, "zero", None) is not None:
            return  # imported with the strategy: honor it verbatim
        policy = str(getattr(cfg, "zero_policy", "off") or "off").lower()
        if policy in ("off", "false", "no", ""):
            return
        if policy not in ("auto", "memory", "all"):
            raise ValueError(
                f"unknown zero_policy {policy!r} "
                f"(expected off/auto/memory/all)")
        from .runtime.zero import opt_slots
        if self.dmesh.num_devices <= 1 \
                or opt_slots(self.optimizer) <= 0:
            return
        if getattr(self.strategy, "pipeline", None) is not None:
            # pipelined regions stack their parameters (and state)
            # under template keys the per-layer assignment cannot
            # address — claiming savings the runtime can't realize
            # would make the memory envelope optimistic; skip
            return
        from .search.zero_plan import audit_record, plan_zero_assignment
        cost_model = getattr(self, "_search_cost_model", None)
        if cost_model is None or cost_model.spec is not self.dmesh.spec:
            # non-searched paths (DP preset, --tp, pipeline presets):
            # a bare cost model over the machine spec, placement-aware
            # on multi-tier machines so the collectives price against
            # their real fabric tier (PR 9)
            from .search.costmodel import OpCostModel
            from .search.optimizer import _attach_placement
            cost_model = OpCostModel(self.dmesh.spec)
            _attach_placement(cfg, cost_model, self.dmesh)
        hbm = float(cfg.device_mem_mb) * (1 << 20) \
            if getattr(cfg, "device_mem_mb", 0) \
            else getattr(self.dmesh.spec, "hbm_bytes", None)
        assignment = plan_zero_assignment(
            self.strategy, self.executor.program.layers, self.dmesh,
            cost_model, self.optimizer, policy=policy,
            overhead_frac=getattr(cfg, "zero_overhead_frac", 0.05),
            hbm_bytes=hbm)
        self.strategy.zero = assignment
        if assignment is None:
            return
        record = audit_record(assignment)
        self._zero_record = record
        audit_path = getattr(self, "_strategy_audit_path", None)
        if audit_path:
            from .obs.audit import annotate_strategy_audit
            annotate_strategy_audit(audit_path, {"zero": record})
        if cfg.export_strategy_file:
            # the search exported before the assignment existed (same
            # ordering as banks): rewrite the zero section so --import
            # round-trips the per-parameter decision
            try:
                import json as _json
                with open(cfg.export_strategy_file) as f:
                    doc = _json.load(f)
                doc["zero"] = assignment.to_json()
                with open(cfg.export_strategy_file, "w") as f:
                    _json.dump(doc, f, indent=1)
            except Exception:  # noqa: BLE001 — export is best-effort
                pass
        if cfg.profiling:
            s = assignment.summary()
            print(f"zero plan ({policy}): {s['n_sharded']}/"
                  f"{s['n_params']} opt states sharded, "
                  f"{s['bytes_saved_total'] / 2**20:.2f} MiB/device "
                  f"saved, predicted overhead "
                  f"{s['overhead_s_total'] * 1e3:.3f} ms/step")

    def _plan_qsync(self):
        """Adopt a per-tensor, per-phase quantized grad-sync plan
        (``FFConfig.quantized_collectives``, ops/quantized_collectives.
        py). A plan already on the strategy (``--import`` round-trip)
        is honored verbatim; ``off`` (the default) leaves the implicit
        full-precision sync untouched — bit-exact."""
        cfg = self.config
        if self.strategy is None:
            return
        from .ops.quantized_collectives import (audit_record, plan_qsync,
                                                qsync_disabled,
                                                resolve_qsync_mode,
                                                resolve_qsync_wire)
        if getattr(self.strategy, "qsync", None) is not None:
            if qsync_disabled(cfg):
                # explicit disable (--no-quantized-collectives /
                # FF_QUANTIZED_COLLECTIVES=off) beats an imported
                # plan: the user asked for the full-precision path —
                # the A/B knob against an exported quantized strategy
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "stripping the imported strategy's quantized-"
                    "collectives plan (explicitly disabled)")
                self.strategy.qsync = None
            # else: imported with the strategy — honor it verbatim.
            # Either way the executor may predate the resolution, so
            # re-resolve the runtime schedule.
            self.executor.attach_qsync()
            return
        mode = resolve_qsync_mode(cfg)
        if mode == "off" or self.dmesh.num_devices <= 1:
            return
        wire = resolve_qsync_wire(cfg)
        cost_model = getattr(self, "_search_cost_model", None)
        if cost_model is None or cost_model.spec is not self.dmesh.spec:
            # non-searched paths (DP preset, --tp): a bare cost model,
            # placement-aware on multi-tier machines so DCN legs price
            # against their real fabric tier (PR 9)
            from .search.costmodel import OpCostModel
            from .search.optimizer import _attach_placement
            cost_model = OpCostModel(self.dmesh.spec)
            _attach_placement(cfg, cost_model, self.dmesh)
        cost_model.attach_quantization(mode, wire)
        plan = plan_qsync(self.strategy, self.executor.program.layers,
                          self.dmesh, cost_model, mode=mode, wire=wire)
        self.strategy.qsync = plan
        self.executor.attach_qsync()
        if plan is None:
            return
        if not getattr(self.strategy, "axis_tiers", None):
            # make the exported artifact self-describing: the plan's
            # per-phase tiers were derived from the mesh — record the
            # axis→tier map the verifier (and a later --import on a
            # different machine) checks the quantized legs against
            try:
                self.strategy.axis_tiers = dict(self.dmesh.axis_tiers)
            except Exception:  # noqa: BLE001 — tierless machine
                pass
        record = audit_record(plan)
        self._qsync_record = record
        audit_path = getattr(self, "_strategy_audit_path", None)
        if audit_path:
            from .obs.audit import annotate_strategy_audit
            annotate_strategy_audit(audit_path,
                                    {"quantized_sync": record})
        if cfg.export_strategy_file:
            # the search exported before the plan existed (same
            # ordering as banks/zero/overlap): rewrite the qsync
            # section so --import round-trips the decision
            try:
                import json as _json
                with open(cfg.export_strategy_file) as f:
                    doc = _json.load(f)
                doc["qsync"] = plan.to_json()
                with open(cfg.export_strategy_file, "w") as f:
                    _json.dump(doc, f, indent=1)
            except Exception:  # noqa: BLE001 — export is best-effort
                pass
        if cfg.profiling:
            s = plan.summary()
            print(f"qsync plan ({mode}, wire {wire}): "
                  f"{s['n_quantized']}/{s['n_params']} grad syncs "
                  f"quantized, predicted "
                  f"{s['baseline_s_total'] * 1e3:.3f} -> "
                  f"{s['quantized_s_total'] * 1e3:.3f} ms/step")

    def _plan_kernels(self):
        """Adopt per-op kernel implementations (kernels/registry.py):
        attention ``xla``/``flash``/``ring``, the optimizer update
        ``fused``/``unfused``. An assignment already on the strategy
        (``--import`` round-trip) is honored verbatim; forced choices
        (``--kernel-impl`` / ``FF_KERNEL_IMPL`` / the retired
        ``use_flash_attention`` shim) bypass scoring but are
        predicate-checked — forcing ``ring`` on a mesh without a
        sequence axis is a typed compile-time error attributed to the
        op. Searched deviation from the defaults requires measured
        calibration evidence (``FF_CALIBRATION_V2``): the analytic
        curves alone would flip CPU runs onto interpret-mode kernels
        the host executes orders of magnitude slower than its own XLA
        path."""
        cfg = self.config
        if self.strategy is None or self.executor is None:
            return
        strat = self.strategy
        if getattr(strat, "kernel_impls", None):
            # imported with the strategy: honor verbatim — the plan
            # verifier re-checks every predicate on this mesh/shapes
            self.executor._kernel_impls = dict(strat.kernel_impls)
            return
        policy = str(getattr(cfg, "kernel_impls", "auto") or "auto").lower()
        if policy in ("off", "none"):
            return
        from .kernels import registry as kreg
        forced = kreg.resolve_forced(cfg)
        if getattr(strat, "pipeline", None) is not None:
            # pipeline stages emit inside their own shard_map region —
            # the ring collective cannot nest there and the kernel ctx
            # is not threaded through stage emission; keep defaults
            if forced:
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "kernel impls are not planned under pipeline "
                    "parallelism; ignoring forced %s", dict(forced))
            return
        from .search.calibration import calibration_enabled
        if not forced and not calibration_enabled(cfg):
            # nothing to do: no forced choices and no measured evidence
            # to search on — the defaults stand, at zero compile cost
            return
        backend = jax.default_backend()
        seq_deg = int(getattr(self.dmesh, "seq_degree", 0) or 0)
        layers = self.executor.program.layers
        attn = [l for l in layers
                if l.op_type == OperatorType.OP_MULTIHEAD_ATTENTION]
        cost_model = getattr(self, "_search_cost_model", None)
        if cost_model is None or cost_model.spec is not self.dmesh.spec:
            # non-searched paths (DP preset, --seq-parallel without a
            # budget): a bare cost model, placement-aware, calibrated
            # when the opt-in is on — same construction as _plan_zero
            from .search.costmodel import OpCostModel
            from .search.optimizer import _attach_placement
            cost_model = OpCostModel(self.dmesh.spec)
            _attach_placement(cfg, cost_model, self.dmesh)
            from .search.calibration import (calibrate_mesh,
                                             calibration_enabled)
            if calibration_enabled(cfg) and not cfg.machine_model_file:
                try:
                    cost_model.attach_calibration(
                        calibrate_mesh(self.dmesh))
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        searchable = cost_model.calib is not None
        if searchable:
            try:
                # grow the impl-keyed rows (op_attention@<impl>); a warm
                # table makes this measurement-free
                from .search.calibration import calibrate_kernel_impls
                calibrate_kernel_impls(self.dmesh,
                                       cost_model.calib.table)
            except Exception:  # noqa: BLE001 — priced analytically
                pass
        tier = None
        if self.dmesh.seq_axis:
            tier = self.dmesh.axis_tiers.get(self.dmesh.seq_axis)

        def _degrees(name):
            """Adopted (output shard degrees, weight shard degree)."""
            os_ = strat.ops.get(name)
            sd: Dict[int, int] = {}
            wdeg = 1
            if os_ is None:
                return sd, wdeg
            spec0 = os_.outputs[0] if os_.outputs else None
            for i, ax in enumerate(spec0 or ()):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, (tuple, list)) else (ax,)
                d = 1
                for a in axes:
                    d *= int(self.dmesh.axis_sizes.get(a, 1))
                if d > 1:
                    sd[i] = d
            for wspec in (os_.weights or {}).values():
                d = 1
                for ax in wspec or ():
                    if ax is None:
                        continue
                    axes = ax if isinstance(ax, (tuple, list)) else (ax,)
                    for a in axes:
                        d *= int(self.dmesh.axis_sizes.get(a, 1))
                wdeg = max(wdeg, d)
            return sd, wdeg

        plan: Dict[str, str] = {}
        audit_ops: List[Dict] = []
        f_attn = forced.get(kreg.ATTENTION)
        for layer in attn:
            q_len = int(layer.inputs[0].shape[1]) if layer.inputs else 0
            kv_len = int(layer.inputs[1].shape[1]) \
                if len(layer.inputs) > 1 else q_len
            ctx = kreg.attention_ctx(layer.params, q_len, kv_len,
                                     backend=backend,
                                     seq_degree=seq_deg)
            if f_attn is not None:
                reason = kreg.get_impl(kreg.ATTENTION,
                                       f_attn).available(ctx)
                if reason is not None:
                    raise ValueError(
                        f"{layer.name}: forced kernel impl "
                        f"attention:{f_attn} is not available on this "
                        f"mesh/shapes: {reason}")
                choice = f_attn
            elif searchable:
                sd, wdeg = _degrees(layer.name)
                best_t, choice = None, kreg.DEFAULT_IMPLS[kreg.ATTENTION]
                for name in kreg.available_impls(kreg.ATTENTION, ctx):
                    cm = cost_model.kernel_impl_cost(
                        layer, kreg.ATTENTION, name, sd, wdeg,
                        seq_degree=seq_deg if name == "ring" else 0,
                        tier=tier)
                    t = cm.forward_time + cm.backward_time
                    if best_t is None or t < best_t:
                        best_t, choice = t, name
            else:
                continue
            sd, wdeg = _degrees(layer.name)
            cm_x = cost_model.kernel_impl_cost(
                layer, kreg.ATTENTION, "xla", sd, wdeg)
            cm_c = cost_model.kernel_impl_cost(
                layer, kreg.ATTENTION, choice, sd, wdeg,
                seq_degree=seq_deg if choice == "ring" else 0,
                tier=tier)
            t_x = cm_x.forward_time + cm_x.backward_time
            t_c = cm_c.forward_time + cm_c.backward_time
            audit_ops.append({
                "name": layer.name, "op": kreg.ATTENTION,
                "impl": choice, "forced": f_attn is not None,
                "predicted_s": round(t_c, 9),
                "forced_xla_s": round(t_x, 9),
                "delta_s": round(t_x - t_c, 9)})
            plan[layer.name] = choice

        # optimizer update: one graph-wide choice for the step's
        # parameter update (fused single-HBM-pass Pallas Adam vs the
        # tree-mapped jnp path)
        f_opt = forced.get(kreg.OPT_UPDATE)
        overlap_active = getattr(self.executor, "_overlap_schedule",
                                 None) is not None
        opt_kind = "adam" if isinstance(self.optimizer, AdamOptimizer) \
            else type(self.optimizer).__name__.lower()
        octx = {"backend": backend, "optimizer": opt_kind}
        param_bytes = 0.0
        for l in layers:
            for w in l.weights or ():
                n = 1
                for s in w.shape:
                    n *= int(s)
                param_bytes += float(n) * 4.0
        if f_opt is not None:
            if overlap_active and f_opt == "fused":
                raise ValueError(
                    "forced kernel impl opt_update:fused does not "
                    "compose with the overlapped update schedule "
                    "(--overlap); disable one of them")
            reason = kreg.get_impl(kreg.OPT_UPDATE, f_opt).available(octx)
            if reason is not None:
                raise ValueError(
                    f"forced kernel impl opt_update:{f_opt} is not "
                    f"available here: {reason}")
            o_choice = f_opt
        elif searchable and not overlap_active and param_bytes:
            best_t, o_choice = None, kreg.DEFAULT_IMPLS[kreg.OPT_UPDATE]
            for name in kreg.available_impls(kreg.OPT_UPDATE, octx):
                cm = cost_model.kernel_impl_cost(
                    None, kreg.OPT_UPDATE, name,
                    param_bytes=param_bytes)
                if best_t is None or cm.forward_time < best_t:
                    best_t, o_choice = cm.forward_time, name
        else:
            o_choice = None
        if o_choice is not None:
            cm_u = cost_model.kernel_impl_cost(
                None, kreg.OPT_UPDATE, "unfused",
                param_bytes=param_bytes)
            cm_c = cost_model.kernel_impl_cost(
                None, kreg.OPT_UPDATE, o_choice,
                param_bytes=param_bytes)
            audit_ops.append({
                "name": "__opt_update__", "op": kreg.OPT_UPDATE,
                "impl": o_choice, "forced": f_opt is not None,
                "predicted_s": round(cm_c.forward_time, 9),
                "forced_xla_s": round(cm_u.forward_time, 9),
                "delta_s": round(cm_u.forward_time
                                 - cm_c.forward_time, 9)})
            if o_choice != kreg.DEFAULT_IMPLS[kreg.OPT_UPDATE]:
                plan[kreg.OPT_UPDATE] = o_choice

        if not plan and not audit_ops:
            return
        strat.kernel_impls = plan
        # the executor snapshotted (the then-empty) strategy.kernel_impls
        # at construction — refresh so the jitted step traces the plan
        self.executor._kernel_impls = dict(plan)
        n_nondefault = sum(
            1 for e in audit_ops
            if e["impl"] != kreg.DEFAULT_IMPLS[e["op"]])
        record = {"policy": policy, "backend": backend,
                  "seq_degree": seq_deg,
                  "n_ops": len(audit_ops),
                  "n_nondefault": n_nondefault,
                  "measured": bool(searchable),
                  "ops": audit_ops}
        self._kernel_record = record
        audit_path = getattr(self, "_strategy_audit_path", None)
        if audit_path:
            from .obs.audit import annotate_strategy_audit
            annotate_strategy_audit(audit_path, {"kernels": record})
        if cfg.export_strategy_file:
            # the search exported before the assignment existed (same
            # ordering as banks/zero/overlap/qsync): rewrite the
            # kernel_impls section so --import round-trips it verbatim
            try:
                import json as _json
                with open(cfg.export_strategy_file) as f:
                    doc = _json.load(f)
                doc["kernel_impls"] = dict(plan)
                with open(cfg.export_strategy_file, "w") as f:
                    _json.dump(doc, f, indent=1)
            except Exception:  # noqa: BLE001 — export is best-effort
                pass
        if cfg.profiling:
            tot = sum(e["delta_s"] for e in audit_ops)
            print(f"kernel plan ({policy}): {n_nondefault}/"
                  f"{len(audit_ops)} ops off the default impl, "
                  f"predicted {tot * 1e3:+.3f} ms/step vs forced-xla")

    # ------------------------------------------------------------------
    def create_data_loader(self, tensor: Tensor, data: np.ndarray):
        """Reference ``FFModel.create_data_loader`` parity: registers the
        full array for one tensor; fit() shards batches from it."""
        data = np.ascontiguousarray(data)
        self._dataloaders.append((tensor, data))
        return (tensor, data)

    def _combined_loader(self, x=None, y=None,
                         batch_size: Optional[int] = None,
                         shuffle: bool = True) -> SingleDataLoader:
        bs = batch_size or self.config.batch_size
        arrays: Dict[str, np.ndarray] = {}
        graph_inputs = getattr(self, "graph_inputs", self.input_tensors)
        if x is not None or y is not None:
            xs = x if isinstance(x, (list, tuple)) else [x]
            if len(xs) != len(graph_inputs):
                raise ValueError(f"{len(xs)} arrays for "
                                 f"{len(graph_inputs)} inputs")
            for t, arr in zip(graph_inputs, xs):
                arrays[t.name] = np.ascontiguousarray(arr)
            arrays["label"] = np.ascontiguousarray(y)
        else:
            gi_guids = {t.guid for t in graph_inputs}
            for t, arr in self._dataloaders:
                is_label = (t is self.label_tensor
                            or t.guid not in gi_guids)
                arrays["label" if is_label else t.name] = arr
        shardings = {}
        for t in graph_inputs:
            if t.name in arrays:
                shardings[t.name] = self.strategy.input_sharding(t.name)
        out_sh = self.strategy.output_sharding(
            self._output_tensor.owner_layer.name)
        if out_sh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ospec = self.strategy.ops[self._output_tensor.owner_layer.name]\
                .outputs[self._output_tensor.owner_idx]
            batch_axes = ospec[0] if ospec and len(ospec) > 0 else None
            shardings["label"] = NamedSharding(self.dmesh.mesh, P(batch_axes))
        return SingleDataLoader(arrays, bs, shardings, shuffle=shuffle,
                                seed=self.config.seed,
                                prefetch=self.config.prefetch_batches)

    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: Optional[int] = None, callbacks=None, verbose=True):
        """Training loop (reference ``flexflow_cffi.py:2062-2104``; Legion
        trace ≙ jit cache).

        Async dispatch: per-step metrics stay device-resident in a
        :class:`MetricsBuffer` and are fetched in ONE ``device_get`` at
        ``print_freq``/epoch boundaries (the reference gets the same
        overlap from Legion's deferred futures); a bounded in-flight
        window (``config.async_dispatch_steps``) keeps the host from
        racing ahead. ``FF_SYNC_EVERY_STEP=1`` restores the old
        fetch-every-step loop for debugging."""
        if self.executor is None:
            raise ValueError("call compile() first")
        epochs = epochs or self.config.epochs
        loader = self._combined_loader(x, y, batch_size)
        history = []
        # the buffer stays attached through the epoch-end callbacks
        # (their checkpoint saves screen through it) and is detached
        # when fit ends — INCLUDING on exceptions, or a stale poisoned
        # buffer would block save_checkpoint of later clean params
        try:
            for epoch in range(epochs):
                # re-fetch per epoch: callbacks (e.g.
                # LearningRateScheduler) may invalidate the jitted step
                # to apply new hyperparams
                step_fn = self.executor.make_train_step()
                pm = PerfMetrics()
                buf = MetricsBuffer.for_config(self.config, pm=pm)
                self._metrics_buffer = buf
                t0 = time.perf_counter()
                nb = 0
                for batch in loader:
                    bm = self._run_train_step(step_fn, batch)
                    bsz = next(iter(batch.values())).shape[0]
                    buf.push(self._step - 1, bm, bsz)
                    nb += 1
                    # dynamic recompilation hook (reference model.cc:2422)
                    rs = getattr(self, "_recompile_state", None)
                    if rs is not None and rs.step(self):
                        step_fn = self.executor.make_train_step()
                    pf = self.config.print_freq
                    if pf > 0 and nb % pf == 0:
                        # flush REGARDLESS of verbosity: print_freq is
                        # the metric-fetch cadence, not just the print
                        # cadence (pending device scalars must not pile
                        # up for a whole quiet epoch)
                        buf.flush()
                        if verbose:
                            rep = pm.report()
                            msg = " ".join(f"{k}={v:.4f}"
                                           for k, v in rep.items())
                            print(f"epoch {epoch} iter "
                                  f"{nb}/{loader.num_batches} {msg}")
                buf.flush()
                dt = time.perf_counter() - t0
                rep = pm.report()
                rep["epoch_time_s"] = dt
                rep["samples_per_sec"] = pm.train_all / dt if dt > 0 \
                    else 0.0
                from .obs import events as obs_events
                from .obs.metrics_registry import REGISTRY
                obs_events.record_span("fit.epoch", t0, dt, epoch=epoch,
                                       batches=nb)
                REGISTRY.gauge(
                    "ff_train_samples_per_sec",
                    "Training throughput of the last completed epoch"
                ).set(rep["samples_per_sec"])
                history.append(rep)
                if verbose:
                    msg = " ".join(f"{k}={v:.4f}" for k, v in rep.items())
                    print(f"epoch {epoch} done: {msg}")
                if callbacks:
                    stop = False
                    for cb in callbacks:
                        cb.on_epoch_end(epoch, rep, self)
                        stop = stop or getattr(cb, "stop_requested",
                                               False)
                    if stop:
                        break
        finally:
            self._metrics_buffer = None
        self._current_metrics = history[-1] if history else {}
        if self.config.trace_export_file:
            from .obs import events as obs_events
            from .obs.trace_export import export_chrome_trace
            if obs_events.enabled():
                export_chrome_trace(self.config.trace_export_file)
        self._end_of_training_telemetry()
        return history

    def _end_of_training_telemetry(self) -> None:
        """End-of-training observability hooks shared by :meth:`fit`
        and the resilience Supervisor: the step-time attribution
        harness (``FF_ATTRIB`` — profiles the compiled plan once and
        writes the measured side + drift report next to the predicted
        audit breakdown) and the per-rank ring dump that
        ``tools/fftrace.py`` merges across a multi-process world. Both
        best-effort, both strictly after the last step — zero per-step
        cost."""
        from .obs import attribution as obs_attrib
        from .obs import events as obs_events
        if obs_attrib.attribution_enabled(self.config):
            try:
                obs_attrib.run_attribution(self)
            except Exception as e:  # noqa: BLE001 — never kill training
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "attribution failed: %r", e)
        if obs_events.enabled():
            import jax
            from .obs.events import _env_on
            if jax.process_count() > 1 \
                    or _env_on(os.environ.get("FF_TRACE_DUMP")):
                from .obs.trace_export import dump_rank_trace
                dump_rank_trace()

    def _run_train_step(self, step_fn, batch):
        # fault-injection sites (resilience/faults.py): crash/device-loss
        # clauses fire BEFORE the step runs, NaN/Inf gradient-corruption
        # clauses poison the state after; active() is one cached check,
        # so fault-free runs pay nothing measurable
        from .resilience import coord, faults
        coord.check()  # surface a detected peer-rank failure pre-step
        if faults.active():
            faults.raise_pending(self._step)
        self.params, self.opt_state, self.state, bm = step_fn(
            self.params, self.opt_state, self.state,
            jnp.int32(self._step), batch)
        if faults.active():
            bad = faults.poison_value(self._step)
            if bad is not None:
                poison = jnp.float32(bad)
                self.params = jax.tree.map(
                    lambda a: (a * poison).astype(a.dtype)
                    if jnp.issubdtype(a.dtype, jnp.inexact) else a,
                    self.params)
                # the in-jit all_finite flag saw the CLEAN loss; the
                # host-side poison must flip it or the deferred NaN
                # screen would wave the poisoned step through
                bm = dict(bm, loss=poison,
                          all_finite=jnp.logical_and(
                              bm.get("all_finite", True),
                              jnp.isfinite(poison)))
        self._step += 1
        return bm

    # phase-level API parity (forward/backward/update as in model.cc)
    def forward(self, batch=None, seq_length: int = -1):
        fwd = self.executor.make_forward()
        if batch is None:
            batch = self._peek_batch()
        self._last_fwd = fwd(self.params, self.state, batch)
        return self._last_fwd

    def generate(self, prompt_ids, prompt_len: "int | np.ndarray",
                 max_new_tokens: int, temperature: float = 0.0,
                 seed: int = 0, extra_inputs=None,
                 eos_token_id: int | None = None,
                 kv_cache: Union[bool, str] = "auto",
                 top_k: int = 0, top_p: float = 1.0):
        """Autoregressive generation for causal LMs (GPT-2 / LLaMA /
        transformer-LM family; the reference has no generation path —
        its Triton backend serves fixed forwards only).

        ``prompt_ids``: (batch, seq_len) int32, the prompt in columns
        [0, prompt_len) and anything (e.g. zeros) after. ``prompt_len``
        may be a (batch,) int array for RAGGED prompts — each row
        decodes from its own length (the batched-serving case). ``temperature``
        0 = greedy argmax, > 0 = sampling from the pre-softmax logits
        (numerically exact — no re-log of already-softmaxed probs).
        ``eos_token_id``: rows that emit it keep emitting it for the
        remaining steps (the scan length stays static — standard jit
        practice). Returns the completed (batch, seq_len) ids.

        ``kv_cache``: "auto" (default) decodes incrementally against a
        per-layer K/V cache — one prefill forward then one O(1)-length
        forward per token — when the graph supports it (causal
        multihead-attention layers, no pipeline region, inputs limited
        to input_ids/position_ids), silently falling back to the exact
        full-re-forward path otherwise. True forces the KV path (raises
        when unsupported), False forces the re-forward oracle."""
        if self.executor is None:
            raise ValueError("call compile() first")
        ids0 = jnp.asarray(prompt_ids, jnp.int32)
        b, L = ids0.shape
        if np.ndim(prompt_len) > 0:
            # ragged prompts: one length per batch row
            prompt_len = np.asarray(prompt_len, np.int32)
            if prompt_len.shape != (b,):
                raise ValueError(
                    f"ragged prompt_len must have shape ({b},), got "
                    f"{prompt_len.shape}")
            if not ((prompt_len >= 1).all()
                    and (prompt_len + max_new_tokens <= L).all()):
                raise ValueError(
                    f"each prompt_len must satisfy 1 <= len and "
                    f"len + max_new_tokens <= {L}; got {prompt_len} "
                    f"with max_new_tokens={max_new_tokens}")
        else:
            if prompt_len < 1:
                raise ValueError(
                    "prompt_len must be >= 1 (the first token "
                    "conditions decode)")
            if prompt_len + max_new_tokens > L:
                raise ValueError(
                    f"prompt_len {prompt_len} + max_new_tokens "
                    f"{max_new_tokens} exceeds the sequence length {L}")
        names = {t.name for t in self.graph_inputs}
        fixed = {k: jnp.asarray(v)
                 for k, v in (extra_inputs or {}).items()}
        if "position_ids" in names and "position_ids" not in fixed:
            fixed["position_ids"] = jnp.tile(
                jnp.arange(L, dtype=jnp.int32)[None], (b, 1))

        # failed KV attempts are remembered per (batch, seq) shape — the
        # unit of trace/compile — so repeated auto-mode requests at a
        # failing shape don't re-pay the attempt, while other shapes
        # (e.g. shorter prompts that fit) still get the KV path
        kv_failed_shapes = getattr(self.executor, "_kv_failed_shapes",
                                   None)
        if kv_failed_shapes is None:
            kv_failed_shapes = self.executor._kv_failed_shapes = set()
        want_kv = kv_cache if isinstance(kv_cache, bool) \
            else (self._kv_decode_eligible(names, extra_inputs)
                  and (b, L) not in kv_failed_shapes)
        if want_kv:
            try:
                return self._generate_kv(ids0, prompt_len, max_new_tokens,
                                         temperature, seed, eos_token_id,
                                         top_k, top_p)
            except Exception:
                if kv_cache is True:
                    raise
                kv_failed_shapes.add((b, L))
                # the fallback is exact but O(L)-per-token — a serving
                # deployment quietly riding it is a perf regression, so
                # it is observable (Prometheus + /healthz), not just a
                # warn-once log line
                self._kv_fallback_count = getattr(
                    self, "_kv_fallback_count", 0) + 1
                from .obs.metrics_registry import REGISTRY
                REGISTRY.counter(
                    "ff_kv_fallback_total",
                    "KV-cache decode attempts that fell back to the "
                    "full re-forward path").inc(
                        model=getattr(self, "_model_name", "")
                        or "<unnamed>")
                import logging
                logging.getLogger("flexflow_tpu").warning(
                    "KV-cache decode failed for this graph at shape "
                    "(%d, %d); falling back to full re-forward "
                    "generation (cached: subsequent auto-mode calls at "
                    "this shape skip the KV attempt)", b, L,
                    exc_info=True)
        return self._generate_reforward(ids0, prompt_len, max_new_tokens,
                                        temperature, seed, eos_token_id,
                                        fixed, top_k, top_p)

    def _kv_decode_eligible(self, names, extra_inputs) -> bool:
        """KV decode needs: no pipeline region, inputs limited to
        input_ids(+position_ids), and every attention layer a causal
        OP_MULTIHEAD_ATTENTION (primitive-built attention, e.g. LLaMA's
        explicit-mask batch_matmul form, carries baked seq-length
        constants that a length-1 trace cannot satisfy)."""
        if self.executor.pipe is not None or extra_inputs:
            return False
        if not names <= {"input_ids", "position_ids"}:
            return False
        mha = [l for l in self.executor.program.layers
               if l.op_type == OperatorType.OP_MULTIHEAD_ATTENTION]
        return bool(mha) and all(l.params.get("causal", False)
                                 for l in mha)

    def _generate_kv(self, ids0, prompt_len, max_new_tokens, temperature,
                     seed, eos_token_id, top_k=0, top_p=1.0):
        """Incremental decode: one full-sequence prefill builds the
        per-layer K/V cache, then each generated token is one seq-len-1
        forward — per-token cost independent of how many tokens have
        been generated (the re-forward path is O(L) per token).

        Prefill and decode are SEPARATE jitted programs so serving can
        observe the two phases the serving objective is built from: the
        prefill span is the prompt cost, the decode span divided by
        ``max_new_tokens`` is the per-token decode-step latency the
        serving search (search/serving_plan.py) ranks plans by — and
        what ``ff_decode_step_seconds{bucket=...}`` reports. The split
        also lets one prefill program serve every sampling config at a
        shape (the old fused program re-traced per temperature/top-k)."""
        ex = self.executor
        b, L = ids0.shape
        has_pos = "position_ids" in {t.name for t in self.graph_inputs}
        ragged = np.ndim(prompt_len) > 0

        def prefill(params, state, ids0, plen):
            batch = {"input_ids": ids0}
            if has_pos:
                batch["position_ids"] = jnp.tile(
                    jnp.arange(L, dtype=jnp.int32)[None], (b, 1))
            # ragged prompts keep the full cache (the ring-buffer seed
            # needs one shared prompt length); masks stay per-row exact
            _, cache = ex.kv_prefill(params, state, batch,
                                     prefill_len=None if ragged else plen)
            return cache

        def decode(params, state, ids0, cache, key0, plen):
            done0 = jnp.zeros((b,), jnp.bool_)

            def step(carry, i):
                ids, cache, key, done = carry
                cur = plen + i         # index being generated; (B,) when
                tok = self._read_token_row(ids, cur, ragged)
                if ragged:             # prompts are ragged
                    pos_in = (cur - 1)[:, None].astype(jnp.int32)
                else:
                    pos_in = jnp.full((b, 1), cur - 1, dtype=jnp.int32)
                sb = {"input_ids": tok}
                if has_pos:
                    sb["position_ids"] = pos_in
                row, cache = ex.kv_decode_step(params, state, sb, cache,
                                               cur - 1)
                key, nxt, done = self._sample_next(row, key, temperature,
                                                   eos_token_id, done,
                                                   top_k, top_p)
                ids = self._write_token(ids, nxt, cur, ragged)
                return (ids, cache, key, done), nxt

            (ids, _, _, _), _ = jax.lax.scan(
                step, (ids0, cache, key0, done0),
                jnp.arange(max_new_tokens))
            return ids

        pk = ("kv_prefill", b, L, ragged)
        dk = ("kv_decode", b, L, max_new_tokens, float(temperature),
              eos_token_id, int(top_k), float(top_p), ragged)
        prefill_fn = self._decode_cache_get(pk, prefill)
        decode_fn = self._decode_cache_get(dk, decode)
        plen = jnp.asarray(prompt_len, jnp.int32)
        from .obs import events as obs_events
        from .obs import request_trace
        from .obs.metrics_registry import DECODE_STEP_BUCKETS, REGISTRY
        t0 = time.perf_counter()
        cache = jax.block_until_ready(
            prefill_fn(self.params, self.state, ids0, plen))
        t1 = time.perf_counter()
        out = jax.block_until_ready(
            decode_fn(self.params, self.state, ids0, cache,
                      jax.random.key(seed), plen))
        t2 = time.perf_counter()
        step_s = (t2 - t1) / max(int(max_new_tokens), 1)
        # tag the phase spans with the ambient request trace (set by the
        # serving front) so a request's prefill/decode link into its
        # lifecycle; None outside a traced request — dropped by attrs
        tid = request_trace.current_id()
        span_attrs = {"trace": tid} if tid else {}
        obs_events.record_span("generate.prefill", t0, t1 - t0,
                               batch=b, seq=L, **span_attrs)
        obs_events.record_span("generate.decode", t1, t2 - t1,
                               batch=b, tokens=int(max_new_tokens),
                               **span_attrs)
        REGISTRY.histogram(
            "ff_decode_step_seconds",
            "Per-token decode-step latency by batch bucket",
            buckets=DECODE_STEP_BUCKETS).observe(step_s, bucket=str(b))
        REGISTRY.histogram(
            "ff_prefill_seconds",
            "Prompt prefill latency by batch bucket",
            buckets=DECODE_STEP_BUCKETS).observe(t1 - t0, bucket=str(b))
        # always-on measured sink for serving drift detection: the MIN
        # observed prefill/decode-step per batch size (min = closest to
        # the cost model's contention-free prediction; bounded — one
        # small dict entry per batch size ever decoded). Unlocked
        # update: worst case a concurrent generate at the same batch
        # size loses one sample, and serving sessions serialize decode
        # per instance anyway  # ffcheck: ok(guarded-field)
        rec = getattr(self, "_decode_measured", None)
        if rec is None:
            rec = self._decode_measured = {}
        old = rec.get(b)
        rec[b] = {
            "prefill_s": (t1 - t0) if old is None
            else min(old["prefill_s"], t1 - t0),
            "decode_step_s": step_s if old is None
            else min(old["decode_step_s"], step_s),
            "n": 1 if old is None else old["n"] + 1,
        }
        return out

    def generate_beam(self, prompt_ids, prompt_len: int,
                      max_new_tokens: int, num_beams: int = 4,
                      eos_token_id: int | None = None):
        """Beam-search decoding over the KV cache (deterministic; no
        length penalty — scores are summed token log-probs). Requires a
        KV-decode-eligible graph (see ``_kv_decode_eligible``); beams
        live on the batch dim (b*K rows), the cache is gathered by beam
        index each step. Returns the best (batch, seq_len) ids.

        Beyond-reference: the reference has no generation path at all;
        beam completes the greedy/temperature/top-k/top-p family."""
        if self.executor is None:
            raise ValueError("call compile() first")
        ids0 = jnp.asarray(prompt_ids, jnp.int32)
        b, L = ids0.shape
        K = int(num_beams)
        if K < 1:
            raise ValueError(f"num_beams must be >= 1, got {K}")
        if np.ndim(prompt_len) > 0:
            raise ValueError("generate_beam needs one scalar prompt_len "
                             "(per-row prompt lengths are unsupported "
                             "for beam search)")
        if prompt_len < 1:
            raise ValueError("prompt_len must be >= 1")
        if prompt_len + max_new_tokens > L:
            raise ValueError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens} exceeds the sequence length {L}")
        names = {t.name for t in self.graph_inputs}
        if not self._kv_decode_eligible(names, None):
            raise ValueError("generate_beam requires a KV-decode-"
                             "eligible graph (causal fused attention)")
        ex = self.executor
        has_pos = "position_ids" in names
        NEG = jnp.float32(-1e30)

        def decode(params, state, ids0, plen):
            batch = {"input_ids": ids0}
            if has_pos:
                batch["position_ids"] = jnp.tile(
                    jnp.arange(L, dtype=jnp.int32)[None], (b, 1))
            _, cache = ex.kv_prefill(params, state, batch,
                                     prefill_len=plen)
            # beams on the batch dim: row r's beams are rows r*K..r*K+K-1
            ids = jnp.repeat(ids0, K, axis=0)              # (b*K, L)
            cache = jax.tree.map(lambda a: jnp.repeat(a, K, axis=0),
                                 cache)
            # all beams start identical: only beam 0 is live, so the
            # first step picks the row's top-K distinct tokens
            scores0 = jnp.tile(jnp.where(jnp.arange(K) == 0, 0.0, NEG),
                               (b,))                       # (b*K,)
            done0 = jnp.zeros((b * K,), jnp.bool_)

            def step(carry, i):
                ids, cache, scores, done = carry
                cur = plen + i
                tok = jax.lax.dynamic_slice_in_dim(ids, cur - 1, 1,
                                                   axis=1)
                sb = {"input_ids": tok}
                if has_pos:
                    sb["position_ids"] = jnp.full((b * K, 1), cur - 1,
                                                  dtype=jnp.int32)
                row, cache = ex.kv_decode_step(params, state, sb, cache,
                                               cur - 1)       # (b*K, V)
                V = row.shape[-1]
                logp = jax.nn.log_softmax(row.astype(jnp.float32),
                                          axis=-1)
                if eos_token_id is not None:
                    # a finished beam persists unchanged: only its eos
                    # continuation is allowed, at zero added cost
                    eos_only = jnp.where(
                        jnp.arange(V)[None, :] == eos_token_id, 0.0, NEG)
                    logp = jnp.where(done[:, None], eos_only, logp)
                total = scores[:, None] + logp             # (b*K, V)
                flat = total.reshape(b, K * V)
                top_s, top_i = jax.lax.top_k(flat, K)      # (b, K)
                beam = top_i // V                          # source beam
                token = (top_i % V).astype(jnp.int32)
                src = (jnp.arange(b)[:, None] * K + beam).reshape(-1)
                ids = jnp.take(ids, src, axis=0)
                cache = jax.tree.map(
                    lambda a: jnp.take(a, src, axis=0), cache)
                done = jnp.take(done, src, axis=0)
                scores = top_s.reshape(-1)
                token = token.reshape(-1)
                if eos_token_id is not None:
                    token = jnp.where(done, jnp.int32(eos_token_id),
                                      token)
                    done = jnp.logical_or(done,
                                          token == eos_token_id)
                ids = jax.lax.dynamic_update_slice_in_dim(
                    ids, token[:, None], cur, axis=1)
                return (ids, cache, scores, done), None

            (ids, _, scores, _), _ = jax.lax.scan(
                step, (ids, cache, scores0, done0),
                jnp.arange(max_new_tokens))
            best = jnp.argmax(scores.reshape(b, K), axis=-1)   # (b,)
            return ids.reshape(b, K, L)[jnp.arange(b), best]

        ck = ("beam", b, L, max_new_tokens, K, eos_token_id)
        fn = self._decode_cache_get(ck, decode)
        return fn(self.params, self.state, ids0, jnp.int32(prompt_len))

    # decode executables are cached per (shape, steps, sampling params);
    # arbitrary client-supplied floats (temperature/top_p) would grow the
    # cache without bound on a long-running server — LRU-capped
    _DECODE_CACHE_CAP = 16

    def _decode_cache_get(self, ck, builder):
        import collections
        cache = self.executor.__dict__.setdefault(
            "_decode_cache", collections.OrderedDict())
        fn = cache.get(ck)
        if fn is None:
            fn = cache[ck] = jax.jit(builder)
            # a fresh decode program is a recompile event too — same
            # per-model counter as FFModel.compile so the warm-start
            # signal covers the generate paths
            from .obs.metrics_registry import REGISTRY
            REGISTRY.counter(
                "ff_model_compiles_total",
                "Model program compiles (trace + XLA build events)").inc(
                model=getattr(self, "_model_name", "") or "<unnamed>")
        else:
            cache.move_to_end(ck)
        while len(cache) > self._DECODE_CACHE_CAP:
            cache.popitem(last=False)
        return fn

    @staticmethod
    def _read_token_row(arr, cur, ragged):
        """Row at position cur-1 per batch row: (B, ...) gather that
        works for scalar cur (shared position) and (B,) cur (ragged)."""
        if ragged:
            if arr.ndim == 2:      # ids (B, L)
                return jnp.take_along_axis(arr, (cur - 1)[:, None],
                                           axis=1)
            gidx = jnp.broadcast_to((cur - 1)[:, None, None],
                                    (arr.shape[0], 1, arr.shape[-1]))
            return jnp.take_along_axis(arr, gidx, axis=1)
        return jax.lax.dynamic_slice_in_dim(arr, cur - 1, 1, axis=1)

    @staticmethod
    def _write_token(ids, nxt, cur, ragged):
        """Write nxt at column cur (per-row when ragged)."""
        if ragged:
            sel = jnp.arange(ids.shape[1])[None, :] == cur[:, None]
            return jnp.where(sel, nxt[:, None], ids)
        return jax.lax.dynamic_update_slice_in_dim(ids, nxt[:, None],
                                                   cur, axis=1)

    def _sample_next(self, row, key, temperature, eos_token_id, done,
                     top_k: int = 0, top_p: float = 1.0):
        """Shared sampling step: ``row`` is (B, V) log-domain scores
        (pre-softmax logits when the graph exposes them). HF processor
        order: temperature, then top-k, then top-p (nucleus)."""
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            logits = row / temperature
            use_k = top_k and 0 < top_k < logits.shape[-1]
            if use_k or top_p < 1.0:
                # ONE descending vocab sort serves both filters: the kth
                # value is desc[:, k-1], and masking to -inf preserves
                # the survivors' descending order for the nucleus scan
                desc = jnp.sort(logits, axis=-1)[:, ::-1]
                if use_k:
                    kth = desc[:, top_k - 1][:, None]
                    logits = jnp.where(logits < kth, -jnp.inf, logits)
                    desc = jnp.where(
                        jnp.arange(desc.shape[-1])[None, :] >= top_k,
                        -jnp.inf, desc)
                if top_p < 1.0:
                    # nucleus: keep the smallest prefix of descending-
                    # prob tokens whose cumulative probability reaches p
                    probs = jax.nn.softmax(desc, axis=-1)
                    cum = jnp.cumsum(probs, axis=-1)
                    excluded = cum - probs > top_p  # prefix >= p before
                    kept = jnp.where(excluded, jnp.inf, desc)
                    thresh = jnp.min(kept, axis=-1, keepdims=True)
                    logits = jnp.where(logits < thresh, -jnp.inf, logits)
            nxt = jax.random.categorical(sub, logits, axis=-1)
        else:
            nxt = jnp.argmax(row, axis=-1)
        nxt = nxt.astype(jnp.int32)
        if eos_token_id is not None:
            eos = jnp.int32(eos_token_id)
            nxt = jnp.where(done, eos, nxt)
            done = jnp.logical_or(done, nxt == eos)
        return key, nxt, done

    def _generate_reforward(self, ids0, prompt_len, max_new_tokens,
                            temperature, seed, eos_token_id, fixed,
                            top_k=0, top_p=1.0):
        """Exact oracle path: full forward per step; the causal mask
        guarantees positions < t ignore columns >= t."""
        ex = self.executor
        b, L = ids0.shape
        ragged = np.ndim(prompt_len) > 0

        def decode(params, state, ids0, key0, fixed, plen):
            done0 = jnp.zeros((b,), jnp.bool_)

            def step(carry, i):
                ids, key, done = carry
                scores = ex.scored_forward(params, state,
                                           {"input_ids": ids, **fixed})
                cur = plen + i                # index being generated
                row = self._read_token_row(scores, cur, ragged)[:, 0, :]
                key, nxt, done = self._sample_next(row, key, temperature,
                                                   eos_token_id, done,
                                                   top_k, top_p)
                ids = self._write_token(ids, nxt, cur, ragged)
                return (ids, key, done), nxt

            (ids, _, _), _ = jax.lax.scan(
                step, (ids0, key0, done0), jnp.arange(max_new_tokens))
            return ids

        # jit cached per (shape, steps, temperature, eos, sampling,
        # fixed-input set); prompt_len is a TRACED argument so serving
        # traffic with varying prompt lengths reuses one compiled
        # program per shape
        ck = ("fwd", b, L, max_new_tokens, float(temperature),
              eos_token_id, int(top_k), float(top_p), ragged,
              tuple(sorted(fixed)))
        fn = self._decode_cache_get(ck, decode)
        return fn(self.params, self.state, ids0, jax.random.key(seed),
                  fixed, jnp.asarray(prompt_len, jnp.int32))

    def zero_gradients(self):
        pass  # grads are recomputed functionally each step

    def backward(self, seq_length: int = -1):
        pass  # fused into train step (jax.grad)

    def update(self):
        pass  # fused into train step

    def _peek_batch(self):
        loader = self._combined_loader()
        loader.reset()
        return loader.next_batch()

    def eval(self, x=None, y=None, batch_size: Optional[int] = None,
             verbose: bool = False) -> Dict[str, float]:
        loader = self._combined_loader(x, y, batch_size, shuffle=False)
        step_fn = self.executor.make_eval_step()
        pm = PerfMetrics()
        for batch in loader:
            _, bm = step_fn(self.params, self.state, batch)
            bsz = next(iter(batch.values())).shape[0]
            pm.update({k: np.asarray(v) for k, v in bm.items()}, bsz)
        rep = pm.report()
        self._current_metrics = rep
        if verbose:
            print("eval:", rep)
        return rep

    # ------------------------------------------------------------------
    def get_layer_by_name(self, name: str) -> Optional[Layer]:
        for l in self.layers:
            if l.name == name:
                return l
        return None

    def get_layers(self) -> Dict[int, Layer]:
        return dict(enumerate(self.layers))

    def get_perf_metrics(self):
        return self._current_metrics

    # ------------------------------------------------------------------
    # checkpoint / resume (beyond-reference: the reference has no built-in
    # checkpointing, SURVEY.md §5)
    def save_checkpoint(self, directory: str, step: Optional[int] = None,
                        max_to_keep: int = 3):
        from .runtime.checkpoint import save_model_checkpoint
        buf = self._metrics_buffer
        if buf is not None:
            # deferred NaN screen ALWAYS runs before a checkpoint save:
            # pending steps are flushed and a non-finite one raises here
            # — a poisoned state must never reach a checkpoint
            buf.flush()
            buf.raise_if_poisoned()
        return save_model_checkpoint(self, directory, step, max_to_keep)

    def restore_checkpoint(self, directory: str,
                           step: Optional[int] = None) -> int:
        from .runtime.checkpoint import restore_model_checkpoint
        return restore_model_checkpoint(self, directory, step)

    # dynamic recompilation (reference recompile_on_condition, model.cc:2422)
    def recompile_on_condition(self, trigger, alter) -> "object":
        from .runtime.recompile import RecompileState
        rs = RecompileState(trigger, alter, ff=self)
        self._recompile_state = rs
        return rs

    # weights access (reference Parameter.get/set_weights NumPy round-trip)
    def get_weights(self, layer_name: str, weight_name: str = "kernel"
                    ) -> np.ndarray:
        return np.asarray(self.params[layer_name][weight_name])

    def set_weights(self, layer_name: str, weight_name: str,
                    value: np.ndarray):
        cur = self.params[layer_name][weight_name]
        if cur.shape != value.shape:
            raise ValueError(f"weight {layer_name}/{weight_name} has "
                             f"shape {cur.shape}, got {value.shape}")
        self.params[layer_name][weight_name] = jax.device_put(
            jnp.asarray(value, cur.dtype), cur.sharding)

    def set_state(self, layer_name: str, key: str, value: np.ndarray):
        """Overwrite one non-trainable state entry (e.g. batch-norm
        running mean/var imported from a trained torch model)."""
        cur = self.state[layer_name][key]
        if cur.shape != tuple(value.shape):
            raise ValueError(f"state {layer_name}/{key} has shape "
                             f"{cur.shape}, got {tuple(value.shape)}")
        self.state[layer_name][key] = jax.device_put(
            jnp.asarray(value, cur.dtype), cur.sharding)

    @property
    def label_tensor_for_loaders(self) -> Tensor:
        if self.label_tensor is None:
            out = self._output_tensor or self.layers[-1].outputs[0]
            self.label_tensor = Tensor(out.shape, DataType.DT_INT32,
                                       name="label")
        return self.label_tensor
