"""AST-based framework-invariant linter (the ``ffcheck`` lint engine).

PRs 4–7 accumulated hard invariants that nothing enforced until now;
each is a rule here, checked statically over the package source:

  ``host-sync``
      No implicit host synchronization in the async-dispatch hot path:
      ``float()`` / ``bool()`` on values, ``np.asarray`` / ``np.array``,
      ``.item()``, and ``jax.device_get`` inside ``executor.py`` or the
      per-step ``runtime/`` modules (:data:`HOST_SYNC_MODULES`) outside
      designated flush points (:data:`FLUSH_FUNCS`). One stray
      conversion re-serializes the dispatch window PR 4 opened.
  ``bare-assert``
      No ``assert`` in runtime-reachable modules: ``python -O`` strips
      asserts, so input/precondition checks must be typed errors
      (``ValueError``/``RuntimeError``) — the repo-wide extension of
      PR 5's ``session.infer`` fix.
  ``raw-wait``
      No unbounded thread/queue waits in serving/resilience/checkpoint
      threads (:data:`WAIT_MODULES`): ``.join()`` / ``.wait()`` /
      ``.get()`` with no timeout can wedge a drain, a supervisor, or an
      exit path forever. Every wait passes a bound.
  ``raw-rank-wait``
      No raw cross-rank waits outside ``resilience/coord.py``: the jax
      distributed client's ``wait_at_barrier`` /
      ``blocking_key_value_get`` hang forever when a peer dies —
      ``coord.Coordinator`` wraps them with heartbeat-attributed
      timeouts (PR 7), and every call site must route through it.
  ``time-in-jit``
      No wall-clock reads (``time.time()`` etc.) inside functions that
      are ``jax.jit``-ed: the call executes once at trace time and
      bakes a constant into the executable.

Suppression: a trailing (or immediately preceding) comment
``# ffcheck: ok(<rule>)`` — comma-separate several rules, or bare
``# ffcheck: ok`` for all — silences a line, visibly and greppably.

Reporters: :func:`render_text` / :func:`render_json`. The CLI front end
is ``tools/ffcheck.py``; ``ci.sh``'s fast tier runs it as a hard gate.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["RULES", "LintFinding", "lint_file", "lint_paths",
           "render_text", "render_json"]

RULES: Dict[str, str] = {
    "host-sync": "implicit host synchronization in a hot path",
    "bare-assert": "bare assert in runtime-reachable code (-O strips it)",
    "raw-wait": "unbounded thread/queue wait",
    "raw-rank-wait": "cross-rank wait not routed through coord.py",
    "time-in-jit": "wall-clock read inside a jitted function",
    # always reported (never filtered by --rules): a file that does not
    # parse cannot be checked for ANY rule
    "parse-error": "file does not parse",
}

#: hot-path modules for ``host-sync`` — the files on the per-step
#: dispatch path. ``runtime/checkpoint.py`` is deliberately absent:
#: checkpoint saves are flush points by design (PR 4 flushes + screens
#: the metrics buffer before every save).
HOST_SYNC_MODULES: Tuple[str, ...] = (
    "executor.py", "runtime/metrics_buffer.py", "runtime/dataloader.py",
    "runtime/metrics.py", "runtime/optimizers.py", "runtime/losses.py",
    "runtime/zero.py",
)

#: function names that ARE flush points: conversions inside them happen
#: on already-fetched host values (or are the one designated fetch).
#: NOTE: deliberately NOT "update" — Optimizer.update in
#: runtime/optimizers.py is the hottest jitted code the rule scopes;
#: PerfMetrics.update (the flush-side fold) is exempted per-module below
FLUSH_FUNCS: Set[str] = {"flush", "report", "state_dict",
                         "load_state_dict", "summary", "snapshot"}

#: per-module additions to FLUSH_FUNCS (matched by path suffix)
MODULE_FLUSH_FUNCS: Dict[str, Set[str]] = {
    # PerfMetrics.update folds ALREADY-FETCHED host values (called from
    # MetricsBuffer.flush) — a flush point by design
    "runtime/metrics.py": {"update"},
}

#: calls whose result is host data by construction — float()/bool() of
#: these never syncs the device (config reads, sizes, clocks)
_SAFE_CALL_NAMES = {"getattr", "len", "min", "max", "round", "abs",
                    "int", "float", "str", "repr", "sum"}
_SAFE_CALL_CHAINS = ("os.environ", "time.", "math.")

#: modules whose threads must never wait unbounded (``raw-wait``)
WAIT_MODULES: Tuple[str, ...] = ("/serving/", "/resilience/",
                                 "runtime/checkpoint.py")

#: keyword names that count as a bound on a wait call
_TIMEOUT_KWARGS = {"timeout", "timeout_s", "timeout_ms", "deadline_s",
                   "deadline"}

#: the jax distributed client's raw blocking primitives (``raw-rank-wait``)
_RANK_WAIT_ATTRS = {"wait_at_barrier", "blocking_key_value_get"}

#: wall-clock reads that must not appear inside jitted fns
_CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time"}

_PRAGMA_RE = re.compile(r"#\s*ffcheck:\s*ok(?:\(([^)]*)\))?")


#: version of the machine-readable finding document emitted by
#: :func:`render_json` (and mirrored at the ffcheck CLI top level).
#: Schema 2 (ISSUE 14): adds ``schema``, per-finding ``id`` (stable
#: across runs — rule + repo-relative path + owning symbol, NOT line
#: numbers, so CI output stays diffable as code shifts) and ``symbol``.
JSON_SCHEMA_VERSION = 2


@dataclasses.dataclass
class LintFinding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    #: owning symbol ("Class.method" / function name) — set by the
    #: concurrency/spmd engines; the line-based linter leaves it empty
    symbol: str = ""

    def format(self) -> str:
        sym = f" ({self.symbol})" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}{sym}")

    def stable_id(self, seq: int = 0) -> str:
        """Stable per-finding ID: hash of (rule, repo-stable path,
        symbol). ``seq`` disambiguates multiple findings of one rule on
        one symbol (ordinal in report order — stable for a fixed
        repo)."""
        from ._modgraph import stable_path
        digest = hashlib.sha1(
            f"{self.rule}|{stable_path(self.path)}|{self.symbol}"
            .encode()).hexdigest()[:12]
        return digest if seq == 0 else f"{digest}-{seq}"

    def to_json(self, seq: int = 0) -> Dict[str, object]:
        doc = dataclasses.asdict(self)
        doc["id"] = self.stable_id(seq)
        return doc


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def _pragmas(source: str) -> Dict[int, Optional[Set[str]]]:
    """line number -> suppressed rule set (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        if m.group(1) is None or not m.group(1).strip():
            out[i] = None
        else:
            out[i] = {r.strip() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def _suppressed(pragmas, rule: str, line: int) -> bool:
    for ln in (line, line - 1):
        rules = pragmas.get(ln, "missing")
        if rules != "missing" and (rules is None or rule in rules):
            return True
    return False


# ---------------------------------------------------------------------------
# per-rule AST checks
# ---------------------------------------------------------------------------

def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _Scope(ast.NodeVisitor):
    """Shared walk that tracks the enclosing function-name stack."""

    def __init__(self):
        self.func_stack: List[str] = []

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


class _HostSyncVisitor(_Scope):
    def __init__(self, add, flush_funcs: Set[str]):
        super().__init__()
        self.add = add
        self.flush_funcs = flush_funcs

    def _in_flush(self) -> bool:
        return any(f in self.flush_funcs for f in self.func_stack)

    @staticmethod
    def _host_safe_arg(arg: ast.AST) -> bool:
        """Arguments that cannot hold a device value: literals, and
        calls to host-only producers (getattr/len/os.environ/...)."""
        if isinstance(arg, ast.Constant):
            return True
        if isinstance(arg, ast.Call):
            fn = arg.func
            if isinstance(fn, ast.Name):
                return fn.id in _SAFE_CALL_NAMES
            if isinstance(fn, ast.Attribute):
                chain = _attr_chain(fn)
                return any(chain.startswith(c)
                           for c in _SAFE_CALL_CHAINS)
        return False

    def visit_Call(self, node: ast.Call):
        if not self._in_flush():
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in ("float", "bool") \
                    and len(node.args) == 1 and not node.keywords \
                    and not self._host_safe_arg(node.args[0]):
                self.add(node, f"{fn.id}() on a value in a hot path "
                               f"forces a device sync; keep metrics "
                               f"device-resident and convert at a flush "
                               f"point (runtime/metrics_buffer.py)")
            elif isinstance(fn, ast.Attribute):
                chain = _attr_chain(fn)
                if chain in ("np.asarray", "np.array", "numpy.asarray",
                             "numpy.array"):
                    self.add(node, f"{chain}() on a traced/device value "
                                   f"in a hot path is an implicit host "
                                   f"sync; use jnp, or fetch at a flush "
                                   f"point")
                elif chain.endswith("jax.device_get") \
                        or chain == "jax.device_get":
                    self.add(node, "jax.device_get outside a flush "
                                   "point re-serializes the dispatch "
                                   "window")
                elif fn.attr == "item" and not node.args \
                        and not node.keywords:
                    self.add(node, ".item() is an implicit host sync; "
                                   "fetch at a flush point instead")
        self.generic_visit(node)


class _AssertVisitor(ast.NodeVisitor):
    def __init__(self, add):
        self.add = add

    def visit_Assert(self, node: ast.Assert):
        self.add(node, "bare assert is stripped under python -O; raise "
                       "a typed ValueError/RuntimeError instead")
        self.generic_visit(node)


class _WaitVisitor(ast.NodeVisitor):
    def __init__(self, add):
        self.add = add

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            kw = {k.arg for k in node.keywords if k.arg}
            bounded = bool(node.args) or (kw & _TIMEOUT_KWARGS)
            if fn.attr in ("join", "wait") and not bounded:
                self.add(node, f".{fn.attr}() without a timeout can "
                               f"wedge this thread forever; pass a "
                               f"bound (and handle expiry)")
            elif fn.attr == "get" and self._queue_like(fn.value) \
                    and not self._get_bounded(node, kw):
                self.add(node, ".get() without a timeout blocks "
                               "forever on an empty queue; pass "
                               "timeout= (or block=False) and handle "
                               "queue.Empty")
        self.generic_visit(node)

    @staticmethod
    def _get_bounded(node: ast.Call, kw: set) -> bool:
        """``queue.get`` blocks forever unless a timeout is passed
        (second positional or keyword) or block is literally False —
        ``get(True)`` / ``get(block=True)`` are still unbounded."""
        if (kw & _TIMEOUT_KWARGS) or len(node.args) >= 2:
            return True
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is False:
            return True
        return any(k.arg == "block"
                   and isinstance(k.value, ast.Constant)
                   and k.value.value is False
                   for k in node.keywords)

    @staticmethod
    def _queue_like(recv: ast.AST) -> bool:
        """Receiver looks like a queue (``self._q``, ``in_queue`` ...)
        — dict/module ``.get()`` (which needs a key anyway) stays out."""
        name = recv.attr if isinstance(recv, ast.Attribute) \
            else recv.id if isinstance(recv, ast.Name) else ""
        name = name.lower()
        return name in ("q", "queue") or name.endswith(("_q", "queue"))


class _RankWaitVisitor(ast.NodeVisitor):
    def __init__(self, add):
        self.add = add

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _RANK_WAIT_ATTRS:
            self.add(node, f"raw {fn.attr}() hangs forever when a peer "
                           f"rank dies; route the wait through "
                           f"resilience.coord (bounded, heartbeat-"
                           f"attributed)")
        self.generic_visit(node)


def _jitted_function_names(tree: ast.AST) -> Set[str]:
    """Names of functions this module jits: ``jax.jit(f)`` / ``jit(f)``
    call sites plus ``@jax.jit`` / ``@partial(jax.jit, ...)``
    decorators."""
    jitted: Set[str] = set()

    def is_jit(fn: ast.AST) -> bool:
        if isinstance(fn, ast.Name):
            return fn.id == "jit"
        if isinstance(fn, ast.Attribute):
            return _attr_chain(fn).endswith("jax.jit") \
                or fn.attr == "jit"
        return False

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_jit(node.func):
            for a in node.args:
                if isinstance(a, ast.Name):
                    jitted.add(a.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit(dec):
                    jitted.add(node.name)
                elif isinstance(dec, ast.Call) and (
                        is_jit(dec.func)
                        or any(is_jit(a) for a in dec.args)):
                    jitted.add(node.name)
    return jitted


def _check_time_in_jit(tree: ast.AST, add) -> None:
    jitted = _jitted_function_names(tree)
    if not jitted:
        return
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or node.name not in jitted:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute):
                chain = _attr_chain(sub.func)
                if chain in {f"time.{a}" for a in _CLOCK_ATTRS} \
                        or chain == "datetime.datetime.now":
                    add(sub, f"{chain}() inside jitted fn "
                             f"{node.name!r} executes once at trace "
                             f"time and bakes a constant into the "
                             f"executable; time on the host, outside "
                             f"the jit")


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def _component_suffix(norm: str, m: str) -> bool:
    """Path-component-anchored suffix match: ``executor.py`` matches
    ``flexflow_tpu/executor.py`` but NOT ``serving/batch_executor.py``,
    and works for package-root-relative paths too."""
    return norm == m or norm.endswith("/" + m)


def _host_sync_scope(norm: str) -> bool:
    return any(_component_suffix(norm, m) for m in HOST_SYNC_MODULES)


def _wait_scope(norm: str) -> bool:
    anchored = "/" + norm
    for m in WAIT_MODULES:
        if m.startswith("/"):
            if m in anchored:
                return True
        elif _component_suffix(norm, m):
            return True
    return False


def lint_file(path: str, source: Optional[str] = None,
              rules: Optional[Iterable[str]] = None) -> List[LintFinding]:
    """Lint one file; returns findings (pragma-suppressed ones removed).
    ``rules`` restricts the rule set (default: all)."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [LintFinding("parse-error", path, e.lineno or 0, 0,
                            f"file does not parse: {e.msg}")]
    active = set(rules) if rules is not None else set(RULES)
    norm = _norm(path)
    lines = source.splitlines()
    pragmas = _pragmas(source)
    findings: List[LintFinding] = []

    def adder(rule: str):
        def add(node: ast.AST, message: str) -> None:
            line = getattr(node, "lineno", 0)
            if _suppressed(pragmas, rule, line):
                return
            snippet = lines[line - 1].strip() \
                if 0 < line <= len(lines) else ""
            findings.append(LintFinding(
                rule, path, line, getattr(node, "col_offset", 0),
                message, snippet))
        return add

    if "bare-assert" in active and "/tests/" not in "/" + norm \
            and not os.path.basename(norm).startswith("test_"):
        _AssertVisitor(adder("bare-assert")).visit(tree)
    if "host-sync" in active and _host_sync_scope(norm):
        flush = set(FLUSH_FUNCS)
        for suffix, extra in MODULE_FLUSH_FUNCS.items():
            if _component_suffix(norm, suffix):
                flush |= extra
        _HostSyncVisitor(adder("host-sync"), flush).visit(tree)
    if "raw-wait" in active and _wait_scope(norm):
        _WaitVisitor(adder("raw-wait")).visit(tree)
    if "raw-rank-wait" in active \
            and not norm.endswith("resilience/coord.py"):
        _RankWaitVisitor(adder("raw-rank-wait")).visit(tree)
    if "time-in-jit" in active:
        _check_time_in_jit(tree, adder("time-in-jit"))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


def lint_paths(paths: Sequence[str],
               rules: Optional[Iterable[str]] = None
               ) -> List[LintFinding]:
    """Lint files and directory trees (``tests``/``__pycache__`` dirs
    and ``test_*.py`` files are skipped)."""
    findings: List[LintFinding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "tests",
                                              ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py") and not fn.startswith("test_"):
                        findings.extend(
                            lint_file(os.path.join(root, fn),
                                      rules=rules))
        else:
            findings.extend(lint_file(p, rules=rules))
    return findings


def render_text(findings: Sequence[LintFinding]) -> str:
    if not findings:
        return "ffcheck: clean"
    out = [f.format() for f in findings]
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    out.append("ffcheck: " + ", ".join(
        f"{n} x {r}" for r, n in sorted(by_rule.items())))
    return "\n".join(out)


def render_json(findings: Sequence[LintFinding]) -> str:
    seen: Dict[Tuple[str, str, str], int] = {}
    docs = []
    for f in findings:
        key = (f.rule, f.path, f.symbol)
        seq = seen.get(key, 0)
        seen[key] = seq + 1
        docs.append(f.to_json(seq))
    return json.dumps({"schema": JSON_SCHEMA_VERSION, "findings": docs,
                       "count": len(findings)}, indent=1)
