"""Shared AST module models for the ffcheck v2 engines.

The lock-discipline analyzer (:mod:`.concurrency`) and the
SPMD-divergence checker (:mod:`.spmd`) both need the same substrate: a
per-module model of classes, synchronization objects, instances, and
imports, plus a conservative package-wide call resolver so a summary
("locks this function acquires", "collectives this function performs")
can propagate through ``self.method()`` / ``module.function()`` /
``instance.method()`` call sites. This module is that substrate — pure
``ast``, no imports of the analyzed code, so an unimportable module
still analyzes.

Resolution is deliberately conservative: a call that cannot be resolved
statically contributes nothing (no false edges), and only modules
handed to the same :class:`Package` participate (single-file analyses
simply resolve less). Dotted module names are derived from the path's
``flexflow_tpu`` component when present so relative imports
(``from ..obs import events``) resolve across the package.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: constructor name -> synchronization-object kind. ``Condition`` wraps
#: an RLock by default, so re-acquisition is not a self-deadlock.
SYNC_CTORS: Dict[str, str] = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Event": "event", "Thread": "thread", "Semaphore": "lock",
    "BoundedSemaphore": "lock", "Barrier": "event",
}

#: kinds that a ``with`` block acquires (guard a critical section)
ACQUIRABLE = ("lock", "rlock", "condition")

#: method names that mutate a container in place — calling one on a
#: guarded field is a write for lock-discipline purposes
MUTATORS: Set[str] = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse",
}

#: keyword names that count as a bound on a wait/join call (mirrors
#: lint's raw-wait rule)
TIMEOUT_KWARGS = {"timeout", "timeout_s", "timeout_ms", "deadline_s",
                  "deadline"}


def norm_path(path: str) -> str:
    return path.replace(os.sep, "/")


def stable_path(path: str) -> str:
    """Repo-stable spelling of a finding path: the suffix from the
    package component on when present (absolute/relative prefixes vary
    per checkout and must not change finding IDs)."""
    norm = norm_path(path)
    parts = norm.split("/")
    for anchor in ("flexflow_tpu", "tests", "tools"):
        if anchor in parts:
            return "/".join(parts[parts.index(anchor):])
    return parts[-1]


def dotted_name(path: str) -> str:
    """Dotted module name derived from the path (anchored at the
    ``flexflow_tpu`` component when present)."""
    norm = norm_path(path)
    parts = norm.split("/")
    if "flexflow_tpu" in parts:
        parts = parts[parts.index("flexflow_tpu"):]
    else:
        parts = parts[-1:]
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def sync_kind_of_call(call: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` -> "lock" etc., else None."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else \
        fn.attr if isinstance(fn, ast.Attribute) else None
    return SYNC_CTORS.get(name or "")


def sync_kind_of_annotation(ann: Optional[ast.AST]) -> Optional[str]:
    """``Optional[threading.Thread]`` -> "thread" etc. — annotations
    type the attrs that start as None (``self._thread: Optional[
    threading.Thread] = None``)."""
    if ann is None:
        return None
    for sub in ast.walk(ann):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            # string annotations ("threading.Thread")
            name = sub.value.rsplit(".", 1)[-1].strip("] '\"")
        if name in SYNC_CTORS:
            return SYNC_CTORS[name]
    return None


class FuncInfo:
    """One function or method (nested defs included)."""

    __slots__ = ("module", "cls", "name", "qualname", "node")

    def __init__(self, module: "ModuleInfo", cls: Optional["ClassInfo"],
                 name: str, qualname: str, node: ast.AST):
        self.module = module
        self.cls = cls
        self.name = name
        self.qualname = qualname
        self.node = node


class ClassInfo:
    def __init__(self, module: "ModuleInfo", name: str):
        self.module = module
        self.name = name
        self.sync: Dict[str, str] = {}        # attr -> kind
        self.instances: Dict[str, Tuple[str, str]] = {}  # attr -> (mod, cls)
        self.methods: Dict[str, FuncInfo] = {}
        self.fields: Set[str] = set()          # every self.<attr> ever assigned


class ModuleInfo:
    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.norm = norm_path(path)
        self.dotted = dotted_name(path)
        # a package __init__ IS its package: `from . import x` there
        # resolves against self.dotted, not its parent
        self.is_package = os.path.basename(self.norm) == "__init__.py"
        self.tree = tree
        self.source = source
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}   # module-level defs
        self.all_functions: List[FuncInfo] = []    # incl. methods/nested
        self.sync: Dict[str, str] = {}             # global -> kind
        self.instances: Dict[str, Tuple[str, str]] = {}
        self.toplevel: Set[str] = set()            # names assigned at top level
        self.imports_mod: Dict[str, str] = {}      # alias -> dotted module
        self.imports_sym: Dict[str, Tuple[str, str]] = {}  # alias -> (mod, sym)


class Package:
    """A set of analyzed modules + the conservative resolver."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}

    # ------------------------------------------------------------------
    def add_source(self, path: str, source: str) -> Optional[ModuleInfo]:
        """Parse + model one file; returns None on syntax error (the
        caller reports rule ``parse-error`` through the linter)."""
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None
        mod = ModuleInfo(path, tree, source)
        self._collect(mod)
        self.modules[mod.dotted] = mod
        return mod

    def add_file(self, path: str) -> Optional[ModuleInfo]:
        with open(path, encoding="utf-8") as f:
            return self.add_source(path, f.read())

    # ------------------------------------------------------------------
    # model collection
    # ------------------------------------------------------------------
    def _collect(self, mod: ModuleInfo) -> None:
        self._collect_imports(mod)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(mod, None, node.name, node.name, node)
                mod.functions[node.name] = fi
                mod.all_functions.append(fi)
                self._collect_nested(mod, None, node, node.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._collect_toplevel_assign(mod, node)

    def _collect_imports(self, mod: ModuleInfo) -> None:
        # imports ANYWHERE in the module (this repo imports lazily
        # inside functions throughout)
        if mod.is_package:
            pkg_parts = mod.dotted.split(".") if mod.dotted else []
        else:
            pkg_parts = mod.dotted.split(".")[:-1] \
                if "." in mod.dotted else []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports_mod[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom):
                base: List[str] = []
                if node.level:
                    if node.level - 1 <= len(pkg_parts):
                        base = pkg_parts[:len(pkg_parts)
                                         - (node.level - 1)]
                    else:
                        continue
                if node.module:
                    base = base + node.module.split(".")
                target = ".".join(base)
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports_sym[a.asname or a.name] = (target, a.name)

    def _collect_toplevel_assign(self, mod: ModuleInfo, node) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = node.value
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        for t in targets:
            if isinstance(t, ast.Tuple):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        mod.toplevel.update(names)
        kind = sync_kind_of_call(value)
        if kind is None and isinstance(node, ast.AnnAssign):
            kind = sync_kind_of_annotation(node.annotation)
        if kind is not None:
            for n in names:
                mod.sync[n] = kind
            return
        inst = self._instance_of_call(mod, value)
        if inst is not None:
            for n in names:
                mod.instances[n] = inst

    def _instance_of_call(self, mod: ModuleInfo,
                          value) -> Optional[Tuple[str, str]]:
        """``X = ClassName(...)`` (same module or imported class) ->
        (dotted module, class name)."""
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        if isinstance(fn, ast.Name):
            if fn.id in mod.classes:
                return (mod.dotted, fn.id)
            sym = mod.imports_sym.get(fn.id)
            if sym is not None:
                return sym  # resolved lazily against the package
        elif isinstance(fn, ast.Attribute) and isinstance(fn.value,
                                                          ast.Name):
            target = mod.imports_mod.get(fn.value.id) \
                or self._sym_module(mod, fn.value.id)
            if target:
                return (target, fn.attr)
        return None

    def _sym_module(self, mod: ModuleInfo, alias: str) -> Optional[str]:
        """An imported symbol that is itself a module
        (``from . import status``) -> its dotted name."""
        sym = mod.imports_sym.get(alias)
        if sym is None:
            return None
        dotted = f"{sym[0]}.{sym[1]}" if sym[0] else sym[1]
        return dotted

    def _collect_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(mod, node.name)
        mod.classes[node.name] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{node.name}.{item.name}"
                fi = FuncInfo(mod, ci, item.name, qual, item)
                ci.methods[item.name] = fi
                mod.all_functions.append(fi)
                self._collect_nested(mod, ci, item, qual)
                self._collect_self_assigns(mod, ci, item)
            elif isinstance(item, ast.Assign):
                for t in item.targets:
                    if isinstance(t, ast.Name):
                        ci.fields.add(t.id)

    def _collect_nested(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                        fn, prefix: str) -> None:
        for sub in ast.walk(fn):
            if sub is fn or not isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = f"{prefix}.<nested>.{sub.name}"
            mod.all_functions.append(FuncInfo(mod, ci, sub.name, qual,
                                              sub))

    def _collect_self_assigns(self, mod: ModuleInfo, ci: ClassInfo,
                              fn) -> None:
        for sub in ast.walk(fn):
            ann = None
            if isinstance(sub, ast.AnnAssign):
                targets, value, ann = [sub.target], sub.value, \
                    sub.annotation
            elif isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            else:
                continue
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                ci.fields.add(t.attr)
                kind = sync_kind_of_call(value) \
                    or sync_kind_of_annotation(ann)
                if kind is not None:
                    ci.sync.setdefault(t.attr, kind)
                    continue
                inst = self._instance_of_call(mod, value)
                if inst is not None:
                    ci.instances.setdefault(t.attr, inst)
                # a list/comprehension of Threads is a thread-collection
                elif value is not None and any(
                        sync_kind_of_call(c) == "thread"
                        for c in ast.walk(value)
                        if isinstance(c, ast.Call)):
                    ci.sync.setdefault(t.attr, "thread")

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def class_info(self, ref: Optional[Tuple[str, str]]
                   ) -> Optional[ClassInfo]:
        if ref is None:
            return None
        m = self.modules.get(ref[0])
        if m is None:
            # `from pkg.mod import Cls` — ref[0] may be the defining
            # module with ref[1] the class
            return None
        ci = m.classes.get(ref[1])
        if ci is not None:
            return ci
        # ref may point at (module, instance-symbol)
        inst = m.instances.get(ref[1])
        if inst is not None:
            return self.class_info(inst)
        return None

    def module_of_alias(self, mod: ModuleInfo,
                        alias: str) -> Optional[ModuleInfo]:
        dotted = mod.imports_mod.get(alias)
        if dotted is None:
            dotted = self._sym_module(mod, alias)
        if dotted is None:
            return None
        return self.modules.get(dotted)

    def resolve_value(self, fn: FuncInfo, expr: ast.AST,
                      local_types: Dict[str, object]):
        """Resolve an expression to one of:
        ``("sync", kind, lock_id)`` — a synchronization object, where
        ``lock_id`` is the stable identity ``(module, class|None, attr)``;
        ``("instance", ClassInfo)``; ``("module", ModuleInfo)``;
        ``("class", ClassInfo)``; or None."""
        if isinstance(expr, ast.Name):
            if expr.id in local_types:
                # locals SHADOW module scope — an untyped local
                # (value None) resolves to nothing, never to a
                # same-named module object
                return local_types[expr.id]
            mod = fn.module
            if expr.id == "self" and fn.cls is not None:
                return ("instance", fn.cls)
            if expr.id in mod.sync:
                return ("sync", mod.sync[expr.id],
                        (mod.dotted, None, expr.id))
            if expr.id in mod.instances:
                ci = self.class_info(mod.instances[expr.id])
                if ci is not None:
                    return ("instance", ci)
                return None
            if expr.id in mod.classes:
                return ("class", mod.classes[expr.id])
            m = self.module_of_alias(mod, expr.id)
            if m is not None:
                return ("module", m)
            sym = mod.imports_sym.get(expr.id)
            if sym is not None:
                tm = self.modules.get(sym[0])
                if tm is not None:
                    if sym[1] in tm.classes:
                        return ("class", tm.classes[sym[1]])
                    if sym[1] in tm.instances:
                        ci = self.class_info(tm.instances[sym[1]])
                        if ci is not None:
                            return ("instance", ci)
                    if sym[1] in tm.sync:
                        return ("sync", tm.sync[sym[1]],
                                (tm.dotted, None, sym[1]))
            return None
        if isinstance(expr, ast.Attribute):
            base = self.resolve_value(fn, expr.value, local_types)
            if base is None:
                return None
            tag = base[0]
            if tag == "instance":
                ci: ClassInfo = base[1]
                if expr.attr in ci.sync:
                    return ("sync", ci.sync[expr.attr],
                            (ci.module.dotted, ci.name, expr.attr))
                if expr.attr in ci.instances:
                    sub = self.class_info(ci.instances[expr.attr])
                    if sub is not None:
                        return ("instance", sub)
                return None
            if tag == "module":
                m: ModuleInfo = base[1]
                if expr.attr in m.sync:
                    return ("sync", m.sync[expr.attr],
                            (m.dotted, None, expr.attr))
                if expr.attr in m.instances:
                    ci = self.class_info(m.instances[expr.attr])
                    if ci is not None:
                        return ("instance", ci)
                if expr.attr in m.classes:
                    return ("class", m.classes[expr.attr])
            return None
        return None

    def resolve_callee(self, fn: FuncInfo, call: ast.Call,
                       local_types: Dict[str, object]
                       ) -> Optional[FuncInfo]:
        """The FuncInfo a call statically resolves to, else None."""
        f = call.func
        mod = fn.module
        if isinstance(f, ast.Name):
            if f.id in local_types and local_types[f.id] is not None:
                return None  # calling a local object — not resolvable
            if f.id in mod.functions:
                return mod.functions[f.id]
            sym = mod.imports_sym.get(f.id)
            if sym is not None:
                tm = self.modules.get(sym[0])
                if tm is not None:
                    return tm.functions.get(sym[1])
            return None
        if isinstance(f, ast.Attribute):
            base = self.resolve_value(fn, f.value, local_types)
            if base is None:
                return None
            if base[0] == "instance":
                return base[1].methods.get(f.attr)
            if base[0] == "class":
                return base[1].methods.get(f.attr)
            if base[0] == "module":
                return base[1].functions.get(f.attr)
        return None


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Files + directory trees, skipping ``tests``/``__pycache__`` dirs
    and ``test_*.py`` (same walk as the linter's)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "tests",
                                              ".git"))
                for fname in sorted(files):
                    if fname.endswith(".py") \
                            and not fname.startswith("test_"):
                        out.append(os.path.join(root, fname))
        else:
            out.append(p)
    return out


def call_is_bounded(node: ast.Call) -> bool:
    """A wait/join call with a positional bound or a timeout kwarg."""
    if node.args:
        return True
    return bool({k.arg for k in node.keywords if k.arg}
                & TIMEOUT_KWARGS)
