"""Static plan verifier: prove a searched strategy executable, pre-device.

FlexFlow's simulator *scores* strategies but never proves them runnable —
this repo learned that twice (PR 6's GSPMD 4x-values and NaN-transition
miscompiles, both shipped by a search that was happy with the plan).
Following the legality conditions of portable-collective redistribution
(PAPERS.md, arXiv 2112.01075), this module checks a (strategy, layers,
machine) triple statically, at compile time, before a device ever runs
a step:

  1. **op-shard** — every op output / weight / graph-input
     PartitionSpec is mesh-axis sound (axes exist, no axis reused
     within a spec, spec rank fits the tensor rank) and every sharded
     dim is divisible by its axes' product (an indivisible shard is
     exactly the layout GSPMD falls back to generic padding/resharding
     on — the miscompile class the planner exists to bypass);
  2. **seam** — every layout seam lowers to a legal
     :class:`~flexflow_tpu.parallel.reshard.ReshardPlanner` plan:
     layout-op output constraints, bank stack/rejoin boundaries,
     pipeline-region entry/exit, and checkpoint-restore placement
     (``reshard.place_host``). A seam whose plan comes back
     ``kind="constraint"`` would fall back to GSPMD's generic
     resharding at runtime — flagged as an error with the op/seam
     attributed;
  3. **memory** — a conservative static per-device peak-memory envelope
     (params + grads + optimizer slots + peak activation pair + the
     largest planned reshard transient) against the machine model's HBM
     (or ``--device-mem-mb``);
  4. **collective-order** — SPMD deadlock freedom: all ranks must issue
     the same collective sequence. Full-mesh constraints and planned
     shard_map seams are order-consistent by construction; the
     structures that can diverge — bank members, place-group branches
     (MPMD-inside-SPMD ``lax.switch``), ragged-pipeline prologue/
     epilogue (``lax.cond`` on the stage index) — must not contain
     collective ops, and subset axes must not collide with the pipeline
     axes (the banks×pipeline double transition, PR 6's NaN bug).
     Extends to OVERLAPPED schedules (``strategy.overlap``,
     ``runtime/overlap.py``): the bucketed grad-sync launch order must
     be a dense total order per device, buckets disjoint with no
     subset-group (bank/place-group/pipeline) members, and the launch
     order must agree with backward completion order — a bucket
     scheduled ahead of a gradient backward has not produced yet is
     the overlapped-schedule deadlock class, rejected statically
     (fixture-pinned).
  5. **placement** — hierarchical-placement soundness (arXiv
     2110.10548, ``parallel/placement.py``): ``axis_tiers`` must map
     real mesh axes to known hardware tiers, every serialized
     reduction-tree phase must stay within a tier its site's tier path
     covers (a phase whose subset crosses an uncovered tier would
     deadlock or silently traverse the wrong fabric), and a
     latency-bound per-op collective placed across DCN — one whose
     payload is below the DCN bandwidth-latency product, so every step
     pays pure inter-slice latency — is a compile-time error with the
     offending tier attributed.
  6. **zero** — per-parameter optimizer-state sharding soundness
     (``strategy.zero``, arXiv 2004.13336): every sharded moment's
     spec must name real mesh axes, divide its weight's shape, and
     never reuse an axis the weight's own placement consumes (the
     collision that turns the reduce-scatter update into GSPMD
     generic resharding). The memory envelope (check 3) prices the
     optimizer slots per-parameter against the same assignment, so a
     plan that only fits *because* of ZeRO verifies.
  7. **kernel** — per-op kernel-implementation soundness
     (``strategy.kernel_impls``, kernels/registry.py): every adopted
     impl must be registered and its availability predicate must hold
     on the adopted mesh/shapes — ``ring`` without a mesh sequence
     axis is the fixture-pinned rejection. The memory envelope
     (check 3) counts ring-assigned attention ops at 1/seq-degree
     activation residency, so a context that only fits *because* of
     ring attention verifies.

``FFModel.compile`` runs this post-search (``FFConfig.plan_verify``,
``FF_PLAN_VERIFY=0`` to disable); failures raise
:class:`PlanVerificationError` naming the offending op/seam, findings
are appended to the strategy audit record, and every run bumps the
``ff_plan_verify_*`` counters under a ``plan_verify.run`` span.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY

__all__ = ["Finding", "PlanReport", "PlanVerificationError",
           "StructMesh", "memory_envelope", "verify_plan",
           "verify_model", "verify_serving_plan", "verify_strategy_file"]


# ---------------------------------------------------------------------------
# findings + report
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Finding:
    """One verification finding, attributed to an op and (optionally) a
    seam. ``check`` is the engine that produced it (op-shard / seam /
    memory / collective-order), ``severity`` "error" or "warn"."""
    check: str
    severity: str
    op: str
    message: str
    seam: Optional[str] = None

    def format(self) -> str:
        where = f"{self.op}" + (f" @ {self.seam}" if self.seam else "")
        return f"[{self.check}] {where}: {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class PlanVerificationError(ValueError):
    """A strategy failed static verification. ``findings`` carries the
    error-severity findings, each attributed to an op/seam."""

    def __init__(self, findings: Sequence[Finding], context: str = ""):
        self.findings = [f for f in findings if f.severity == "error"]
        lines = [f.format() for f in self.findings]
        head = f"plan verification failed ({len(lines)} error(s))"
        if context:
            head += f" for {context}"
        super().__init__(head + ":\n  " + "\n  ".join(lines))


@dataclasses.dataclass
class PlanReport:
    """The result of one verification pass: findings plus the derived
    artifacts (memory breakdown, static collective schedule)."""
    findings: List[Finding] = dataclasses.field(default_factory=list)
    memory: Dict[str, float] = dataclasses.field(default_factory=dict)
    collectives: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    duration_s: float = 0.0

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warn"]

    def ok(self) -> bool:
        return not self.errors

    def add(self, check: str, severity: str, op: str, message: str,
            seam: Optional[str] = None) -> None:
        self.findings.append(Finding(check, severity, op, message, seam))

    def to_json(self) -> Dict[str, Any]:
        return {"findings": [f.to_json() for f in self.findings],
                "memory": dict(self.memory),
                "collectives": list(self.collectives),
                "duration_s": self.duration_s,
                "ok": self.ok()}

    def raise_if_failed(self, context: str = "") -> None:
        if not self.ok():
            raise PlanVerificationError(self.findings, context)


# ---------------------------------------------------------------------------
# spec helpers (layout normalization itself lives in parallel.reshard)
# ---------------------------------------------------------------------------

def _spec_entries(spec) -> List[Tuple[str, ...]]:
    """Per-entry mesh-axis tuples of a PartitionSpec (or its JSON form),
    WITHOUT rank padding — used for rank/soundness checks."""
    out: List[Tuple[str, ...]] = []
    if spec is None:
        return out
    for e in tuple(spec):
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(e))
        else:
            out.append((e,))
    return out


def _check_spec(report: PlanReport, axis_sizes: Dict[str, int], op: str,
                what: str, spec, shape: Optional[Sequence[int]],
                seam: Optional[str] = None) -> None:
    """Mesh-axis soundness + divisibility of one PartitionSpec against
    one (possibly unknown) shape."""
    entries = _spec_entries(spec)
    if not entries:
        return
    seen: set = set()
    for axes in entries:
        for a in axes:
            if a not in axis_sizes:
                report.add("op-shard", "error", op,
                           f"{what} spec {spec} names unknown mesh axis "
                           f"{a!r} (mesh axes: {sorted(axis_sizes)})",
                           seam)
            elif a in seen:
                report.add("op-shard", "error", op,
                           f"{what} spec {spec} reuses mesh axis {a!r} "
                           f"(an axis may shard at most one dim)", seam)
            seen.add(a)
    if shape is None:
        return
    if len(entries) > len(shape):
        report.add("op-shard", "error", op,
                   f"{what} spec {spec} has {len(entries)} entries for a "
                   f"rank-{len(shape)} tensor of shape {tuple(shape)}",
                   seam)
        return
    for d, axes in enumerate(entries):
        deg = 1
        for a in axes:
            deg *= axis_sizes.get(a, 1)
        if deg > 1 and shape[d] % deg != 0:
            report.add("op-shard", "error", op,
                       f"{what} dim {d} of shape {tuple(shape)} is not "
                       f"divisible by its shard degree {deg} "
                       f"(axes {axes}) — this layout only executes via "
                       f"GSPMD's generic padded resharding", seam)


def _spec_degree(spec, axis_sizes: Dict[str, int]) -> int:
    """Total shard degree of a spec (shared definition:
    ``runtime/zero.spec_degree``)."""
    from ..runtime.zero import spec_degree
    return spec_degree(spec, axis_sizes)


def _opt_slots(optimizer) -> int:
    """Optimizer-state leaves per parameter for the memory envelope
    (shared definition: ``runtime/zero.opt_slots``)."""
    from ..runtime.zero import opt_slots
    return opt_slots(optimizer)


def _zero_of(strategy, zero=None):
    """Normalize a per-parameter ZeRO assignment: the explicit ``zero``
    argument wins, else the strategy's own ``.zero`` attribute; JSON
    dicts are lifted to :class:`~flexflow_tpu.runtime.zero.
    ZeroAssignment`. None = fully replicated optimizer state."""
    from ..runtime.zero import ZeroAssignment
    z = zero if zero is not None else getattr(strategy, "zero", None)
    if z is None or isinstance(z, ZeroAssignment):
        return z
    return ZeroAssignment.from_json(z)


# ---------------------------------------------------------------------------
# the verifier
# ---------------------------------------------------------------------------

def verify_plan(strategy, layers: Sequence, *,
                machine_spec=None,
                graph_inputs: Sequence = (),
                optimizer=None,
                hbm_bytes: Optional[float] = None,
                context: str = "") -> PlanReport:
    """Statically verify one (strategy, layers, machine) triple.

    ``strategy`` is a :class:`~flexflow_tpu.parallel.strategy.
    ShardingStrategy` (or any object with ``.ops``/``.inputs``/
    ``.banks``/``.place_groups``/``.pipeline`` and a ``.dmesh`` carrying
    ``axis_sizes``); ``layers`` the executable layer list the specs are
    keyed by (the rewritten program when the search rewrote the graph).
    Returns a :class:`PlanReport`; call :meth:`PlanReport.
    raise_if_failed` (what ``FFModel.compile`` does) to turn errors into
    a typed :class:`PlanVerificationError`.
    """
    t0 = time.perf_counter()
    report = PlanReport()
    dmesh = getattr(strategy, "dmesh", None)
    axis_sizes: Dict[str, int] = dict(getattr(dmesh, "axis_sizes", {}))
    spec = machine_spec or getattr(dmesh, "spec", None)
    by_name = {l.name: l for l in layers}

    _check_op_shards(report, strategy, by_name, axis_sizes, graph_inputs)
    reshard_peak = _check_seams(report, strategy, layers, by_name,
                                axis_sizes, spec, graph_inputs)
    _check_collective_order(report, strategy, layers, by_name, axis_sizes)
    _check_overlap(report, getattr(strategy, "overlap", None),
                   grouped=_overlap_grouped(strategy, layers),
                   pos={l.name: i for i, l in enumerate(layers)},
                   op_types={name: l.op_type
                             for name, l in by_name.items()},
                   have_layers=bool(by_name))
    _check_memory(report, strategy, layers, axis_sizes, spec, optimizer,
                  hbm_bytes, reshard_peak)
    _check_placement(report,
                     getattr(strategy, "axis_tiers", None) or {},
                     getattr(strategy, "collective_trees", None) or (),
                     axis_sizes, spec)
    unaddressable = _zero_unaddressable(strategy, layers)
    _check_zero(report, _zero_of(strategy),
                {name: getattr(os_, "weights", {}) or {}
                 for name, os_ in getattr(strategy, "ops", {}).items()},
                {name: {w.name: tuple(w.shape)
                        for w in (l.weights or ())}
                 for name, l in by_name.items()},
                axis_sizes, have_layers=bool(by_name),
                unaddressable=unaddressable)
    qsync = getattr(strategy, "qsync", None)
    qsync_tiers = dict(getattr(strategy, "axis_tiers", None) or {})
    if not qsync_tiers:
        # a non-searched (preset) strategy carries no placement record:
        # the mesh's own axis→tier derivation is the ground truth the
        # plan was built against
        try:
            qsync_tiers = dict(dmesh.axis_tiers)
        except Exception:  # noqa: BLE001 — tierless mesh
            qsync_tiers = {}
    _check_qsync(report,
                 qsync.to_json() if qsync is not None
                 and hasattr(qsync, "to_json") else qsync,
                 qsync_tiers,
                 {name: getattr(os_, "weights", {}) or {}
                  for name, os_ in getattr(strategy, "ops",
                                           {}).items()},
                 axis_sizes, have_layers=bool(by_name),
                 known_layers=set(by_name),
                 unaddressable=unaddressable)
    kimpls = getattr(strategy, "kernel_impls", None) or {}
    if kimpls:
        from ..ffconst import OperatorType
        from ..kernels import registry as kreg
        seq_deg = int(axis_sizes.get("seq", 0) or 0)
        attn_ctxs: Dict[str, Dict[str, Any]] = {}
        for name, l in by_name.items():
            if l.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
                q_len = int(l.inputs[0].shape[1]) if l.inputs else 0
                kv_len = int(l.inputs[1].shape[1]) \
                    if len(l.inputs) > 1 else q_len
                attn_ctxs[name] = kreg.attention_ctx(
                    l.params, q_len, kv_len, seq_degree=seq_deg)
        _check_kernel(report, kimpls, axis_sizes, attn_ctxs,
                      have_layers=bool(by_name),
                      known_layers=set(by_name))
    serving_doc = getattr(strategy, "serving", None)
    if serving_doc:
        _check_serving(report, serving_doc, by_name, axis_sizes, spec,
                       hbm_bytes)

    report.duration_s = time.perf_counter() - t0
    REGISTRY.counter("ff_plan_verify_runs_total",
                     "Static plan verification passes").inc()
    for f in report.findings:
        REGISTRY.counter("ff_plan_verify_findings_total",
                         "Plan verification findings by check"
                         ).inc(check=f.check)
    if report.errors:
        REGISTRY.counter("ff_plan_verify_errors_total",
                         "Plan verifications that found errors").inc()
    obs_events.record_span("plan_verify.run", t0, report.duration_s,
                           findings=len(report.findings),
                           errors=len(report.errors),
                           context=context or "")
    return report


# -- check 1: per-op shard specs --------------------------------------------

def _check_op_shards(report, strategy, by_name, axis_sizes,
                     graph_inputs) -> None:
    weight_shapes = {
        name: {w.name: tuple(w.shape) for w in (l.weights or ())}
        for name, l in by_name.items()}
    for name, os_ in getattr(strategy, "ops", {}).items():
        layer = by_name.get(name)
        for i, sp in enumerate(getattr(os_, "outputs", ()) or ()):
            if sp is None:
                continue
            shape = None
            if layer is not None and i < len(layer.outputs):
                shape = layer.outputs[i].shape
            _check_spec(report, axis_sizes, name, f"output[{i}]", sp,
                        shape)
        for wname, sp in (getattr(os_, "weights", {}) or {}).items():
            if sp is None:
                continue
            shape = weight_shapes.get(name, {}).get(wname)
            _check_spec(report, axis_sizes, name, f"weight {wname!r}",
                        sp, shape, seam="checkpoint-restore")
    in_shapes = {t.name: tuple(t.shape) for t in graph_inputs}
    for tname, sp in getattr(strategy, "inputs", {}).items():
        _check_spec(report, axis_sizes, tname, "input", sp,
                    in_shapes.get(tname))


# -- check 2: layout seams --------------------------------------------------

class StructMesh:
    """Structural mesh stand-in: ``axis_sizes`` plus a machine spec —
    everything the verifier, ``load_strategy``, and
    ``ReshardPlanner.plan`` need, with no jax devices behind it. Used
    by the CLI's strategy verification and the fixture tests."""

    def __init__(self, axis_sizes: Dict[str, int], spec=None):
        from ..parallel.machine import MachineSpec
        self.axis_sizes = {str(k): int(v) for k, v in axis_sizes.items()}
        self.spec = spec or MachineSpec(
            num_devices=int(np.prod(list(self.axis_sizes.values())
                                    or [1])),
            generation="cpu-sim")


def _seam_planner(strategy, spec, axis_sizes):
    """A non-persisting planner over the strategy's mesh: seam probes
    must not warm the executor's shared disk cache."""
    from ..parallel.reshard import ReshardPlanner
    return ReshardPlanner(StructMesh(axis_sizes, spec), persist=False)


def _probe_seam(report, planner, op: str, seam: str, src, dst,
                shape: Sequence[int], itemsize: int = 4) -> float:
    """Plan one seam transition; error when the planner cannot lower it
    (kind="constraint" = the GSPMD generic-resharding fallback — the
    PR 6 miscompile class). Returns the plan's transient peak bytes."""
    try:
        plan = planner.plan(src, dst, tuple(shape), itemsize)
    except Exception as e:  # noqa: BLE001 — surface, don't crash
        report.add("seam", "error", op,
                   f"planner failed to lower {src} -> {dst} on shape "
                   f"{tuple(shape)}: {e}", seam)
        return 0.0
    if plan.kind == "constraint":
        report.add(
            "seam", "error", op,
            f"transition {src} -> {dst} on shape {tuple(shape)} has no "
            f"legal portable-collective lowering (indivisible shard) "
            f"and would fall back to GSPMD generic resharding — the "
            f"known miscompile class the reshard planner exists to "
            f"bypass", seam)
        return 0.0
    report.collectives.append(
        {"seam": seam, "op": op, "kind": plan.kind,
         "steps": plan.describe()})
    return float(plan.peak_bytes)


def _check_seams(report, strategy, layers, by_name, axis_sizes, spec,
                 graph_inputs) -> float:
    from jax.sharding import PartitionSpec as P

    from ..parallel.reshard import (LAYOUT_OPS, _input_specs_replicated,
                                    norm_spec)
    planner = _seam_planner(strategy, spec, axis_sizes)
    peak = 0.0
    from ..dtypes import itemsize as _isz

    # (a) layout-op output constraints (executor emit_layers →
    #     reshard.constrain_output): replicated inputs + sharded output
    #     spec on a reshape/concat/... is an explicit transition
    for layer in layers:
        if layer.op_type not in LAYOUT_OPS:
            continue
        os_ = getattr(strategy, "ops", {}).get(layer.name)
        if os_ is None:
            continue
        for i, sp in enumerate(os_.outputs or ()):
            if sp is None or i >= len(layer.outputs):
                continue
            shape = layer.outputs[i].shape
            if not any(norm_spec(sp, len(shape))):
                continue
            if not _input_specs_replicated(strategy, layer):
                continue
            peak = max(peak, _probe_seam(
                report, planner, layer.name, "layout-op-output",
                P(), sp, shape, _isz(layer.outputs[i].dtype)))

    # (b) bank boundaries (executor._emit_bank → banks.shard_stack /
    #     rejoin_stack): the stacked member input moves onto the bank
    #     layout (an axis move) and the output stack rejoins by an
    #     explicit bank-dim gather
    for bk in getattr(strategy, "banks", None) or ():
        peak = max(peak, _check_bank(report, planner, strategy, bk,
                                     by_name, axis_sizes, _isz))

    # (c) pipeline-region entry/exit (pipeline_lowering.
    #     region_entry_transition / region_exit_transition)
    region = getattr(strategy, "pipeline", None)
    if region is not None:
        peak = max(peak, _check_pipeline_region(
            report, planner, strategy, region, layers, axis_sizes,
            graph_inputs))

    # (d) checkpoint-restore placement (reshard.place_host): a sharded
    #     weight restores shard-by-shard, which needs the same
    #     divisibility the op-shard check proved — attribute any
    #     sharded-but-indivisible weight to this seam (done in
    #     _check_op_shards via seam="checkpoint-restore").
    return peak


def _check_bank(report, planner, strategy, bk, by_name, axis_sizes,
                _isz) -> float:
    from jax.sharding import PartitionSpec as P

    from ..parallel.reshard import norm_spec, tensor_spec
    name = f"bank[{'+'.join(bk.members[:2])}{'...' if len(bk.members) > 2 else ''}]"
    missing = [m for m in bk.members if m not in by_name]
    if missing:
        report.add("seam", "error", name,
                   f"bank members {missing} are not in the program",
                   "bank-boundary")
        return 0.0
    bad_axes = [a for a in bk.axes if a not in axis_sizes]
    if bad_axes:
        report.add("seam", "error", name,
                   f"bank axes {bad_axes} are not mesh axes "
                   f"(mesh: {sorted(axis_sizes)})", "bank-boundary")
        return 0.0
    B = 1
    for a in bk.axes:
        B *= axis_sizes[a]
    K = len(bk.members)
    if K % max(B, 1) != 0:
        report.add("seam", "error", name,
                   f"bank degree {B} (axes {tuple(bk.axes)}) does not "
                   f"divide the member count {K}", "bank-boundary")
        return 0.0
    members = [by_name[m] for m in bk.members]
    m0 = members[0]
    if not m0.inputs or not m0.outputs:
        return 0.0
    bank_spec = bk.axes[0] if len(bk.axes) == 1 else tuple(bk.axes)
    batch_spec = None
    ish = m0.inputs[0].shape
    if bk.batch_axes and ish:
        bdeg = 1
        for a in bk.batch_axes:
            bdeg *= axis_sizes.get(a, 1)
        if ish[0] % bdeg == 0:
            batch_spec = (bk.batch_axes[0] if len(bk.batch_axes) == 1
                          else tuple(bk.batch_axes))
    stacked = (K,) + tuple(ish)
    # entry: member-input layout lifted one dim right → bank layout
    mem = norm_spec(tensor_spec(strategy, m0.inputs[0]), len(ish))
    src = P(None, *[tuple(d) if d else None for d in mem])
    dst = P(bank_spec, batch_spec, *([None] * (len(stacked) - 2)))
    peak = _probe_seam(report, planner, name, "bank-stack", src, dst,
                       stacked, _isz(m0.inputs[0].dtype))
    # exit: gather ONLY the bank dim (banks.rejoin_stack)
    osh = (K,) + tuple(m0.outputs[0].shape)
    pad = [None] * (len(osh) - 2)
    peak = max(peak, _probe_seam(
        report, planner, name, "bank-rejoin",
        P(bank_spec, batch_spec, *pad), P(None, batch_spec, *pad),
        osh, _isz(m0.outputs[0].dtype)))
    return peak


def _find_tensor(layers, graph_inputs, guid):
    for t in graph_inputs:
        if t.guid == guid:
            return t
    for l in layers:
        for t in l.outputs:
            if t.guid == guid:
                return t
    return None


def _check_pipeline_region(report, planner, strategy, region, layers,
                           axis_sizes, graph_inputs) -> float:
    from jax.sharding import PartitionSpec as P

    from ..parallel.reshard import norm_spec, tensor_spec
    peak = 0.0
    rname = f"pipeline[{region.n_stages} stages]"
    pp = getattr(region, "pp_axis", None)
    if pp is None or pp not in axis_sizes:
        report.add("seam", "error", rname,
                   f"pipeline pp_axis {pp!r} is not a mesh axis "
                   f"(mesh: {sorted(axis_sizes)})", "pipeline-entry")
        return peak
    if axis_sizes[pp] != region.n_stages:
        report.add("seam", "error", rname,
                   f"pp axis {pp!r} has size {axis_sizes[pp]} but the "
                   f"region has {region.n_stages} stages (one stage per "
                   f"pipeline rank)", "pipeline-entry")
    tp = getattr(region, "tp_axis", None)
    if tp is not None and tp not in axis_sizes:
        report.add("seam", "error", rname,
                   f"pipeline tp_axis {tp!r} is not a mesh axis",
                   "pipeline-entry")
    if getattr(region, "n_chunks", 1) > 1 \
            and region.n_microbatches % region.n_stages != 0:
        report.add("seam", "error", rname,
                   f"interleaved schedule needs M % S == 0, got "
                   f"M={region.n_microbatches} S={region.n_stages}",
                   "pipeline-entry")
    # entry: sharded activation gathered to replicated before the
    # microbatch reshape (region_entry_transition)
    entry_t = _find_tensor(layers, graph_inputs, region.entry_guid)
    if entry_t is not None and entry_t.shape:
        B = entry_t.shape[0]
        M = max(region.n_microbatches, 1)
        if B % M != 0:
            report.add("seam", "error", rname,
                       f"batch {B} is not divisible into {M} "
                       f"microbatches", "pipeline-entry")
        src = tensor_spec(strategy, entry_t)
        if src is not None and any(norm_spec(src, len(entry_t.shape))):
            from ..dtypes import itemsize as _isz
            peak = max(peak, _probe_seam(
                report, planner, rname, "pipeline-entry", src, P(),
                entry_t.shape, _isz(entry_t.dtype)))
    # exit: the engine's (M, mb, ...) output gathered back to
    # replicated (region_exit_transition) — dp-sharded on dim 1
    exit_t = _find_tensor(layers, graph_inputs, region.exit_guid)
    dp_axes = tuple(getattr(region, "dp_axes", ()) or ())
    if exit_t is not None and exit_t.shape and dp_axes:
        dp = dp_axes[0] if len(dp_axes) == 1 else tuple(dp_axes)
        M = max(region.n_microbatches, 1)
        B = exit_t.shape[0]
        if B % M == 0:
            ys_shape = (M, B // M) + tuple(exit_t.shape[1:])
            xs_spec = P(None, dp, *([None] * (len(ys_shape) - 2)))
            from ..dtypes import itemsize as _isz
            peak = max(peak, _probe_seam(
                report, planner, rname, "pipeline-exit", xs_spec, P(),
                ys_shape, _isz(exit_t.dtype)))
    return peak


# -- check 3: memory envelope -----------------------------------------------

def memory_envelope(strategy, layers, axis_sizes, optimizer, *,
                    reshard_peak: float = 0.0,
                    zero=None) -> Dict[str, float]:
    """Conservative static per-device memory envelope of one plan:
    params + grads + optimizer slots + live fwd/bwd activation pair +
    the largest planned reshard transient.

    The optimizer-slot term is **per-parameter**: a leaf the ZeRO
    assignment shards (``strategy.zero`` / the ``zero`` argument)
    counts at ``slots x bytes / (weight degree x zero degree)`` instead
    of the flat ``params x slots`` — so a plan that only fits *because*
    of optimizer-state sharding verifies (and the ZeRO planner adopts
    against the same arithmetic the verifier will enforce). With no
    assignment the numbers are bit-identical to the historical flat
    formula. Shared by ``_check_memory`` and
    ``search/zero_plan.plan_zero_assignment``."""
    from ..dtypes import itemsize as _isz
    from ..parallel.reshard import tensor_spec
    ops = getattr(strategy, "ops", {})
    zero_a = _zero_of(strategy, zero)
    unaddressable = _zero_unaddressable(strategy, layers) \
        if zero_a is not None else {}
    bank_deg = {}
    for bk in getattr(strategy, "banks", None) or ():
        d = 1
        for a in bk.axes:
            d *= axis_sizes.get(a, 1)
        for m in bk.members:
            bank_deg[m] = max(d, 1)
    slots = _opt_slots(optimizer)
    kernel_impls = getattr(strategy, "kernel_impls", None) or {}
    seq_degree = int(axis_sizes.get("seq", 1) or 1)
    params_local = 0.0
    opt_local = 0.0
    n_zero_sharded = 0
    act_peak, act_op = 0.0, ""
    for layer in layers:
        os_ = ops.get(layer.name)
        wspecs = getattr(os_, "weights", {}) if os_ is not None else {}
        for w in layer.weights or ():
            total = float(int(np.prod(w.shape)) or 1) * _isz(w.dtype)
            deg = _spec_degree(wspecs.get(w.name), axis_sizes)
            deg *= bank_deg.get(layer.name, 1)
            local = total / max(deg, 1)
            params_local += local
            # unaddressable layers (bank/place-group/pipeline state
            # lives under group keys) can never realize zero savings
            # at runtime — counting them would make the envelope
            # optimistic (the zero check errors on them separately)
            zdeg = 1
            if zero_a is not None and layer.name not in unaddressable:
                zdeg = zero_a.degree_for(layer.name, w.name)
            if zdeg > 1:
                n_zero_sharded += 1
            opt_local += slots * local / max(zdeg, 1)
        local = 0.0
        for t in list(layer.inputs) + list(layer.outputs):
            total = float(int(np.prod(t.shape)) or 1) * _isz(t.dtype)
            # inputs resolve through their PRODUCER's assigned spec
            # (tensor_spec) — counting them unsharded would inflate the
            # envelope by the sharding degree and false-fail the gate
            sp = tensor_spec(strategy, t)
            local += total / max(_spec_degree(sp, axis_sizes), 1)
        if kernel_impls.get(layer.name) == "ring" and seq_degree > 1:
            # ring attention (kernels/ring_attention.py) executes
            # inside a shard_map over the sequence axis: each device
            # holds only the 1/seq-degree chunk of q/k/v/output, and
            # the K/V block rotates in place — the op's live residency
            # divides by the seq degree. This is what lets a context
            # that only fits BECAUSE of ring attention verify.
            local /= seq_degree
        if local > act_peak:
            act_peak, act_op = local, layer.name
    total = params_local * 2 + opt_local + 2 * act_peak + reshard_peak
    return {
        "params_bytes": params_local,
        "grads_bytes": params_local,
        "opt_state_bytes": opt_local,
        "opt_slots": float(slots),
        "zero_sharded_params": float(n_zero_sharded),
        "peak_activation_bytes": act_peak,
        "peak_activation_op": act_op,
        "reshard_transient_bytes": reshard_peak,
        "envelope_bytes": total,
    }


def _check_memory(report, strategy, layers, axis_sizes, spec, optimizer,
                  hbm_bytes, reshard_peak) -> None:
    if hbm_bytes is None:
        hbm_bytes = getattr(spec, "hbm_bytes", None)
    if not hbm_bytes:
        return
    env = memory_envelope(strategy, layers, axis_sizes, optimizer,
                          reshard_peak=reshard_peak)
    # (XLA's scheduler can only do better than this ENVELOPE;
    # rematerialization and fusion shrink the activation term, never
    # grow it)
    report.memory = {**env, "hbm_bytes": float(hbm_bytes)}
    total = env["envelope_bytes"]
    act_op = env["peak_activation_op"]
    if total > hbm_bytes:
        zero_note = ""
        if env["zero_sharded_params"]:
            zero_note = (f", with {env['zero_sharded_params']:.0f} "
                         f"ZeRO-sharded opt leaves already counted")
        report.add(
            "memory", "error", act_op or "<model>",
            f"static per-device envelope {total / 2**20:.1f} MiB exceeds "
            f"the machine model's {hbm_bytes / 2**20:.1f} MiB HBM "
            f"(params {env['params_bytes'] / 2**20:.1f} MiB x 2 + opt "
            f"state {env['opt_state_bytes'] / 2**20:.1f} MiB"
            f"{zero_note} + 2 x peak activation "
            f"{env['peak_activation_bytes'] / 2**20:.1f} MiB [{act_op}] "
            f"+ reshard transient {reshard_peak / 2**20:.1f} MiB)",
            "memory-envelope")


# -- check 3.5: per-parameter ZeRO assignment ---------------------------------

def _zero_unaddressable(strategy, layers) -> Dict[str, str]:
    """Layers whose optimizer state the per-layer assignment CANNOT
    address at runtime: bank / place-group members (state stacked
    under the group key on device subsets) and layers inside a
    pipeline region (state stacked under template keys). The planner
    excludes them; an imported assignment that shards one would claim
    envelope savings the runtime can't realize — flagged as an error
    instead of letting an optimistic plan verify and OOM at step 1."""
    out: Dict[str, str] = {}
    for bk in getattr(strategy, "banks", None) or ():
        for m in bk.members:
            out[m] = "bank"
    for pg in getattr(strategy, "place_groups", None) or ():
        for m in pg.members:
            out[m] = "place-group"
    region = getattr(strategy, "pipeline", None)
    if region is not None:
        for l in list(layers)[region.start:region.end]:
            out[l.name] = "pipeline-region"
    return out


def _check_zero(report, zero_a, weight_specs, weight_shapes, axis_sizes,
                have_layers: bool = True,
                unaddressable: Optional[Dict[str, str]] = None) -> None:
    """Soundness of a per-parameter optimizer-state sharding assignment
    (``strategy.zero``): every sharded moment's spec must name real
    mesh axes, divide its weight's shape, and — the invariant that
    makes the GSPMD lowering a reduce-scatter instead of a resharding
    storm — must NOT reuse a mesh axis the weight's own placement
    already consumes. A colliding assignment is a typed compile-time
    error (:class:`PlanVerificationError`), not a runtime surprise."""
    if zero_a is None:
        return
    from ..runtime.zero import spec_axes
    unaddressable = unaddressable or {}
    for lname, ws in zero_a.decisions.items():
        lw_specs = weight_specs.get(lname, {})
        lw_shapes = weight_shapes.get(lname, {})
        if lname in unaddressable \
                and any(rec.get("spec") is not None
                        for rec in ws.values()):
            report.add(
                "zero", "error", lname,
                f"zero assignment shards optimizer state of "
                f"{unaddressable[lname]} member {lname!r}, whose state "
                f"is stacked under a group key the per-layer "
                f"assignment cannot address — the runtime would leave "
                f"it replicated while the memory envelope counted it "
                f"sharded (an optimistic plan that OOMs at step 1)",
                "zero-assignment")
            continue
        if have_layers and lname not in weight_shapes:
            if any(rec.get("spec") is not None for rec in ws.values()):
                report.add("zero", "error", lname,
                           f"zero assignment shards state of op "
                           f"{lname!r}, which is not in the program",
                           "zero-assignment")
            continue
        for wname, rec in ws.items():
            sp = rec.get("spec")
            if sp is None:
                continue
            sp = _json_spec(sp) if isinstance(sp, list) else sp
            shape = lw_shapes.get(wname)
            if have_layers and lw_shapes and wname not in lw_shapes:
                report.add("zero", "error", lname,
                           f"zero assignment shards unknown weight "
                           f"{wname!r} (weights: {sorted(lw_shapes)})",
                           "zero-assignment")
                continue
            _check_spec(report, axis_sizes, lname,
                        f"opt-state for weight {wname!r}", sp, shape,
                        seam="zero-assignment")
            wspec = lw_specs.get(wname)
            # the moment FOLLOWS the weight's own placement on the
            # weight's sharded dims (m/v are zeros_like the param);
            # the ZeRO axes proper are the EXTRA ones. A weight axis
            # re-used on a DIFFERENT dim is the collision that turns
            # the reduce-scatter update into generic resharding.
            z_entries = _spec_entries(sp)
            w_entries = _spec_entries(wspec)
            w_entries += [()] * (len(z_entries) - len(w_entries))
            w_axes = set(spec_axes(wspec))
            overlap = sorted(
                a for d, axes in enumerate(z_entries)
                for a in axes
                if a in w_axes and a not in w_entries[d])
            if overlap:
                report.add(
                    "zero", "error", lname,
                    f"zero assignment shards the {wname!r} optimizer "
                    f"state over mesh axis(es) {overlap} that the "
                    f"weight's own placement {wspec} already consumes "
                    f"on a different dim — the moment must shard over "
                    f"the axes the weight is REPLICATED on "
                    f"(reduce-scatter group), or the update "
                    f"degenerates to GSPMD generic resharding",
                    "zero-assignment")


# -- check 3.75: quantized grad-sync plan -------------------------------------

def _check_qsync(report, qsync_doc, axis_tiers, weight_specs,
                 axis_sizes, have_layers: bool = True,
                 known_layers=(), unaddressable=None) -> None:
    """Soundness of a quantized-collectives plan (``strategy.qsync``,
    ops/quantized_collectives.py):

      - a quantized phase is legal only on its DECLARED tier path —
        every axis a phase names must exist and sit on the phase's
        declared tier per ``axis_tiers`` (a plan that labels an ICI
        axis as a "dcn" leg would narrow the FAST fabric while the
        accuracy-risk gate believed only the slow one was touched);
      - replicated-math seams stay full-precision: only the gradient
        all-reduce of a REPLICATED weight may quantize — a decision on
        a sharded weight (whose gradient flows through per-op
        collectives) or a bank / place-group / pipeline member is an
        error;
      - wire dtypes must be known, and an axis may appear in at most
        one phase of a decision.
    """
    if not qsync_doc:
        return
    from ..parallel.placement import WIRE_ITEMSIZE
    from ..parallel.topology import TIER_ORDER
    from ..runtime.zero import spec_degree
    unaddressable = unaddressable or {}
    known_layers = set(known_layers or ())
    decisions = (qsync_doc or {}).get("decisions", {})
    for lname, ws in decisions.items():
        lw_specs = weight_specs.get(lname, {})
        quantized = any(
            p.get("wire") for rec in ws.values()
            for p in rec.get("phases", ()))
        if not quantized:
            continue
        if lname in unaddressable:
            report.add(
                "qsync", "error", lname,
                f"qsync plan quantizes gradient sync of "
                f"{unaddressable[lname]} member {lname!r}, whose "
                f"gradients live under a group key on a device subset "
                f"— the explicit sync cannot address them and the "
                f"implicit one would stay full-precision while the "
                f"plan claimed otherwise", "qsync-plan")
            continue
        if have_layers and known_layers and lname not in known_layers:
            report.add("qsync", "error", lname,
                       f"qsync plan names op {lname!r}, which is not "
                       f"in the program", "qsync-plan")
            continue
        for wname, rec in ws.items():
            phases = rec.get("phases", ())
            if not any(p.get("wire") for p in phases):
                continue
            wspec = lw_specs.get(wname)
            if wspec is not None \
                    and spec_degree(wspec, axis_sizes) > 1:
                report.add(
                    "qsync", "error", lname,
                    f"qsync plan quantizes the gradient of weight "
                    f"{wname!r}, whose placement {wspec} is SHARDED — "
                    f"its gradient flows through per-op (replicated-"
                    f"math) collectives, which must stay full-"
                    f"precision; only the data-parallel all-reduce of "
                    f"a replicated weight may quantize", "qsync-plan")
            seen_axes: set = set()
            for p in phases:
                wire = p.get("wire")
                tier = str(p.get("tier", "ici"))
                if wire is not None and wire not in WIRE_ITEMSIZE:
                    report.add("qsync", "error", lname,
                               f"phase on tier {tier!r} names unknown "
                               f"wire dtype {wire!r} (known: "
                               f"{sorted(WIRE_ITEMSIZE)})",
                               "qsync-plan")
                if tier not in TIER_ORDER:
                    report.add("qsync", "error", lname,
                               f"phase declares unknown tier {tier!r} "
                               f"(tiers: {list(TIER_ORDER)})",
                               "qsync-plan")
                for a in p.get("axes", ()):
                    if axis_sizes and a not in axis_sizes:
                        report.add(
                            "qsync", "error", lname,
                            f"phase on tier {tier!r} names unknown "
                            f"mesh axis {a!r} (axes: "
                            f"{sorted(axis_sizes)})", "qsync-plan")
                        continue
                    if a in seen_axes:
                        report.add(
                            "qsync", "error", lname,
                            f"axis {a!r} appears in more than one "
                            f"phase of {wname!r}'s sync — the staged "
                            f"reduction would traverse it twice",
                            "qsync-plan")
                    seen_axes.add(a)
                    actual = (axis_tiers or {}).get(a, "ici")
                    if wire is not None and actual != tier:
                        report.add(
                            "qsync", "error", lname,
                            f"quantized phase declares tier {tier!r} "
                            f"but its axis {a!r} is placed on tier "
                            f"{actual!r} — a quantized leg is legal "
                            f"only on its declared tier path (the "
                            f"accuracy-risk gate scoped the narrowing "
                            f"to {tier!r} fabric)", "qsync-plan")


# -- check 3.7: per-op kernel implementations --------------------------------

def _check_kernel(report, kimpls, axis_sizes: Dict[str, int],
                  attn_ctxs: Dict[str, Dict[str, Any]], *,
                  have_layers: bool, known_layers=()) -> None:
    """Adopted kernel-impl assignment (``strategy.kernel_impls``,
    kernels/registry.py): every impl name must be registered and its
    availability predicate must hold on the adopted mesh/shapes —
    ``ring`` on a mesh without a sequence axis is THE fixture-pinned
    rejection (an imported plan would otherwise reach emit and fail
    deep inside tracing). ``attn_ctxs`` maps attention layer names to
    their predicate contexts; a name missing from it with layers known
    is a kernel impl assigned to a non-attention op."""
    from ..kernels import registry as kreg
    seq_deg = int(axis_sizes.get("seq", 0) or 0)
    for key, impl in (kimpls or {}).items():
        if key == kreg.OPT_UPDATE:
            if impl not in kreg.impl_names(kreg.OPT_UPDATE):
                report.add(
                    "kernel", "error", key,
                    f"unknown opt_update impl {impl!r} (known: "
                    f"{sorted(kreg.impl_names(kreg.OPT_UPDATE))})",
                    "kernel-impl")
            # the fused predicate is backend-gated (TPU-only): a
            # runtime property, re-checked when the importing process
            # plans (FFModel._plan_kernels), not statically here
            continue
        if impl not in kreg.impl_names(kreg.ATTENTION):
            report.add(
                "kernel", "error", key,
                f"unknown attention impl {impl!r} (known: "
                f"{sorted(kreg.impl_names(kreg.ATTENTION))})",
                "kernel-impl")
            continue
        ctx = attn_ctxs.get(key)
        if ctx is None:
            if have_layers and key not in known_layers:
                report.add(
                    "kernel", "error", key,
                    f"kernel impl {impl!r} is assigned to an op the "
                    f"program does not contain", "kernel-impl")
                continue
            if have_layers:
                report.add(
                    "kernel", "error", key,
                    f"kernel impl {impl!r} is assigned to a "
                    f"non-attention op", "kernel-impl")
                continue
            # spec-only strategy file (no program block): shapes are
            # unknown, but the one mesh-level requirement still binds
            if impl == "ring" and seq_deg < 2:
                report.add(
                    "kernel", "error", key,
                    "kernel impl 'ring' requires a mesh sequence axis "
                    "('seq', degree >= 2); the strategy's mesh_axes "
                    f"have {dict(axis_sizes)}", "kernel-impl")
            continue
        reason = kreg.get_impl(kreg.ATTENTION, impl).available(ctx)
        if reason is not None:
            report.add(
                "kernel", "error", key,
                f"kernel impl {impl!r} is not available on the "
                f"adopted mesh/shapes: {reason}", "kernel-impl")


# -- check 4: collective-ordering consistency --------------------------------

def _check_collective_order(report, strategy, layers, by_name,
                            axis_sizes) -> None:
    from ..ffconst import PARALLEL_OPS
    region = getattr(strategy, "pipeline", None)
    region_names: set = set()
    if region is not None:
        region_names = {l.name for l in layers[region.start:region.end]}
        pp_axes = {a for a in (getattr(region, "pp_axis", None),
                               getattr(region, "tp_axis", None))
                   if a is not None}
    else:
        pp_axes = set()

    def subset_check(kind: str, members, axes, seam: str) -> None:
        name = f"{kind}[{'+'.join(list(members)[:2])}" \
               f"{'...' if len(members) > 2 else ''}]"
        overlap = set(axes) & pp_axes
        if overlap:
            report.add(
                "collective-order", "error", name,
                f"{kind} axes {sorted(overlap)} collide with the "
                f"pipeline region's stage/tp axes — the double "
                f"transition this composes is the banks x pipeline "
                f"NaN-miscompile class (PR 6); place the {kind} on "
                f"disjoint axes", seam)
        inside = sorted(set(members) & region_names)
        if inside:
            report.add(
                "collective-order", "error", name,
                f"members {inside} lie inside the pipeline region: "
                f"their subset lowering cannot nest in the GPipe "
                f"shard_map (stage-divergent collective sequence = "
                f"deadlock)", seam)
        for m in members:
            l = by_name.get(m)
            if l is not None and l.op_type in PARALLEL_OPS:
                report.add(
                    "collective-order", "error", m,
                    f"collective op {l.op_type.name} cannot be a {kind} "
                    f"member: only its subset would issue the "
                    f"collective (rank-divergent sequence = deadlock)",
                    seam)

    for bk in getattr(strategy, "banks", None) or ():
        subset_check("bank", bk.members, bk.axes, "bank-boundary")
    for pg in getattr(strategy, "place_groups", None) or ():
        # (a member's OUTPUT spec may legitimately shard over the
        # placement axis — the lowering rejoins branches with a masked
        # full-axis psum, so the constraint applies to the rejoined
        # value, not inside a branch)
        subset_check("place-group", pg.members, (pg.axis,),
                     "place-group")
    if region is not None:
        from ..ffconst import PARALLEL_OPS as _POPS
        for l in list(getattr(region, "prologue", ()) or ()) \
                + list(getattr(region, "epilogue", ()) or ()):
            if l.op_type in _POPS:
                report.add(
                    "collective-order", "error", l.name,
                    "collective op inside a ragged-pipeline prologue/"
                    "epilogue runs under lax.cond on the stage index — "
                    "only one stage would issue it (deadlock)",
                    "pipeline-prologue")


# -- check 4.5: overlapped grad-sync schedule --------------------------------

def _overlap_grouped(strategy, layers) -> Dict[str, str]:
    """Layer name -> subset-group kind for the overlap check: bank /
    place-group members and pipeline-region layers — the layers whose
    gradients are NOT per-layer addressable on every rank."""
    grouped: Dict[str, str] = {}
    for bk in getattr(strategy, "banks", None) or ():
        for m in bk.members:
            grouped[m] = "bank"
    for pg in getattr(strategy, "place_groups", None) or ():
        for m in pg.members:
            grouped[m] = "place-group"
    region = getattr(strategy, "pipeline", None)
    if region is not None:
        for l in list(layers)[region.start:region.end]:
            grouped[l.name] = "pipeline-region"
    return grouped


def _check_overlap(report, overlap_rec, *, grouped: Dict[str, str],
                   pos: Dict[str, int], op_types: Dict[str, Any],
                   have_layers: bool) -> None:
    """Collective-ordering soundness of an overlapped grad-sync schedule
    (``strategy.overlap``, built by ``runtime/overlap.py`` or imported):

      - the bucket launch order must be TOTAL per device — a dense,
        duplicate-free ``order`` sequence. Every rank derives the same
        chain from the same record, so a total order here is a total
        order everywhere (the no-new-deadlock-class invariant: two
        ranks can never launch bucket collectives in different orders);
      - bucket members must be disjoint, exist in the program, and not
        be collective (parallel) ops;
      - members must not sit inside a pipeline region, bank, or place
        group: their gradients live under group keys on device subsets,
        so a bucket naming one would launch its sync collective from a
        SUBSET of ranks while the chain token holds the rest — the
        rank-divergent launch sequence the total order exists to
        prevent;
      - the launch order must agree with backward completion order:
        every member of bucket k must come LATER in program order than
        every member of bucket k+1 (backward produces deep layers'
        grads first). A bucket scheduled before a grad that backward
        has not produced yet would stall the whole chain on it — on an
        async multi-runtime the overlapped-schedule deadlock class
        (rejection pinned by ``tests/fixtures/badplan_overlap_order.
        json``).
    """
    if not overlap_rec:
        return
    from ..ffconst import PARALLEL_OPS
    buckets = list(overlap_rec.get("buckets") or ())
    if not buckets:
        return
    orders = [int(b.get("order", -1)) for b in buckets]
    if sorted(orders) != list(range(len(buckets))):
        report.add(
            "collective-order", "error", "overlap-schedule",
            f"bucket launch order {orders} is not a dense total order "
            f"over {len(buckets)} buckets — ranks could disagree on "
            f"the grad-sync launch sequence (deadlock)",
            "overlap-schedule")
        return
    seen: Dict[str, int] = {}
    by_order = sorted(buckets, key=lambda b: int(b.get("order", 0)))
    for b in by_order:
        o = int(b.get("order", 0))
        name = f"overlap-bucket[{o}]"
        for m in b.get("members") or ():
            if m in seen:
                report.add(
                    "collective-order", "error", name,
                    f"member {m!r} appears in buckets {seen[m]} and "
                    f"{o} — its grad sync would launch twice, in a "
                    f"chain position other ranks may resolve "
                    f"differently", "overlap-schedule")
            seen[m] = o
            op_type = op_types.get(m)
            if have_layers and m not in op_types:
                report.add("collective-order", "error", name,
                           f"member {m!r} is not in the program",
                           "overlap-schedule")
                continue
            if op_type is not None and op_type in PARALLEL_OPS:
                report.add(
                    "collective-order", "error", name,
                    f"collective op {getattr(op_type, 'name', op_type)}"
                    f" cannot be an overlap-bucket member (it has no "
                    f"weight gradient to sync; chaining it reorders "
                    f"the per-op collective sequence across ranks)",
                    "overlap-schedule")
            if m in grouped:
                report.add(
                    "collective-order", "error", name,
                    f"member {m!r} is a {grouped[m]} member: its "
                    f"gradients live under a group key on a device "
                    f"subset, so only that subset would launch the "
                    f"bucket's sync while the chain token holds the "
                    f"other ranks (rank-divergent launch = deadlock)",
                    "overlap-schedule")
    if not pos:
        return
    for prev, nxt in zip(by_order, by_order[1:]):
        prev_members = [m for m in (prev.get("members") or ()) if m in pos]
        nxt_members = [m for m in (nxt.get("members") or ()) if m in pos]
        if not prev_members or not nxt_members:
            continue
        lo = min(pos[m] for m in prev_members)
        hi = max(pos[m] for m in nxt_members)
        if lo <= hi:
            bad_prev = min(prev_members, key=lambda m: pos[m])
            bad_nxt = max(nxt_members, key=lambda m: pos[m])
            report.add(
                "collective-order", "error",
                f"overlap-bucket[{int(prev.get('order', 0))}]",
                f"launch order contradicts backward completion order: "
                f"bucket {int(prev.get('order', 0))} member "
                f"{bad_prev!r} (program position {pos[bad_prev]}) "
                f"launches before bucket {int(nxt.get('order', 0))} "
                f"member {bad_nxt!r} (position {pos[bad_nxt]}), but "
                f"backward produces {bad_nxt!r}'s gradient FIRST — "
                f"the chain would stall every later bucket on a grad "
                f"not yet produced (the overlapped-schedule deadlock "
                f"class)", "overlap-schedule")


# -- check 5: hierarchical placement -----------------------------------------

def _dcn_tier_constants(spec) -> Tuple[float, float]:
    """(bandwidth bytes/s, latency s) of the DCN tier: the machine
    model's tier graph when available, else the MachineSpec defaults —
    strategy-file verification has no machine behind it but the
    latency-bound check must still bind."""
    try:
        tg = spec.tier_graph
        for t in tg.tiers:
            if t.name == "dcn":
                return t.bandwidth, t.latency_s
    except Exception:  # noqa: BLE001
        pass
    bw = getattr(spec, "dcn_bandwidth", None) or 25e9
    lat = (getattr(spec, "dcn_latency_us", None) or 10.0) * 1e-6
    return float(bw), float(lat)


def _check_placement(report, axis_tiers, collective_trees, axis_sizes,
                     spec) -> None:
    from ..parallel.topology import TIER_ORDER
    for axis, tier in dict(axis_tiers).items():
        if axis_sizes and axis not in axis_sizes:
            report.add("placement", "error", axis,
                       f"axis_tiers names axis {axis!r} absent from the "
                       f"mesh (axes: {sorted(axis_sizes)})",
                       "axis-placement")
        if tier not in TIER_ORDER:
            report.add("placement", "error", axis,
                       f"axis {axis!r} is placed on unknown tier "
                       f"{tier!r} (tiers: {list(TIER_ORDER)})",
                       "axis-placement")
    dcn_bw, dcn_lat = _dcn_tier_constants(spec)
    # devices reachable WITHOUT crossing DCN: a collective whose degree
    # fits inside this span had an inner placement available — crossing
    # DCN anyway is a placement error; a wider collective has no choice
    # (flagging it would reject every full-mesh reduction)
    inner_span = 1
    for axis, tier in dict(axis_tiers).items():
        if tier != "dcn":
            inner_span *= int(axis_sizes.get(axis, 1))
    for rec in collective_trees:
        site = str(rec.get("site", "?"))
        coll = str(rec.get("collective", "?"))
        name = f"{site}/{coll}"
        path = [(str(t), int(d)) for t, d in rec.get("tier_path", ())]
        covered = {t for t, _ in path}
        bad_tiers = sorted(t for t in covered if t not in TIER_ORDER)
        if bad_tiers:
            report.add("placement", "error", name,
                       f"tier path {path} names unknown tier(s) "
                       f"{bad_tiers}", "reduction-tree")
            continue
        deg_of = dict(path)
        total_deg = 1
        for _t, d in path:
            total_deg *= d
        outermost = path[-1][0] if path else None
        for ph in rec.get("phases", ()):
            pt = str(ph.get("tier"))
            ph_deg = int(ph.get("degree", 1))
            # a single-phase ring / halving-doubling tree SPANS the
            # whole path through its bottleneck (outermost) tier: its
            # degree is the path's total product, which is legal there
            spans_path = pt == outermost and ph_deg == total_deg
            if pt not in covered:
                report.add(
                    "placement", "error", name,
                    f"tree phase {ph.get('collective')}[x"
                    f"{ph.get('degree')}] runs on tier {pt!r}, which "
                    f"the site's tier path {path} does not cover — the "
                    f"phase's participant subset would traverse a "
                    f"fabric the placement never reserved",
                    "reduction-tree")
            elif ph_deg > deg_of.get(pt, 1) and not spans_path:
                report.add(
                    "placement", "error", name,
                    f"tree phase {ph.get('collective')} degree "
                    f"{ph.get('degree')} exceeds the {pt} tier's "
                    f"degree {deg_of.get(pt, 1)} in path {path}",
                    "reduction-tree")
        # latency-bound per-op collective across DCN when an inner
        # placement existed: the payload is below the DCN bandwidth-
        # latency product, so the inter-slice leg is pure latency EVERY
        # step — a placement the search must never ship. Collectives
        # wider than the intra-slice span have no inner option and are
        # a strategy (not placement) matter; grad sync, once per step
        # on the whole gradient, only warns.
        avoidable = axis_tiers and \
            int(rec.get("degree", 0) or 0) <= inner_span
        if "dcn" in covered and site != "grad_sync" and avoidable:
            vol = float(rec.get("volume_bytes", 0.0) or 0.0)
            d_dcn = deg_of.get("dcn", 1)
            bound = dcn_bw * dcn_lat * max(d_dcn, 1)
            if 0 < vol < bound:
                report.add(
                    "placement", "error", name,
                    f"latency-bound per-step collective placed across "
                    f"tier 'dcn': payload {vol / 1024:.1f} KiB is below "
                    f"the DCN bandwidth-latency product "
                    f"({bound / 1024:.0f} KiB at "
                    f"{dcn_bw / 1e9:.0f} GB/s x {dcn_lat * 1e6:.0f} us "
                    f"x{d_dcn}) — every step pays pure inter-slice "
                    f"latency; place this collective on an inner tier",
                    "latency-bound-dcn")
        elif "dcn" in covered and site == "grad_sync":
            vol = float(rec.get("volume_bytes", 0.0) or 0.0)
            d_dcn = deg_of.get("dcn", 1)
            if 0 < vol < dcn_bw * dcn_lat * max(d_dcn, 1):
                report.add(
                    "placement", "warn", name,
                    f"gradient sync across DCN is latency-bound at "
                    f"{vol / 1024:.1f} KiB — consider a larger "
                    f"per-step gradient volume or intra-slice "
                    f"replication", "latency-bound-dcn")


# ---------------------------------------------------------------------------
# wiring helpers
# ---------------------------------------------------------------------------

# -- check 8: per-(model, batch-class) serving plans --------------------------

def _check_serving(report, serving_doc, by_name, axis_sizes, spec,
                   hbm_bytes) -> None:
    """Serving-block soundness: every bucket's KV-cache shard degree
    must divide the layer's KV-head count (a decode step cannot split
    a KV head across devices), the recorded per-layer KV bytes must
    match the declared geometry, each bucket's op specs must be
    mesh-sound, and the decode-resident envelope (weights + KV cache +
    live activations) at the LARGEST bucket must fit the machine's
    HBM. The memory gate is what makes a replicated-KV plan that only
    fits sharded fail typed at compile instead of OOMing on the first
    large-bucket request. ``serving_doc`` is always the JSON block
    (``ServingPlan.to_block`` form) — both the in-memory attach and
    ``load_strategy`` carry it that way."""
    from ..dtypes import itemsize as _isz
    try:
        buckets = {int(k): (v or {}) for k, v in
                   (serving_doc.get("buckets") or {}).items()}
    except (TypeError, ValueError):
        report.add("serving", "error", "<serving>",
                   "serving block bucket keys must be integers",
                   "serving-plan")
        return
    if not buckets:
        report.add("serving", "error", "<serving>",
                   "serving block carries no buckets", "serving-plan")
        return
    max_seq = int(serving_doc.get("max_seq") or 0)
    if max_seq <= 0:
        report.add("serving", "error", "<serving>",
                   "serving block has no max_seq (KV geometry is "
                   "unsized)", "serving-plan")
        return
    for bucket, sub in sorted(buckets.items()):
        ctx = f"bucket={bucket}"
        for name, os_ in (sub.get("ops") or {}).items():
            layer = by_name.get(name)
            for i, sp in enumerate(os_.get("outputs") or ()):
                if sp is None:
                    continue
                shape = None
                if layer is not None and i < len(layer.outputs):
                    shape = layer.outputs[i].shape
                _check_spec(report, axis_sizes, name,
                            f"serving[{ctx}] output[{i}]",
                            _json_spec(sp), shape)
            wsh = {w.name: tuple(w.shape)
                   for w in (getattr(layer, "weights", None) or ())}
            for wname, sp in (os_.get("weights") or {}).items():
                if sp is None:
                    continue
                _check_spec(report, axis_sizes, name,
                            f"serving[{ctx}] weight {wname!r}",
                            _json_spec(sp), wsh.get(wname),
                            seam="checkpoint-restore")
        for name, kv in (sub.get("kv") or {}).items():
            kv = kv or {}
            deg = int(kv.get("shard_degree") or 1)
            kvh = int(kv.get("num_kv_heads") or 0)
            hd = int(kv.get("head_dim") or 0)
            if by_name and name not in by_name:
                report.add("serving", "error", name,
                           f"serving[{ctx}]: KV entry names a layer "
                           f"absent from the program", "serving-kv")
                continue
            if deg < 1 or kvh <= 0 or kvh % deg != 0:
                report.add(
                    "serving", "error", name,
                    f"serving[{ctx}]: KV shard degree {deg} does not "
                    f"divide num_kv_heads {kvh} — a decode step cannot "
                    f"split a KV head across devices", "serving-kv")
                continue
            sdeg = int(kv.get("seq_shard_degree") or 1)
            if sdeg > 1:
                # seq-sharded KV only executes on a mesh whose sequence
                # axis carries the degree: the decode-step combine is a
                # ppermute rotation OVER that axis
                mesh_seq = int(axis_sizes.get("seq", 1) or 1)
                if mesh_seq % sdeg != 0 or mesh_seq < sdeg:
                    report.add(
                        "serving", "error", name,
                        f"serving[{ctx}]: KV seq shard degree {sdeg} "
                        f"needs a mesh sequence axis of that degree "
                        f"(mesh has seq={mesh_seq})", "serving-kv")
                    continue
                if max_seq and max_seq % sdeg != 0:
                    report.add(
                        "serving", "error", name,
                        f"serving[{ctx}]: KV seq shard degree {sdeg} "
                        f"does not divide max_seq {max_seq}",
                        "serving-kv")
                    continue
            want = (2 * bucket * max_seq * kvh * hd * 4) \
                // (deg * max(sdeg, 1))
            got = int(kv.get("bytes") or 0)
            if got and hd and got != want:
                report.add(
                    "serving", "error", name,
                    f"serving[{ctx}]: recorded KV bytes {got} disagree "
                    f"with the geometry 2*{bucket}*{max_seq}*{kvh}*"
                    f"{hd}*4/({deg}*{sdeg}) = {want}", "serving-kv")
    # decode-resident envelope at the LARGEST bucket. Needs the layer
    # list for weight/output shapes; spec-only strategy files verify
    # structurally above and skip the gate.
    if not hbm_bytes:
        hbm_bytes = getattr(spec, "hbm_bytes", None)
    if not by_name or not hbm_bytes:
        return
    bucket = max(buckets)
    env = serving_envelope(buckets[bucket], bucket, by_name, axis_sizes)
    total = env["envelope_bytes"]
    act_op = env["peak_activation_op"]
    if total > hbm_bytes:
        report.add(
            "serving", "error", act_op or "<model>",
            f"serving envelope at bucket {bucket} "
            f"{total / 2**20:.1f} MiB exceeds the machine model's "
            f"{hbm_bytes / 2**20:.1f} MiB HBM (weights "
            f"{env['weights_bytes'] / 2**20:.1f} MiB + KV cache "
            f"{env['kv_bytes'] / 2**20:.1f} MiB + 2 x peak activation "
            f"{env['peak_activation_bytes'] / 2**20:.1f} MiB [{act_op}])"
            f" — shard the KV cache (head-parallel attention, or "
            f"seq-sharded KV on a sequence-axis mesh) or drop the "
            f"bucket", "serving-memory")


def serving_envelope(sub: Dict, bucket: int, by_name: Dict,
                     axis_sizes: Dict[str, int]) -> Dict[str, float]:
    """Decode-resident per-device envelope of ONE bucket's serving
    sub-strategy (``ServingPlan.to_block()`` bucket form): sharded
    weights + resident KV cache + a live fwd activation pair, with
    activations rescaled from the compile batch to the bucket. No
    grads/optimizer terms — serving is forward-only. Shared by
    ``_check_serving``'s HBM gate and the serving search/smoke, so a
    plan adopted by the search verifies against the same arithmetic."""
    from ..dtypes import itemsize as _isz
    ops_doc = sub.get("ops") or {}
    params_local = 0.0
    kv_local = float(sum(int((kv or {}).get("bytes") or 0)
                         for kv in (sub.get("kv") or {}).values()))
    act_peak, act_op = 0.0, ""
    for name, layer in by_name.items():
        os_ = ops_doc.get(name) or {}
        wspecs = os_.get("weights") or {}
        for w in layer.weights or ():
            total = float(int(np.prod(w.shape)) or 1) * _isz(w.dtype)
            sp = wspecs.get(w.name)
            deg = _spec_degree(_json_spec(sp), axis_sizes) if sp else 1
            params_local += total / max(deg, 1)
        outs = os_.get("outputs") or ()
        local = 0.0
        for i, t in enumerate(layer.outputs):
            total = float(int(np.prod(t.shape)) or 1) * _isz(t.dtype)
            if t.shape and t.shape[0]:
                # activations were shaped at the compile batch; the
                # serving bucket is what is live at runtime
                total *= bucket / float(t.shape[0])
            sp = outs[i] if i < len(outs) else None
            deg = _spec_degree(_json_spec(sp), axis_sizes) if sp else 1
            local += total / max(deg, 1)
        if local > act_peak:
            act_peak, act_op = local, name
    return {
        "weights_bytes": params_local,
        "kv_bytes": kv_local,
        "peak_activation_bytes": act_peak,
        "peak_activation_op": act_op,
        "envelope_bytes": params_local + kv_local + 2 * act_peak,
    }


def verify_serving_plan(plan, layers: Sequence, dmesh, *,
                        hbm_bytes: Optional[float] = None,
                        context: str = "") -> PlanReport:
    """Verify a searched :class:`~flexflow_tpu.search.serving_plan.
    ServingPlan` (or its serialized ``serving`` block) against the
    program and mesh it was searched for. Raises a typed
    :class:`PlanVerificationError` on error findings — called by
    ``optimize_serving_strategy`` before a plan is exported and by the
    serving smoke gate."""
    t0 = time.perf_counter()
    report = PlanReport()
    axis_sizes: Dict[str, int] = dict(getattr(dmesh, "axis_sizes", {}))
    spec = getattr(dmesh, "spec", None)
    by_name = {l.name: l for l in layers}
    block = plan.to_block() if hasattr(plan, "to_block") else dict(plan)
    _check_serving(report, block, by_name, axis_sizes, spec, hbm_bytes)
    report.duration_s = time.perf_counter() - t0
    REGISTRY.counter("ff_plan_verify_runs_total",
                     "Static plan verification passes").inc()
    for f in report.findings:
        REGISTRY.counter("ff_plan_verify_findings_total",
                         "Plan verification findings by check"
                         ).inc(check=f.check)
    obs_events.record_span("plan_verify.serving", t0, report.duration_s,
                           findings=len(report.findings),
                           errors=len(report.errors),
                           context=context or "")
    report.raise_if_failed(context or "the serving plan")
    return report


def verify_model(model) -> PlanReport:
    """Verify a compiled-to-the-strategy :class:`FFModel` (called from
    ``FFModel.compile`` post-search). Raises
    :class:`PlanVerificationError` on error findings; appends the report
    to the strategy audit record when the search wrote one."""
    program = model.executor.program
    cfg = model.config
    hbm = None
    if getattr(cfg, "device_mem_mb", 0):
        hbm = float(cfg.device_mem_mb) * (1 << 20)
    report = verify_plan(
        model.strategy, program.layers,
        machine_spec=model.dmesh.spec,
        graph_inputs=model.graph_inputs,
        optimizer=model.optimizer,
        hbm_bytes=hbm,
        context="FFModel.compile")
    audit_path = getattr(model, "_strategy_audit_path", None)
    if audit_path:
        from ..obs.audit import annotate_strategy_audit
        annotate_strategy_audit(audit_path,
                                {"plan_verify": report.to_json()})
    report.raise_if_failed("the compiled strategy")
    return report


def verify_strategy_file(path: str, doc: Optional[Dict] = None
                         ) -> PlanReport:
    """Structural verification of a saved strategy JSON (``ffcheck
    --verify-strategies``): mesh-axis soundness of every recorded spec,
    bank/place-group divisibility, and — when the file carries the
    searched program — full shape-level divisibility via the recorded
    layer list. No devices are touched. ``doc`` skips re-parsing when
    the caller already holds the loaded JSON."""
    import json

    t0 = time.perf_counter()
    if doc is None:
        with open(path) as f:
            doc = json.load(f)
    report = PlanReport()
    axis_sizes = {str(k): int(v)
                  for k, v in (doc.get("mesh_axes") or {}).items()}
    if not axis_sizes:
        report.add("op-shard", "error", path,
                   "strategy file has no mesh_axes section")
        report.duration_s = time.perf_counter() - t0
        return report
    # shapes from the serialized program, when present (output shapes
    # re-inferred through the op registry; inputs are name-only in the
    # wire format, so input tensors are synthesized unconstrained)
    out_shapes: Dict[str, List[Tuple[int, ...]]] = {}
    weight_shapes: Dict[str, Dict[str, Tuple[int, ...]]] = {}
    prog = doc.get("program")
    if prog:
        try:
            out_shapes, weight_shapes = _program_shapes(prog)
        except Exception as e:  # noqa: BLE001 — degrade to spec-only
            report.add("op-shard", "warn", path,
                       f"could not reconstruct program shapes ({e}); "
                       f"verifying specs without divisibility")
    for name, os_ in (doc.get("ops") or {}).items():
        for i, sp in enumerate(os_.get("outputs") or ()):
            if sp is None:
                continue
            shape = None
            shapes = out_shapes.get(name)
            if shapes and i < len(shapes):
                shape = shapes[i]
            _check_spec(report, axis_sizes, name, f"output[{i}]",
                        _json_spec(sp), shape)
        for wname, sp in (os_.get("weights") or {}).items():
            if sp is None:
                continue
            _check_spec(report, axis_sizes, name, f"weight {wname!r}",
                        _json_spec(sp),
                        weight_shapes.get(name, {}).get(wname),
                        seam="checkpoint-restore")
    for tname, sp in (doc.get("inputs") or {}).items():
        if sp is not None:
            _check_spec(report, axis_sizes, tname, "input",
                        _json_spec(sp), None)
    for b in doc.get("banks") or ():
        K = len(b.get("members") or ())
        B = 1
        bad = []
        for a in b.get("axes") or ():
            if a not in axis_sizes:
                bad.append(a)
            B *= axis_sizes.get(a, 1)
        name = f"bank[{'+'.join((b.get('members') or ['?'])[:2])}]"
        if bad:
            report.add("seam", "error", name,
                       f"bank axes {bad} are not mesh axes",
                       "bank-boundary")
        if K and K % max(B, 1) != 0:
            report.add("seam", "error", name,
                       f"bank degree {B} does not divide member count "
                       f"{K}", "bank-boundary")
    # placement annotations (axis_tiers / collective_trees): tier
    # soundness, tree-phase coverage, and the latency-bound-across-DCN
    # rejection — the machine constants come from the file's meta block
    # when present, else the MachineSpec defaults
    spec = None
    meta = doc.get("meta") or {}
    if meta.get("machine_file"):
        try:
            from ..parallel.machine import MachineSpec
            spec = MachineSpec.from_file(meta["machine_file"])
        except Exception:  # noqa: BLE001 — fall to defaults
            spec = None
    _check_placement(report, doc.get("axis_tiers") or {},
                     doc.get("collective_trees") or (), axis_sizes,
                     spec)
    # subset-group membership, shared by the zero check (unaddressable
    # state) and the overlap check (divergent bucket launch) — ONE walk
    # so a future group kind cannot go missing from one of them
    grouped: Dict[str, str] = {}
    for b in doc.get("banks") or ():
        for m in b.get("members") or ():
            grouped[m] = "bank"
    for g in doc.get("place_groups") or ():
        for m in g.get("members") or ():
            grouped[m] = "place-group"
    # per-parameter ZeRO assignment (doc["zero"]): axis soundness,
    # divisibility (when the program's weight shapes are known), and
    # the weight-axis-overlap rejection
    zdoc = doc.get("zero")
    if zdoc:
        from ..runtime.zero import ZeroAssignment
        w_specs = {
            name: {w: _json_spec(s)
                   for w, s in (os_.get("weights") or {}).items()
                   if s is not None}
            for name, os_ in (doc.get("ops") or {}).items()}
        _check_zero(report, ZeroAssignment.from_json(zdoc), w_specs,
                    weight_shapes, axis_sizes,
                    have_layers=bool(weight_shapes),
                    unaddressable=grouped)
    # quantized grad-sync plan (doc["qsync"]): wire/tier soundness,
    # the quantized-phase-on-declared-tier rule, and the replicated-
    # math-seam rejection (sharded weights stay full-precision)
    qdoc = doc.get("qsync")
    if qdoc:
        w_specs = {
            name: {w: _json_spec(s)
                   for w, s in (os_.get("weights") or {}).items()
                   if s is not None}
            for name, os_ in (doc.get("ops") or {}).items()}
        _check_qsync(report, qdoc, doc.get("axis_tiers") or {},
                     w_specs, axis_sizes,
                     have_layers=bool(weight_shapes),
                     known_layers=set(weight_shapes),
                     unaddressable=grouped)
    # overlapped grad-sync schedule (doc["overlap"]): launch-order
    # totality, member disjointness/subset-group exclusion, and — when
    # the file carries the serialized program — backward-completion
    # order consistency via the recorded layer order
    ovdoc = doc.get("overlap")
    if ovdoc:
        prog_layers = (prog or {}).get("layers") or ()
        pos = {ls["name"]: i for i, ls in enumerate(prog_layers)}
        op_types = {}
        from ..ffconst import OperatorType
        for ls in prog_layers:
            try:
                op_types[ls["name"]] = OperatorType[ls["op_type"]]
            except KeyError:
                op_types[ls["name"]] = None
        _check_overlap(report, ovdoc, grouped=grouped, pos=pos,
                       op_types=op_types, have_layers=bool(op_types))
    # per-op kernel implementations (doc["kernel_impls"]): registered
    # impl names + availability predicates on the recorded mesh/shapes;
    # 'ring' without a seq axis in mesh_axes is the pinned rejection
    kdoc = doc.get("kernel_impls")
    if kdoc:
        from ..kernels import registry as kreg
        attn_ctxs: Dict[str, Dict[str, Any]] = {}
        known: set = set()
        prog_layers = (prog or {}).get("layers") or ()
        if prog_layers:
            from ..search.serialization import _param_from_json
            for ls in prog_layers:
                known.add(ls["name"])
                if ls.get("op_type") != "OP_MULTIHEAD_ATTENTION":
                    continue
                try:
                    params = {k: _param_from_json(v)
                              for k, v in ls.get("params", {}).items()}
                    shapes = out_shapes.get(ls["name"])
                    q_len = int(shapes[0][1]) \
                        if shapes and len(shapes[0]) > 1 else 0
                    attn_ctxs[ls["name"]] = kreg.attention_ctx(
                        params, q_len, q_len,
                        seq_degree=axis_sizes.get("seq", 0))
                except Exception:  # noqa: BLE001 — shape unknown ≠ unsound
                    # minimal ctx: mesh-level predicates (the ring seq
                    # axis) still bind; shape-level ones pass open
                    attn_ctxs[ls["name"]] = kreg.attention_ctx(
                        {}, 0, 0, seq_degree=axis_sizes.get("seq", 0))
        _check_kernel(report, kdoc, axis_sizes, attn_ctxs,
                      have_layers=bool(prog_layers),
                      known_layers=known)
    # per-(model, batch-class) serving block (doc["serving"]): bucket
    # structure, per-bucket spec soundness, and KV-shard/GQA
    # divisibility — the envelope gate needs live layer shapes and is
    # enforced at compile/search time instead
    sdoc = doc.get("serving")
    if sdoc:
        _check_serving(report, sdoc, {}, axis_sizes, spec, None)
    report.duration_s = time.perf_counter() - t0
    return report


def _json_spec(j):
    """JSON spec form → PartitionSpec-like tuple (no jax import)."""
    return tuple(tuple(e) if isinstance(e, list) else e for e in j)


def _program_shapes(prog):
    """Re-infer every recorded layer's output + weight shapes from a
    serialized program (search/serialization.program_to_json form).
    Graph inputs carry no shapes in the wire format, so layers whose
    inputs reach back to them are skipped (shape unknown ≠ unsound)."""
    from ..ffconst import OperatorType
    from ..ops import get_op_def
    out_shapes: Dict[str, List[Tuple[int, ...]]] = {}
    out_dtypes: Dict[str, List[Any]] = {}
    weight_shapes: Dict[str, Dict[str, Tuple[int, ...]]] = {}
    from ..search.serialization import _param_from_json
    for ls in prog.get("layers", ()):
        shapes, dtypes = [], []
        known = True
        for ref in ls["inputs"]:
            if "op" in ref and ref["op"] in out_shapes:
                src_shapes = out_shapes[ref["op"]]
                src_dtypes = out_dtypes[ref["op"]]
                if ref["idx"] < len(src_shapes):
                    shapes.append(src_shapes[ref["idx"]])
                    dtypes.append(src_dtypes[ref["idx"]])
                    continue
            known = False
            break
        if not known:
            continue
        try:
            params = {k: _param_from_json(v)
                      for k, v in ls["params"].items()}
            op = get_op_def(OperatorType[ls["op_type"]])
            outs = op.infer(params, shapes, dtypes)
            out_shapes[ls["name"]] = [tuple(s) for s, _ in outs]
            out_dtypes[ls["name"]] = [d for _, d in outs]
            weight_shapes[ls["name"]] = {
                w.name: tuple(w.shape)
                for w in op.weights(params, shapes, dtypes) or ()}
        except Exception:  # noqa: BLE001 — unknown op: skip its shapes
            continue
    return out_shapes, weight_shapes
