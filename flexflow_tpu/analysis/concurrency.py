"""Lock-discipline + thread-lifecycle static analysis (ffcheck v2).

The runtime grew hand-rolled threads (heartbeat daemons, async
checkpoint writers, the serving scheduler workers, the obs ring) whose
safety rested on convention; every recent PR's review-hardening pass
found a lock race by hand (PR 7's ``_scan_peers`` peer-table race, PR
5's drain-vs-unload snapshot). This engine proves the conventions — or
names the line that breaks them:

  ``guarded-field``
      Per-class (and per-module, for module-global state like
      ``obs/events.py``'s ring) inference of lock-guarded attributes: a
      field WRITTEN at least once while holding a lock (outside
      ``__init__``/module top level) is *guarded* by that lock, and
      every other access — read or write, including container mutators
      like ``.append``/``.clear`` and item assignment — must hold it.
      Accesses through a same-module instance attribute resolve
      cross-object (``self.breaker.state`` is checked against
      ``CircuitBreaker``'s discipline). Methods named ``*_locked``
      are assumed to run with their scope's locks held (the repo's
      existing convention, e.g. ``events._reset_locked``).
  ``lock-order``
      A cross-module lock-acquisition-order graph: acquiring lock B
      while holding lock A adds edge A→B, including acquisitions
      reached through statically-resolvable calls (``self.m()``,
      ``module.f()``, ``instance.m()`` — conservative: unresolvable
      calls add nothing). Any cycle is a potential deadlock; a
      non-reentrant lock re-acquired while held is a self-cycle.
  ``thread-lifecycle``
      Every ``threading.Thread`` constructed must be ``daemon=True``
      at construction (or via a ``.daemon = True`` assignment on its
      binding) or joined with a timeout somewhere in its owning scope
      — a non-daemon, never-joined thread blocks interpreter exit and
      leaks on unload.
  ``unbounded-wait``
      ``Event.wait()`` / ``Condition.wait()`` / ``Thread.join()``
      without a bound, on receivers *typed* by construction-site
      inference (``self._stop = threading.Event()``, annotations,
      cross-object attrs) — the class-sharpened, repo-wide form of the
      linter's name-heuristic ``raw-wait`` rule.

Locks are identified per (module, class, attribute); two instances of
one class share an identity — sound for the singleton/worker-pool
shapes this repo uses. Suppression: the shared ``# ffcheck:
ok(<rule>)`` pragma with a one-line justification comment (policy in
``docs/static_analysis.md``). Findings carry the owning symbol
(``Class.method``) for stable IDs in the schema-2 JSON report.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import _modgraph as mg
from .lint import LintFinding, _pragmas, _suppressed

__all__ = ["CONCURRENCY_RULES", "analyze_paths", "analyze_sources"]

CONCURRENCY_RULES: Dict[str, str] = {
    "guarded-field": "lock-guarded attribute accessed without its lock",
    "lock-order": "lock-acquisition-order cycle (potential deadlock)",
    "thread-lifecycle": "thread neither daemon nor joined with a timeout",
    "unbounded-wait": "unbounded wait on a typed Event/Condition/Thread",
}

LockId = Tuple[str, Optional[str], str]     # (module, class|None, attr)
FieldKey = Tuple[str, Optional[str], str]


def _lock_sort(lock: LockId):
    return (lock[0], lock[1] or "", lock[2])


def _lock_name(lock: LockId) -> str:
    mod, cls, attr = lock
    short = mod.rsplit(".", 1)[-1]
    return f"{short}.{cls}.{attr}" if cls else f"{short}.{attr}"


class _Access:
    __slots__ = ("field", "kind", "held", "node", "in_init", "fn")

    def __init__(self, field: FieldKey, kind: str, held: frozenset,
                 node: ast.AST, in_init: bool, fn: mg.FuncInfo):
        self.field = field
        self.kind = kind
        self.held = held
        self.node = node
        self.in_init = in_init
        self.fn = fn


class _FuncFacts:
    def __init__(self, fn: mg.FuncInfo):
        self.fn = fn
        self.accesses: List[_Access] = []
        # (lock_id, kind, held-before, node)
        self.acquires: List[Tuple[LockId, str, frozenset, ast.AST]] = []
        # (callee FuncInfo, held, node)
        self.calls: List[Tuple[mg.FuncInfo, frozenset, ast.AST]] = []
        # (node, sync kind, bounded, receiver description)
        self.waits: List[Tuple[ast.AST, str, bool, str]] = []
        # (node, daemon-at-ctor, binding) binding: ("attr", attr) |
        # ("local", name) | None
        self.threads: List[Tuple[ast.Call, bool,
                                 Optional[Tuple[str, str]]]] = []


def _initial_held(pkg: mg.Package, fn: mg.FuncInfo) -> Set[LockId]:
    """``*_locked`` helpers run with their scope's locks held (repo
    convention; enforced at the call sites by the same analysis)."""
    if not fn.name.endswith("_locked"):
        return set()
    held: Set[LockId] = set()
    scope_sync = fn.cls.sync if fn.cls is not None else fn.module.sync
    owner = fn.cls.name if fn.cls is not None else None
    for attr, kind in scope_sync.items():
        if kind in mg.ACQUIRABLE:
            held.add((fn.module.dotted, owner, attr))
    return held


class _FnWalker:
    """One function's lock-held dataflow walk."""

    def __init__(self, pkg: mg.Package, fn: mg.FuncInfo):
        self.pkg = pkg
        self.fn = fn
        self.facts = _FuncFacts(fn)
        self.in_init = fn.name == "__init__"
        self._pending_acq: List[LockId] = []
        self._pending_rel: List[LockId] = []
        self.locals: Dict[str, object] = {}
        # (field, line) -> index into facts.accesses (one access per
        # field per line; a mutator call upgrades the base read to 'w')
        self._seen_access: Dict[Tuple[FieldKey, int], int] = {}
        self._collect_locals(fn.node)

    # -- local typing --------------------------------------------------
    def _collect_locals(self, node) -> None:
        args = node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self.locals[a.arg] = None
        if self.fn.cls is not None and "self" in self.locals:
            self.locals["self"] = ("instance", self.fn.cls)
        globals_: Set[str] = set()
        for sub in self._own_nodes(node):
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                globals_.update(sub.names)
        for sub in self._own_nodes(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    self._bind_target(t, sub.value, globals_)
            elif isinstance(sub, ast.AnnAssign) and isinstance(
                    sub.target, ast.Name):
                if sub.target.id not in globals_:
                    kind = mg.sync_kind_of_call(sub.value) \
                        or mg.sync_kind_of_annotation(sub.annotation)
                    self.locals[sub.target.id] = (
                        ("sync", kind, None) if kind else None)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                self._bind_target(sub.target, None, globals_)
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    if item.optional_vars is not None:
                        self._bind_target(item.optional_vars, None,
                                          globals_)
            elif isinstance(sub, ast.ExceptHandler) and sub.name:
                self.locals[sub.name] = None
            elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                for gen in sub.generators:
                    self._bind_target(gen.target, None, globals_)
            elif isinstance(sub, ast.NamedExpr) and isinstance(
                    sub.target, ast.Name):
                if sub.target.id not in globals_:
                    self.locals.setdefault(sub.target.id, None)

    def _own_nodes(self, fn_node):
        """All nodes of this function EXCLUDING nested function bodies
        (they are separate FuncInfos with their own walk)."""
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _bind_target(self, target, value, globals_: Set[str]) -> None:
        if isinstance(target, ast.Tuple):
            for e in target.elts:
                self._bind_target(e, None, globals_)
            return
        if not isinstance(target, ast.Name) or target.id in globals_:
            return
        typed = None
        if value is not None:
            kind = mg.sync_kind_of_call(value)
            if kind is not None:
                typed = ("sync", kind, None)  # fresh local sync object
            else:
                typed = self.pkg.resolve_value(self.fn, value,
                                               self.locals)
        prev = self.locals.get(target.id)
        # keep the first informative binding (t = self._thread; t = None)
        if prev is None or target.id not in self.locals:
            self.locals[target.id] = typed

    # -- walk ----------------------------------------------------------
    def run(self) -> _FuncFacts:
        held = frozenset(_initial_held(self.pkg, self.fn))
        self._walk(self.fn.node.body, held)
        return self.facts

    def _resolve(self, expr):
        return self.pkg.resolve_value(self.fn, expr, self.locals)

    def _walk(self, stmts: Sequence[ast.stmt], held: frozenset) -> None:
        held = set(held)
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                newly: List[LockId] = []
                for item in st.items:
                    r = self._resolve(item.context_expr)
                    if r is not None and r[0] == "sync" \
                            and r[1] in mg.ACQUIRABLE \
                            and r[2] is not None:
                        self.facts.acquires.append(
                            (r[2], r[1], frozenset(held),
                             item.context_expr))
                        newly.append(r[2])
                    else:
                        self._expr(item.context_expr, frozenset(held))
                self._walk(st.body, frozenset(held) | set(newly))
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in st.decorator_list:
                    self._expr(dec, frozenset(held))
            elif isinstance(st, ast.ClassDef):
                pass  # classes inside functions: out of scope
            elif isinstance(st, ast.If):
                self._expr(st.test, frozenset(held))
                self._walk(st.body, frozenset(held))
                self._walk(st.orelse, frozenset(held))
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._expr(st.iter, frozenset(held))
                self._walk(st.body, frozenset(held))
                self._walk(st.orelse, frozenset(held))
            elif isinstance(st, ast.While):
                self._expr(st.test, frozenset(held))
                self._walk(st.body, frozenset(held))
                self._walk(st.orelse, frozenset(held))
            elif isinstance(st, ast.Try) or st.__class__.__name__ == \
                    "TryStar":
                self._walk(st.body, frozenset(held))
                for h in st.handlers:
                    if h.type is not None:
                        self._expr(h.type, frozenset(held))
                    self._walk(h.body, frozenset(held))
                self._walk(st.orelse, frozenset(held))
                self._walk(st.finalbody, frozenset(held))
            elif st.__class__.__name__ == "Match":
                self._expr(st.subject, frozenset(held))
                for case in st.cases:
                    self._walk(case.body, frozenset(held))
            else:
                acq, rel = self._stmt_exprs(st, frozenset(held))
                held |= set(acq)
                held -= set(rel)

    def _stmt_exprs(self, st: ast.stmt, held: frozenset
                    ) -> Tuple[List[LockId], List[LockId]]:
        """Visit a simple statement's expressions; returns explicit
        ``.acquire()``/``.release()`` lock-id lists (held state for the
        REST of the enclosing block — coarse but sound enough)."""
        self._pending_acq = []
        self._pending_rel = []
        if isinstance(st, ast.Assign):
            self._maybe_thread_binding(st.targets, st.value)
            for t in st.targets:
                self._expr(t, held)
            self._expr(st.value, held)
        elif isinstance(st, ast.AnnAssign):
            self._maybe_thread_binding([st.target], st.value)
            self._expr(st.target, held)
            if st.value is not None:
                self._expr(st.value, held)
        elif isinstance(st, ast.AugAssign):
            self._expr(st.target, held, force_write=True)
            self._expr(st.value, held)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, held)
        return self._pending_acq, self._pending_rel

    # -- thread constructions ------------------------------------------
    def _maybe_thread_binding(self, targets, value) -> None:
        if value is None:
            return
        ctors = [c for c in ast.walk(value) if isinstance(c, ast.Call)
                 and mg.sync_kind_of_call(c) == "thread"]
        if not ctors:
            return
        binding: Optional[Tuple[str, str]] = None
        for t in targets:
            if isinstance(t, ast.Name):
                binding = ("local", t.id)
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                binding = ("attr", t.attr)
        for c in ctors:
            self.facts.threads.append((c, _ctor_daemon(c), binding))

    # -- expressions ---------------------------------------------------
    def _record_access(self, field: FieldKey, kind: str, held: frozenset,
                       node: ast.AST) -> None:
        key = (field, getattr(node, "lineno", 0))
        idx = self._seen_access.get(key)
        if idx is not None:
            if kind == "w":
                self.facts.accesses[idx].kind = "w"
            return
        self._seen_access[key] = len(self.facts.accesses)
        self.facts.accesses.append(
            _Access(field, kind, held, node, self.in_init, self.fn))

    def _field_of_attribute(self, node: ast.Attribute
                            ) -> Optional[FieldKey]:
        base = self._resolve(node.value)
        if base is None:
            return None
        if base[0] == "instance":
            ci: mg.ClassInfo = base[1]
            if node.attr in ci.methods or node.attr in ci.sync:
                return None
            return (ci.module.dotted, ci.name, node.attr)
        if base[0] == "module":
            # cross-module global access (mod._x) joins mod's own
            # discipline — e.g. a package __init__ poking a submodule's
            # guarded state
            m: mg.ModuleInfo = base[1]
            if node.attr in m.toplevel and node.attr not in m.sync \
                    and node.attr not in m.functions \
                    and node.attr not in m.classes \
                    and node.attr not in m.imports_mod \
                    and node.attr not in m.imports_sym:
                return (m.dotted, None, node.attr)
        return None

    def _field_of_name(self, node: ast.Name) -> Optional[FieldKey]:
        if node.id in self.locals:
            return None
        mod = self.fn.module
        if node.id not in mod.toplevel or node.id in mod.sync \
                or node.id in mod.functions or node.id in mod.classes \
                or node.id in mod.imports_mod \
                or node.id in mod.imports_sym:
            return None
        return (mod.dotted, None, node.id)

    def _expr(self, node: ast.AST, held: frozenset,
              force_write: bool = False) -> None:
        if node is None:
            return
        if isinstance(node, ast.Attribute):
            field = self._field_of_attribute(node)
            if field is not None:
                kind = "w" if force_write or isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "r"
                self._record_access(field, kind, held, node)
            self._expr(node.value, held)
            return
        if isinstance(node, ast.Name):
            field = self._field_of_name(node)
            if field is not None:
                kind = "w" if force_write or isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "r"
                self._record_access(field, kind, held, node)
            return
        if isinstance(node, ast.Subscript):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._expr(node.value, held, force_write=write)
            self._expr(node.slice, held)
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, ast.Lambda):
            # executed later in principle; in practice this repo's
            # lambdas are local-only — walked with the current held set
            self._expr(node.body, held)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword,
                                  ast.comprehension)):
                self._expr(child, held)

    def _call(self, node: ast.Call, held: frozenset) -> None:
        fnx = node.func
        if isinstance(fnx, ast.Attribute):
            base_r = self._resolve(fnx.value)
            # explicit acquire()/release()
            if base_r is not None and base_r[0] == "sync" \
                    and base_r[1] in mg.ACQUIRABLE \
                    and base_r[2] is not None:
                if fnx.attr == "acquire":
                    self.facts.acquires.append(
                        (base_r[2], base_r[1], held, node))
                    self._pending_acq.append(base_r[2])
                elif fnx.attr == "release":
                    self._pending_rel.append(base_r[2])
            # typed waits
            if base_r is not None and base_r[0] == "sync":
                skind = base_r[1]
                if (fnx.attr in ("wait", "wait_for")
                        and skind in ("event", "condition")) \
                        or (fnx.attr == "join" and skind == "thread"):
                    bounded = mg.call_is_bounded(node)
                    if fnx.attr == "wait_for":
                        # wait_for(pred) — only a timeout kwarg or a
                        # SECOND positional bounds it
                        bounded = len(node.args) >= 2 or bool(
                            {k.arg for k in node.keywords if k.arg}
                            & mg.TIMEOUT_KWARGS)
                    self.facts.waits.append(
                        (node, skind, bounded,
                         mg.attr_chain(fnx) or fnx.attr))
            # container mutators on fields = writes
            if fnx.attr in mg.MUTATORS:
                f = None
                if isinstance(fnx.value, ast.Attribute):
                    f = self._field_of_attribute(fnx.value)
                elif isinstance(fnx.value, ast.Name):
                    f = self._field_of_name(fnx.value)
                if f is not None:
                    self._record_access(f, "w", held, node)
        # unbound thread construction (bound ones recorded at Assign)
        if mg.sync_kind_of_call(node) == "thread" and not any(
                node is c for c, _, _ in self.facts.threads):
            self.facts.threads.append((node, _ctor_daemon(node), None))
        callee = self.pkg.resolve_callee(self.fn, node, self.locals)
        if callee is not None:
            self.facts.calls.append((callee, held, node))
        self._expr(fnx, held)
        for a in node.args:
            self._expr(a, held)
        for k in node.keywords:
            self._expr(k.value, held)


def _ctor_daemon(call: ast.Call) -> bool:
    for k in call.keywords:
        if k.arg == "daemon" and isinstance(k.value, ast.Constant):
            return bool(k.value.value)
    return False


# ---------------------------------------------------------------------------
# package-level analysis
# ---------------------------------------------------------------------------

class _Analysis:
    def __init__(self, pkg: mg.Package):
        self.pkg = pkg
        self.facts: Dict[int, _FuncFacts] = {}
        for mod in pkg.modules.values():
            for fi in mod.all_functions:
                self.facts[id(fi)] = _FnWalker(pkg, fi).run()

    # -- guarded-field -------------------------------------------------
    def guarded_field_findings(self) -> List[LintFinding]:
        fields: Dict[FieldKey, List[_Access]] = {}
        for facts in self.facts.values():
            for a in facts.accesses:
                if a.field[2].startswith("__"):
                    continue
                fields.setdefault(a.field, []).append(a)
        out: List[LintFinding] = []
        for field, accs in fields.items():
            locked_writes = [a for a in accs
                             if a.kind == "w" and a.held
                             and not a.in_init]
            if not locked_writes:
                continue
            guards = frozenset.intersection(
                *[a.held for a in locked_writes])
            if not guards:
                # written under DIFFERENT locks in different places —
                # fall back to the union (lenient: any of them counts)
                guards = frozenset().union(
                    *[a.held for a in locked_writes])
            owner = f"{field[1]}." if field[1] else ""
            lock_names = "/".join(sorted(_lock_name(g) for g in guards))
            n_locked = len([a for a in accs if a.held])
            for a in accs:
                if a.in_init or (a.held & guards):
                    continue
                what = "written" if a.kind == "w" else "read"
                out.append(_finding(
                    "guarded-field", a.fn, a.node,
                    f"{owner}{field[2]} is guarded by {lock_names} "
                    f"({n_locked} locked access(es), incl. writes) but "
                    f"{what} here without it; hold the lock or pragma "
                    f"with a justification"))
        return out

    # -- lock-order ----------------------------------------------------
    def _transitive_acquires(self) -> Dict[int, Set[LockId]]:
        summary: Dict[int, Set[LockId]] = {
            fid: {lock for lock, _, _, _ in facts.acquires}
            for fid, facts in self.facts.items()}
        changed = True
        while changed:
            changed = False
            for fid, facts in self.facts.items():
                cur = summary[fid]
                for callee, _, _ in facts.calls:
                    extra = summary.get(id(callee))
                    if extra and not extra <= cur:
                        cur |= extra
                        changed = True
        return summary

    def lock_order_findings(self) -> List[LintFinding]:
        summary = self._transitive_acquires()
        kinds: Dict[LockId, str] = {}
        # edge -> (fn, node) first site
        edges: Dict[Tuple[LockId, LockId],
                    Tuple[mg.FuncInfo, ast.AST]] = {}
        self_deadlocks: List[Tuple[LockId, mg.FuncInfo, ast.AST]] = []
        for facts in self.facts.values():
            for lock, kind, held, node in facts.acquires:
                kinds.setdefault(lock, kind)
                for h in held:
                    if h == lock:
                        if kind == "lock":
                            self_deadlocks.append((lock, facts.fn, node))
                    else:
                        edges.setdefault((h, lock), (facts.fn, node))
            for callee, held, node in facts.calls:
                if not held:
                    continue
                for lock in summary.get(id(callee), ()):
                    for h in held:
                        if h == lock:
                            if kinds.get(lock, "lock") == "lock":
                                self_deadlocks.append(
                                    (lock, facts.fn, node))
                        else:
                            edges.setdefault((h, lock),
                                             (facts.fn, node))
        out: List[LintFinding] = []
        for lock, fn, node in self_deadlocks:
            out.append(_finding(
                "lock-order", fn, node,
                f"non-reentrant {_lock_name(lock)} re-acquired while "
                f"already held — guaranteed self-deadlock (use an "
                f"RLock or a *_locked helper)"))
        graph: Dict[LockId, Set[LockId]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for cycle in _cycles(graph):
            pretty = " -> ".join(_lock_name(l) for l in cycle) \
                + f" -> {_lock_name(cycle[0])}"
            sites = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                fn, node = edges[(a, b)]
                sites.append(f"{mg.stable_path(fn.module.path)}:"
                             f"{getattr(node, 'lineno', 0)}")
            fn0, node0 = edges[(cycle[0], cycle[1 % len(cycle)])]
            out.append(_finding(
                "lock-order", fn0, node0,
                f"lock-order cycle {pretty} (acquisition sites: "
                f"{', '.join(sites)}) — threads taking the locks in "
                f"opposite orders deadlock"))
        return out

    # -- thread-lifecycle ----------------------------------------------
    def thread_lifecycle_findings(self) -> List[LintFinding]:
        out: List[LintFinding] = []
        for facts in self.facts.values():
            fn = facts.fn
            for node, daemon, binding in facts.threads:
                if daemon:
                    continue
                if binding is not None and self._binding_managed(
                        fn, binding):
                    continue
                where = f"bound to {binding[1]!r}" if binding \
                    else "unbound"
                out.append(_finding(
                    "thread-lifecycle", fn, node,
                    f"Thread ({where}) is neither daemon=True nor "
                    f"joined with a timeout in its owning scope — a "
                    f"non-daemon leaked thread blocks interpreter "
                    f"exit"))
        return out

    def _binding_managed(self, fn: mg.FuncInfo,
                         binding: Tuple[str, str]) -> bool:
        kind, name = binding
        if kind == "attr":
            scope_nodes = [m.node for m in fn.cls.methods.values()] \
                if fn.cls is not None else [fn.node]
            return any(_attr_thread_managed(n, name)
                       for n in scope_nodes)
        # local: daemon/join in this function, or the local escapes
        # (returned / stored on self / passed on) — then lifecycle is
        # the receiver's problem, checked at ITS binding
        node = fn.node
        if _local_thread_managed(node, name):
            return True
        return _local_escapes(node, name)

    # -- unbounded-wait ------------------------------------------------
    def unbounded_wait_findings(self) -> List[LintFinding]:
        out: List[LintFinding] = []
        for facts in self.facts.values():
            for node, skind, bounded, recv in facts.waits:
                if bounded:
                    continue
                verb = "join()" if skind == "thread" else "wait()"
                out.append(_finding(
                    "unbounded-wait", facts.fn, node,
                    f"unbounded {verb} on {recv} ({skind}) can wedge "
                    f"this thread forever if the peer never signals; "
                    f"pass a timeout and handle expiry"))
        return out


def _attr_thread_managed(scope_node: ast.AST, attr: str) -> bool:
    for sub in ast.walk(scope_node):
        # self.<attr>.daemon = True
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and isinstance(t.value, ast.Attribute) \
                        and t.value.attr == attr \
                        and isinstance(sub.value, ast.Constant) \
                        and sub.value.value is True:
                    return True
        # self.<attr>.join(bounded) or  t = self.<attr>; t.join(bounded)
        if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                    ast.Attribute) \
                and sub.func.attr == "join" and mg.call_is_bounded(sub):
            chain = mg.attr_chain(sub.func)
            if f".{attr}." in f".{chain}.":
                return True
        # for w in self.<attr>: w.join(bounded)
        if isinstance(sub, (ast.For, ast.AsyncFor)) \
                and isinstance(sub.iter, ast.Attribute) \
                and sub.iter.attr == attr \
                and isinstance(sub.target, ast.Name):
            if _local_thread_managed(sub, sub.target.id):
                return True
        # t = self.<attr>  ...  t.join(bounded)
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                and isinstance(sub.targets[0], ast.Name) \
                and isinstance(sub.value, ast.Attribute) \
                and sub.value.attr == attr:
            if _local_thread_managed(scope_node, sub.targets[0].id):
                return True
    return False


def _local_thread_managed(scope_node: ast.AST, name: str) -> bool:
    for sub in ast.walk(scope_node):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == name \
                        and isinstance(sub.value, ast.Constant) \
                        and sub.value.value is True:
                    return True
        if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                    ast.Attribute) \
                and sub.func.attr == "join" \
                and isinstance(sub.func.value, ast.Name) \
                and sub.func.value.id == name \
                and mg.call_is_bounded(sub):
            return True
    return False


def _local_escapes(fn_node: ast.AST, name: str) -> bool:
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Return) and sub.value is not None:
            if any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(sub.value)):
                return True
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == name:
                    return True
        if isinstance(sub, ast.Call):
            fnx = sub.func
            is_start = isinstance(fnx, ast.Attribute) \
                and fnx.attr in ("start", "join", "is_alive") \
                and isinstance(fnx.value, ast.Name) \
                and fnx.value.id == name
            args = list(sub.args) + [k.value for k in sub.keywords]
            if not is_start and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in args):
                return True
    return False


def _cycles(graph: Dict[LockId, Set[LockId]]) -> List[List[LockId]]:
    """Elementary cycles via SCC + one representative cycle per SCC
    (Tarjan; a representative is enough — the finding lists the SCC)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on: Set[LockId] = set()
    stack: List[LockId] = []
    sccs: List[List[LockId]] = []
    counter = [0]

    def strong(v: LockId):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(list(reversed(comp)))

    for v in sorted(graph, key=_lock_sort):
        if v not in index:
            strong(v)
    # one ACTUAL cycle per SCC: shortest path from a successor of the
    # root back to the root, within the component (BFS — guarantees
    # every consecutive edge, including the closing one, exists; a
    # greedy walk can build a path whose wrap-around edge does not,
    # e.g. two 2-cycles sharing a lock)
    cycles: List[List[LockId]] = []
    for comp in sccs:
        comp_set = set(comp)
        root = comp[0]
        best: Optional[List[LockId]] = None
        for start in sorted(graph.get(root, ()), key=_lock_sort):
            if start not in comp_set:
                continue
            # BFS start -> root within the SCC (guaranteed to exist)
            prev: Dict[LockId, Optional[LockId]] = {start: None}
            queue = [start]
            while queue and root not in prev:
                v = queue.pop(0)
                for w in sorted(graph.get(v, ()), key=_lock_sort):
                    if w in comp_set and w not in prev:
                        prev[w] = v
                        queue.append(w)
            if root not in prev:
                continue
            rev: List[LockId] = []   # [root, pred-of-root, ..., start]
            v: Optional[LockId] = root
            while v is not None:
                rev.append(v)
                v = prev[v]
            # cycle node order: root -> start -> ... -> pred-of-root
            path = [root] + rev[1:][::-1]
            if best is None or len(path) < len(best):
                best = path
        if best is not None:
            cycles.append(best)
    return cycles


def _finding(rule: str, fn: mg.FuncInfo, node: ast.AST,
             message: str) -> LintFinding:
    line = getattr(node, "lineno", 0)
    lines = fn.module.source.splitlines()
    snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ""
    return LintFinding(rule, fn.module.path, line,
                       getattr(node, "col_offset", 0), message,
                       snippet, symbol=fn.qualname)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def _run(pkg: mg.Package, parse_errors: List[LintFinding],
         rules: Optional[Iterable[str]]) -> List[LintFinding]:
    active = set(rules) if rules is not None else set(CONCURRENCY_RULES)
    ana = _Analysis(pkg)
    findings: List[LintFinding] = list(parse_errors)
    if "guarded-field" in active:
        findings.extend(ana.guarded_field_findings())
    if "lock-order" in active:
        findings.extend(ana.lock_order_findings())
    if "thread-lifecycle" in active:
        findings.extend(ana.thread_lifecycle_findings())
    if "unbounded-wait" in active:
        findings.extend(ana.unbounded_wait_findings())
    # pragma suppression (shared `# ffcheck: ok(<rule>)` syntax)
    out: List[LintFinding] = []
    by_path = {m.path: m for m in pkg.modules.values()}
    pragma_cache: Dict[str, Dict[int, Optional[Set[str]]]] = {}
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            if f.path not in pragma_cache:
                pragma_cache[f.path] = _pragmas(mod.source)
            if _suppressed(pragma_cache[f.path], f.rule, f.line):
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Iterable[str]] = None
                  ) -> List[LintFinding]:
    """Run the concurrency engine over files/trees (``tests`` dirs and
    ``test_*.py`` skipped, like the linter's walk)."""
    pkg = mg.Package()
    parse_errors: List[LintFinding] = []
    for path in mg.iter_py_files(paths):
        if pkg.add_file(path) is None:
            parse_errors.append(LintFinding(
                "parse-error", path, 0, 0, "file does not parse"))
    return _run(pkg, parse_errors, rules)


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[Iterable[str]] = None
                    ) -> List[LintFinding]:
    """Analyze in-memory ``{path: source}`` modules (tests; multi-module
    snippets resolve cross-module exactly like on-disk trees)."""
    pkg = mg.Package()
    parse_errors: List[LintFinding] = []
    for path, src in sources.items():
        if pkg.add_source(path, src) is None:
            parse_errors.append(LintFinding(
                "parse-error", path, 0, 0, "file does not parse"))
    return _run(pkg, parse_errors, rules)
