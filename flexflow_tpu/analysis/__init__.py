"""Static analysis: plan verification + framework-invariant linting.

Two engines behind one CLI (``tools/ffcheck.py``) and one library API:

  - :mod:`flexflow_tpu.analysis.plan_verifier` — proves a searched
    strategy/PCG executable on a machine model BEFORE a device runs it:
    mesh-axis soundness and shard divisibility for every op, a legal
    ``reshard.ReshardPlanner`` lowering for every layout seam, a static
    per-device peak-memory envelope, and SPMD collective-ordering
    consistency (deadlock freedom). Wired into ``FFModel.compile``
    post-search; failures raise :class:`PlanVerificationError` with
    op/seam attribution.
  - :mod:`flexflow_tpu.analysis.lint` — AST rules for the hard
    invariants PRs 4–7 established (no implicit host sync in the
    dispatch window, ``-O``-safe typed errors instead of ``assert``,
    every cross-rank/thread wait bounded, no wall-clock reads inside
    jitted fns), with a ``# ffcheck: ok(<rule>)`` suppression pragma.

Both run in ``ci.sh``'s fast tier as a hard gate. See
``docs/static_analysis.md``.
"""
from .lint import LintFinding, lint_file, lint_paths  # noqa: F401
from .plan_verifier import (Finding, PlanReport,  # noqa: F401
                            PlanVerificationError, StructMesh,
                            verify_model, verify_plan,
                            verify_strategy_file)
