"""Static analysis: plan verification + invariant/concurrency linting.

Four engines behind one CLI (``tools/ffcheck.py``) and one library API:

  - :mod:`flexflow_tpu.analysis.plan_verifier` — proves a searched
    strategy/PCG executable on a machine model BEFORE a device runs it:
    mesh-axis soundness and shard divisibility for every op, a legal
    ``reshard.ReshardPlanner`` lowering for every layout seam, a static
    per-device peak-memory envelope, and SPMD collective-ordering
    consistency (deadlock freedom). Wired into ``FFModel.compile``
    post-search; failures raise :class:`PlanVerificationError` with
    op/seam attribution.
  - :mod:`flexflow_tpu.analysis.lint` — AST rules for the hard
    invariants PRs 4–7 established (no implicit host sync in the
    dispatch window, ``-O``-safe typed errors instead of ``assert``,
    every cross-rank/thread wait bounded, no wall-clock reads inside
    jitted fns), with a ``# ffcheck: ok(<rule>)`` suppression pragma.
  - :mod:`flexflow_tpu.analysis.concurrency` — lock-discipline proof
    over the threaded runtime (ISSUE 14): inferred lock-guarded
    attributes enforced at every access, a cross-module
    lock-acquisition-order graph with cycle detection, thread
    lifecycle (daemon or bounded join), and typed unbounded-wait.
  - :mod:`flexflow_tpu.analysis.spmd` — SPMD-divergence checker: a
    call-graph reachability walk flagging collective/rendezvous
    operations reachable from only one side of rank-dependent control
    flow (the "collective inside a rank-conditional" deadlock class).

All of them run in ``ci.sh``'s fast tier as a hard gate (with a
wall-time budget). See ``docs/static_analysis.md``.
"""
from .concurrency import CONCURRENCY_RULES  # noqa: F401
from .concurrency import analyze_paths as analyze_concurrency  # noqa: F401
from .concurrency import analyze_sources as analyze_concurrency_sources  # noqa: F401,E501
from .lint import (JSON_SCHEMA_VERSION, LintFinding,  # noqa: F401
                   lint_file, lint_paths)
from .plan_verifier import (Finding, PlanReport,  # noqa: F401
                            PlanVerificationError, StructMesh,
                            verify_model, verify_plan,
                            verify_strategy_file)
from .spmd import SPMD_RULES, SPMD_SCOPE  # noqa: F401
from .spmd import analyze_paths as analyze_spmd  # noqa: F401
from .spmd import analyze_sources as analyze_spmd_sources  # noqa: F401
