"""SPMD-divergence static checker (ffcheck v2).

The multi-rank runtime's deadliest bug class is a *collective inside a
rank-conditional branch*: rank 0 takes the ``if``, calls a barrier (or
a blocking KV get, or a quorum publish), and the other ranks — who
never entered the branch — never arrive. The process hangs until the
coordinator's bounded-barrier timeout fires, and the root cause is a
control-flow asymmetry nothing type-checks. PR 7's two-phase checkpoint
commit navigates this by careful convention (rank-0-only blocks contain
ONLY file I/O; every barrier sits outside them); this engine enforces
the convention:

  ``rank-gated-collective``
      For every ``if`` whose test is *rank-dependent* — it calls
      ``process_index()``, compares something named ``rank``, or reads
      a ``*RANK*`` environment variable — the sets of collective
      operations reachable from the two branches (transitively,
      through statically-resolvable calls) must MATCH. A collective
      reachable from only one branch is flagged at its call site with
      the gating condition attributed. World-*size* tests
      (``process_count() > 1``, ``world <= 1``) are uniform across
      ranks and deliberately NOT rank-dependent.

Collective/rendezvous primitives recognized (by call name, plus
anything that transitively reaches one): ``wait_at_barrier``,
``blocking_key_value_get``, ``barrier``, ``process_allgather``,
``sync_global_devices``, ``broadcast_one_to_all``, ``clock_sync``.

Default scope (CLI ``--spmd`` with no paths): the modules where
rank-divergent control flow lives — ``resilience/``,
``runtime/checkpoint.py``, ``parallel/distributed.py``. Explicit file
arguments are always analyzed regardless of scope (fixtures, tests).

Known limitation (documented, not silently ignored): divergence via
early ``return``/``raise`` under a rank conditional followed by a
collective in the fall-through is NOT modeled — only branch-local
reachability is compared. Suppression: the shared
``# ffcheck: ok(rank-gated-collective)`` pragma with a one-line
justification.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import _modgraph as mg
from .lint import LintFinding, _pragmas, _suppressed

__all__ = ["SPMD_RULES", "SPMD_SCOPE", "COLLECTIVE_CALLS",
           "analyze_paths", "analyze_sources"]

SPMD_RULES: Dict[str, str] = {
    "rank-gated-collective":
        "collective reachable from only one side of a rank-conditional "
        "branch (divergence deadlock)",
}

#: path scope the repo-wide walk restricts to (same component-anchored
#: matching as the linter's module scopes)
SPMD_SCOPE: Tuple[str, ...] = ("/resilience/", "runtime/checkpoint.py",
                               "parallel/distributed.py")

#: call names that ARE collective/rendezvous operations
COLLECTIVE_CALLS: Set[str] = {
    "wait_at_barrier", "blocking_key_value_get", "barrier",
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    "clock_sync",
}

_RANK_WORD = re.compile(r"(?:^|_)rank(?:$|_)", re.IGNORECASE)


def _in_scope(path: str) -> bool:
    norm = "/" + mg.norm_path(path)
    for m in SPMD_SCOPE:
        if m.startswith("/"):
            if m in norm:
                return True
        elif norm.endswith("/" + m):
            return True
    return False


# ---------------------------------------------------------------------------
# rank-dependence of an expression
# ---------------------------------------------------------------------------

def _ident_is_ranky(name: str) -> bool:
    """``rank``, ``world_rank``, ``self.rank``'s attr — identifier
    contains the word "rank" (underscore-delimited; ``ranked`` etc.
    stay out)."""
    return bool(_RANK_WORD.search(name))


def _is_rank_dependent(test: ast.AST) -> Optional[str]:
    """A human-readable description of why the test diverges per rank,
    or None when it is uniform. ``process_count``/``world`` size tests
    are uniform by construction."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            chain = mg.attr_chain(node.func) if isinstance(
                node.func, ast.Attribute) else (
                node.func.id if isinstance(node.func, ast.Name) else "")
            last = chain.rsplit(".", 1)[-1]
            if last == "process_index":
                return f"{chain}()"
            if last in ("getenv", "get") and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str) \
                        and "RANK" in a0.value.upper():
                    return f"env {a0.value!r}"
        elif isinstance(node, ast.Subscript):
            base = mg.attr_chain(node.value)
            if base.endswith("environ") \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and "RANK" in node.slice.value.upper():
                return f"env {node.slice.value!r}"
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            for s in sides:
                name = None
                if isinstance(s, ast.Name):
                    name = s.id
                elif isinstance(s, ast.Attribute):
                    name = s.attr
                if name is not None and _ident_is_ranky(name):
                    return f"comparison on {name!r}"
    return None


# ---------------------------------------------------------------------------
# collective reachability
# ---------------------------------------------------------------------------

class _CollectiveIndex:
    """Per-function summaries: collective ops a function performs,
    directly or transitively through statically-resolvable calls."""

    def __init__(self, pkg: mg.Package):
        self.pkg = pkg
        self.summary: Dict[int, Set[str]] = {}
        self._locals: Dict[int, Dict[str, object]] = {}
        for mod in pkg.modules.values():
            for fi in mod.all_functions:
                self.summary[id(fi)] = self._direct(fi)
        changed = True
        while changed:
            changed = False
            for mod in pkg.modules.values():
                for fi in mod.all_functions:
                    cur = self.summary[id(fi)]
                    for call in self._calls(fi):
                        callee = pkg.resolve_callee(
                            fi, call, self._locals_of(fi))
                        if callee is None:
                            continue
                        extra = self.summary.get(id(callee))
                        if extra and not extra <= cur:
                            cur |= extra
                            changed = True

    def _locals_of(self, fi: mg.FuncInfo) -> Dict[str, object]:
        # parameter names shadow module globals during resolution
        if id(fi) not in self._locals:
            args = fi.node.args
            names = [a.arg for a in
                     list(args.posonlyargs) + list(args.args)
                     + list(args.kwonlyargs)]
            env: Dict[str, object] = {n: None for n in names}
            if fi.cls is not None and "self" in env:
                env["self"] = ("instance", fi.cls)
            self._locals[id(fi)] = env
        return self._locals[id(fi)]

    @staticmethod
    def _calls(fi: mg.FuncInfo) -> List[ast.Call]:
        out = []
        stack = list(ast.iter_child_nodes(fi.node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are their own FuncInfo
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _direct(self, fi: mg.FuncInfo) -> Set[str]:
        out: Set[str] = set()
        for call in self._calls(fi):
            name = _call_name(call)
            if name in COLLECTIVE_CALLS:
                out.add(name)
        return out

    # -- per-statement reachability ------------------------------------
    def reachable(self, fi: mg.FuncInfo, stmts: Sequence[ast.stmt]
                  ) -> Dict[str, ast.AST]:
        """Collective op name -> first contributing node among
        ``stmts`` (direct call site, or the call whose callee reaches
        it)."""
        out: Dict[str, ast.AST] = {}
        for st in stmts:
            stack = [st]
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    continue
                if isinstance(n, ast.Call):
                    name = _call_name(n)
                    if name in COLLECTIVE_CALLS:
                        out.setdefault(name, n)
                    callee = self.pkg.resolve_callee(
                        fi, n, self._locals_of(fi))
                    if callee is not None:
                        for op in self.summary.get(id(callee), ()):
                            out.setdefault(op, n)
                stack.extend(ast.iter_child_nodes(n))
        return out


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _check_function(pkg: mg.Package, index: _CollectiveIndex,
                    fi: mg.FuncInfo,
                    findings: List[LintFinding]) -> None:
    lines = fi.module.source.splitlines()

    def add(node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        snippet = lines[line - 1].strip() \
            if 0 < line <= len(lines) else ""
        findings.append(LintFinding(
            "rank-gated-collective", fi.module.path, line,
            getattr(node, "col_offset", 0), message, snippet,
            symbol=fi.qualname))

    stack = list(fi.node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.If):
            why = _is_rank_dependent(n.test)
            if why is not None:
                body = index.reachable(fi, n.body)
                other = index.reachable(fi, n.orelse)
                for op, site in sorted(body.items()):
                    if op not in other:
                        add(site,
                            f"collective {op!r} reachable only when "
                            f"the rank-conditional ({why}) holds — "
                            f"ranks not taking this branch never "
                            f"arrive; hoist it out or add the "
                            f"matching call on the other path")
                for op, site in sorted(other.items()):
                    if op not in body:
                        add(site,
                            f"collective {op!r} reachable only when "
                            f"the rank-conditional ({why}) does NOT "
                            f"hold — ranks taking the branch never "
                            f"arrive; hoist it out or add the "
                            f"matching call on the other path")
        for child in ast.iter_child_nodes(n):
            stack.append(child)


def _run(pkg: mg.Package, parse_errors: List[LintFinding],
         rules: Optional[Iterable[str]]) -> List[LintFinding]:
    active = set(rules) if rules is not None else set(SPMD_RULES)
    findings: List[LintFinding] = list(parse_errors)
    if "rank-gated-collective" in active:
        index = _CollectiveIndex(pkg)
        for mod in pkg.modules.values():
            if not mod.__dict__.get("_spmd_check", True):
                continue
            for fi in mod.all_functions:
                _check_function(pkg, index, fi, findings)
    out: List[LintFinding] = []
    by_path = {m.path: m for m in pkg.modules.values()}
    pragma_cache: Dict[str, Dict[int, Optional[Set[str]]]] = {}
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            if f.path not in pragma_cache:
                pragma_cache[f.path] = _pragmas(mod.source)
            if _suppressed(pragma_cache[f.path], f.rule, f.line):
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col))
    return out


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Iterable[str]] = None
                  ) -> List[LintFinding]:
    """Run the SPMD checker. Directory trees are restricted to
    :data:`SPMD_SCOPE`, but every module in ``paths`` still loads into
    the call-graph (a collective reached THROUGH an out-of-scope helper
    is attributed at the in-scope call site); explicitly-named files
    are checked regardless of scope."""
    pkg = mg.Package()
    parse_errors: List[LintFinding] = []
    explicit = {mg.norm_path(p) for p in paths}
    for path in mg.iter_py_files(paths):
        mod = pkg.add_file(path)
        if mod is None:
            parse_errors.append(LintFinding(
                "parse-error", path, 0, 0, "file does not parse"))
            continue
        mod.__dict__["_spmd_check"] = (
            mg.norm_path(path) in explicit or _in_scope(path))
    return _run(pkg, parse_errors, rules)


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[Iterable[str]] = None
                    ) -> List[LintFinding]:
    """Analyze in-memory ``{path: source}`` modules (all checked —
    tests name their scope explicitly)."""
    pkg = mg.Package()
    parse_errors: List[LintFinding] = []
    for path, src in sources.items():
        if pkg.add_source(path, src) is None:
            parse_errors.append(LintFinding(
                "parse-error", path, 0, 0, "file does not parse"))
    return _run(pkg, parse_errors, rules)
