from .optimizers import SGDOptimizer, AdamOptimizer, Optimizer  # noqa: F401
from .dataloader import SingleDataLoader  # noqa: F401
from .metrics import PerfMetrics  # noqa: F401
