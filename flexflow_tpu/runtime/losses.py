"""Loss functions.

Reference parity: ``src/loss_functions/loss_functions.cc:41-160``. The
reference computes the gradient of the final op's output directly (e.g.
(probs - onehot)/B for softmax+CE). Here losses are scalar functions
differentiated by jax.grad; when the graph ends in Softmax and the loss is
cross-entropy, the executor passes the *logits* here and we use the fused
stable form — the resulting gradient is identical to the reference's
hand-written (probs - labels)/batch kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ffconst import LossType


def compute_loss(loss_type: LossType, pred, label, *, logits: bool = False):
    """Mean-reduced scalar loss. `pred` is the final op output (or pre-
    softmax logits when logits=True and the loss is a cross-entropy)."""
    loss_type = LossType(loss_type)
    pred = pred.astype(jnp.float32)

    if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
        label = label.reshape(pred.shape[:-1] + (-1,))[..., 0].astype(jnp.int32)
        if logits:
            logp = jax.nn.log_softmax(pred, axis=-1)
        else:
            logp = jnp.log(jnp.clip(pred, 1e-10, 1.0))
        nll = -jnp.take_along_axis(logp, label[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    if loss_type == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
        label = label.astype(jnp.float32)
        if logits:
            logp = jax.nn.log_softmax(pred, axis=-1)
        else:
            logp = jnp.log(jnp.clip(pred, 1e-10, 1.0))
        # mean over batch rows, sum over classes (reference scale 1/batch)
        batch = pred.size // pred.shape[-1]
        return -jnp.sum(label * logp) / batch

    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE:
        d = pred - label.astype(jnp.float32)
        # reference grad scale 2/volume (loss_functions.cc:51) == mean over
        # ALL elements (torch mse_loss equivalent)
        return jnp.mean(d * d)

    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE:
        d = pred - label.astype(jnp.float32)
        # reference grad = (pred-label)/batchSize (scale 1/batch,
        # loss_functions.cc:53 + .cu kernel) => loss = sum(d^2)/(2*batch)
        return 0.5 * jnp.sum(d * d) / d.shape[0]

    if loss_type == LossType.LOSS_IDENTITY:
        return jnp.mean(pred)

    raise ValueError(loss_type)


_CE_LOSSES = (LossType.LOSS_CATEGORICAL_CROSSENTROPY,
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)


def wants_logits(loss_type: LossType) -> bool:
    return LossType(loss_type) in _CE_LOSSES
