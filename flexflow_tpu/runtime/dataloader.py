"""Data loading: host numpy datasets → device batches with the right sharding.

Reference parity: ``SingleDataLoader`` (``include/flexflow/dataloader.h:34``,
``src/dataloader/dataloader.cc``): the reference pins the full dataset in
zero-copy memory and index-launches a per-device batch-copy GPU task each
iteration. TPU-native: the dataset stays in host RAM; each ``next_batch``
device_puts the batch with the batch-dim NamedSharding, so each chip
receives only its shard (the analog of the shard-wise Legion copy), with a
configurable-depth prefetch queue (``FFConfig.prefetch_batches``,
default 2) so the H2D transfers of the next batches overlap compute —
deeper than one slot matters once the async-dispatch train loop keeps
several steps in flight.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np


class SingleDataLoader:
    """One loader per (input, label) pair set, full-dataset resident."""

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 shardings: Optional[Dict[str, jax.sharding.Sharding]] = None,
                 shuffle: bool = False, seed: int = 0,
                 drop_remainder: bool = True, prefetch: int = 2):
        sizes = {k: v.shape[0] for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"ragged dataset: {sizes}")
        self.arrays = arrays
        self.num_samples = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.shardings = shardings or {}
        self.shuffle = shuffle
        self.rng = np.random.default_rng(seed)
        self.drop_remainder = drop_remainder
        self.idx = 0
        # which epoch this loader position belongs to — maintained by
        # the training driver (the supervisor persists/restores it);
        # plain fit() leaves it at 0
        self.epoch = 0
        self._order = np.arange(self.num_samples)
        # rng state as of the start of the current epoch (BEFORE its
        # shuffle) + whether that shuffle has been applied: together
        # they re-derive `_order` exactly, so state_dict stays O(1)
        # instead of serializing the full permutation
        self._epoch_rng_state = self.rng.bit_generator.state
        self._shuffled = False
        # prefetch queue: device batches for indices idx..idx+len-1,
        # dispatched ahead of consumption (prefetch=0 disables, 1 is
        # the old single-slot double-buffer). Prefetching reads only
        # `_order` — never the rng — so resume stays exact.
        self.prefetch = max(0, int(prefetch))
        self._prefetched: deque = deque()

    @property
    def num_batches(self) -> int:
        if self.drop_remainder:
            return self.num_samples // self.batch_size
        return -(-self.num_samples // self.batch_size)

    def reset(self):
        self.idx = 0
        self._prefetched.clear()
        # fresh permutation from arange (not an in-place reshuffle of
        # the previous order): the order is then a pure function of
        # (_epoch_rng_state, shuffle), which is what lets state_dict
        # persist O(1) rng state instead of the permutation itself
        self._epoch_rng_state = self.rng.bit_generator.state
        self._order = np.arange(self.num_samples)
        self._shuffled = False
        if self.shuffle:
            self.rng.shuffle(self._order)
            self._shuffled = True

    # ------------------------------------------------------------------
    # resumable state (resilience supervisor: exact mid-epoch resume)
    # ------------------------------------------------------------------
    def state_dict(self):
        """JSON-serializable loader position: rng state (as of epoch
        start), epoch, and batch position — O(1), never the sample
        permutation. ``load_state_dict`` of this snapshot replays the
        exact remaining batches, including every later epoch's shuffle,
        by re-deriving the order from the saved rng state."""
        return {
            "idx": int(self.idx),
            "epoch": int(self.epoch),
            "num_samples": int(self.num_samples),
            "batch_size": int(self.batch_size),
            "rng_state": self._epoch_rng_state,
            "shuffled": bool(self._shuffled),
        }

    def load_state_dict(self, sd) -> None:
        if sd.get("num_samples", self.num_samples) != self.num_samples:
            raise ValueError(
                f"loader state for {sd.get('num_samples')} samples "
                f"restored into a {self.num_samples}-sample dataset")
        # idx counts BATCHES: a different batch size would silently
        # reposition the sample stream
        if sd.get("batch_size", self.batch_size) != self.batch_size:
            raise ValueError(
                f"loader state saved with batch_size "
                f"{sd.get('batch_size')} restored into a loader with "
                f"batch_size {self.batch_size}")
        self.idx = int(sd["idx"])
        self.epoch = int(sd.get("epoch", 0))
        self.rng.bit_generator.state = sd["rng_state"]
        self._epoch_rng_state = sd["rng_state"]
        self._order = np.arange(self.num_samples)
        self._shuffled = False
        if sd.get("shuffled"):
            self.rng.shuffle(self._order)  # rng lands post-shuffle
            self._shuffled = True
        self._prefetched.clear()  # re-prefetched on next next_batch

    def _device_put(self, batch: Dict[str, np.ndarray]):
        from ..parallel.distributed import put_global
        return {k: put_global(v, self.shardings.get(k))
                for k, v in batch.items()}

    def _host_batch(self, i: int) -> Optional[Dict[str, np.ndarray]]:
        lo = i * self.batch_size
        hi = lo + self.batch_size
        if hi > self.num_samples:
            if self.drop_remainder or lo >= self.num_samples:
                return None
            hi = self.num_samples
        sel = self._order[lo:hi]
        # threaded C++ row gather when built (reference dataloader batch-copy
        # index launches, dataloader.cc:324); numpy fallback inside
        from .. import native
        return {k: native.gather_batch(v, sel)
                for k, v in self.arrays.items()}

    def next_batch(self):
        """Reference ``next_batch_xd_launcher`` analog; returns device dict
        or None at epoch end. Keeps up to ``prefetch`` following batches'
        transfers in flight (async H2D overlap)."""
        if self._prefetched:
            batch = self._prefetched.popleft()
        else:
            hb = self._host_batch(self.idx)
            if hb is None:
                return None
            batch = self._device_put(hb)
        self.idx += 1
        while len(self._prefetched) < self.prefetch:
            nb = self._host_batch(self.idx + len(self._prefetched))
            if nb is None:
                break
            self._prefetched.append(self._device_put(nb))
        return batch

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        self.reset()
        while True:
            b = self.next_batch()
            if b is None:
                return
            yield b
