"""Optimizers: SGD (+momentum/nesterov) and Adam, matching the reference's
update semantics (``src/runtime/optimizer.cc:158,449`` /
``optimizer_kernel.cu:77-196``).

Gradient sync: the reference launches per-view ncclAllReduce before the
update. Here weights are replicated (or sharded) via NamedSharding in the
jitted step, so XLA inserts the all-reduce/reduce-scatter automatically —
ParameterSyncType.NCCL and PS both map to this path.

Implemented as pure (init_state, update) pairs over pytrees — optax-style,
hand-rolled so the update math exactly mirrors the reference kernels.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, params):
        raise NotImplementedError

    def update(self, params, grads, state, step):
        """Returns (new_params, new_state). `step` is 1-based."""
        raise NotImplementedError

    def next(self):  # reference Optimizer::next() parity (per-step hook)
        pass


class SGDOptimizer(Optimizer):
    """Reference ``SGDOptimizer`` (``optimizer_kernel.cu:77-100``):
    grad += wd*w;  v = momentum*v + grad;  (nesterov: grad += momentum*v)
    w -= lr * (grad or v)."""

    def __init__(self, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def init_state(self, params):
        if self.momentum == 0.0:
            return {}
        return {"v": jax.tree.map(jnp.zeros_like, params)}

    def update(self, params, grads, state, step):
        lr = jnp.asarray(self.lr, jnp.float32)
        wd = self.weight_decay

        if self.momentum == 0.0:
            new_params = jax.tree.map(
                lambda w, g: w - (lr * (g + wd * w)).astype(w.dtype),
                params, grads)
            return new_params, state

        def upd(w, g, v):
            g = g + wd * w
            v = self.momentum * v + g
            step_dir = g + self.momentum * v if self.nesterov else v
            return w - (lr * step_dir).astype(w.dtype), v

        flat = jax.tree.map(upd, params, grads, state["v"],
                            is_leaf=lambda x: isinstance(x, jnp.ndarray))
        new_params = jax.tree.map(lambda t: t[0], flat,
                                  is_leaf=lambda x: isinstance(x, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"v": new_v}


class AdamOptimizer(Optimizer):
    """Reference ``AdamOptimizer`` (``optimizer.cc:449``,
    ``optimizer_kernel.cu:196``): bias-corrected alpha_t, decoupled-from-
    nothing weight decay folded into the gradient (L2 style, as the
    reference does)."""

    def __init__(self, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0,
                 epsilon: float = 1e-8):
        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon

    @property
    def lr(self):
        return self.alpha

    def init_state(self, params):
        return {"m": jax.tree.map(jnp.zeros_like, params),
                "v": jax.tree.map(jnp.zeros_like, params)}

    def update(self, params, grads, state, step):
        t = step.astype(jnp.float32)
        alpha_t = self.alpha * jnp.sqrt(1.0 - self.beta2 ** t) \
            / (1.0 - self.beta1 ** t)

        def upd(w, g, m, v):
            g = (g + self.weight_decay * w).astype(jnp.float32)
            m = self.beta1 * m + (1 - self.beta1) * g
            v = self.beta2 * v + (1 - self.beta2) * g * g
            w = w - (alpha_t * m / (jnp.sqrt(v) + self.epsilon)).astype(w.dtype)
            return w, m, v

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        is_t = lambda x: isinstance(x, tuple)
        return (jax.tree.map(lambda t3: t3[0], flat, is_leaf=is_t),
                {"m": jax.tree.map(lambda t3: t3[1], flat, is_leaf=is_t),
                 "v": jax.tree.map(lambda t3: t3[2], flat, is_leaf=is_t)})


def fused_adam_tree_update(opt: AdamOptimizer, params, grads, state, step):
    """Adam update through the one-HBM-pass Pallas kernel
    (kernels/opt_update.py fused_adam_update), selected by the searched
    kernel tier (``opt_update: fused``). Bit-equal update math to
    ``AdamOptimizer.update`` — w/g/m/v stream through VMEM once instead
    of XLA's per-term HBM round trips."""
    from ..kernels.opt_update import fused_adam_update

    t = step.astype(jnp.float32)
    alpha_t = opt.alpha * jnp.sqrt(1.0 - opt.beta2 ** t) \
        / (1.0 - opt.beta1 ** t)

    def upd(w, g, m, v):
        return fused_adam_update(
            w, g, m, v, alpha_t, beta1=opt.beta1, beta2=opt.beta2,
            eps=opt.epsilon, wd=opt.weight_decay)

    flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
    is_t = lambda x: isinstance(x, tuple)
    return (jax.tree.map(lambda t3: t3[0], flat, is_leaf=is_t),
            {"m": jax.tree.map(lambda t3: t3[1], flat, is_leaf=is_t),
             "v": jax.tree.map(lambda t3: t3[2], flat, is_leaf=is_t)})
