"""Metrics: PerfMetrics accumulation.

Reference parity: ``src/metrics_functions/metrics_functions.cc:68-130`` —
per-shard ``PerfMetrics`` reduced through a Legion future chain. Here the
per-batch metrics are computed inside the jitted step (so the reduction is
an XLA collective over the sharded batch) and accumulated on host floats.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

from ..ffconst import LossType, MetricsType

# batch-metric keys that are COUNTS over samples (vs per-sample means):
# accumulation/reduction layers must SUM these across micro-batches,
# never average (see Executor.make_train_step)
COUNT_KEYS = frozenset({"accuracy_correct"})

# keys that are sqrt-of-a-mean: composing across micro-batches must
# average the SQUARES and take one sqrt at the end (mean of per-micro
# sqrts is not the full-batch RMSE)
RMS_KEYS = frozenset({"rmse_loss"})


@dataclasses.dataclass
class PerfMetrics:
    """Host-side accumulator (reference ``PerfMetrics`` struct parity)."""
    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0
    loss: float = 0.0

    _KEYS = ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss",
             "mae_loss", "loss")

    def update(self, batch_metrics: Dict[str, float], batch_size: int):
        self.train_all += batch_size
        if "accuracy_correct" in batch_metrics:
            self.train_correct += int(batch_metrics["accuracy_correct"])
        for k in self._KEYS:
            if k in batch_metrics:
                setattr(self, k, getattr(self, k)
                        + float(batch_metrics[k]) * batch_size)

    def report(self) -> Dict[str, float]:
        n = max(self.train_all, 1)
        out = {}
        if self.train_correct or self.train_all:
            out["accuracy"] = self.train_correct / n
        for k in self._KEYS:
            v = getattr(self, k)
            if v:
                out[k] = v / n
        return out


def compute_batch_metrics(metrics: Sequence[MetricsType], pred, label,
                          loss_type: LossType) -> Dict[str, jnp.ndarray]:
    """Inside-jit metric computation (reference ``Metrics::compute_task``)."""
    out: Dict[str, jnp.ndarray] = {}
    pf = pred.astype(jnp.float32)
    sparse = LossType(loss_type) == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
    for m in metrics:
        m = MetricsType(m)
        if m == MetricsType.METRICS_ACCURACY:
            yhat = jnp.argmax(pf, axis=-1)
            if sparse:
                y = label.reshape(yhat.shape + (-1,))[..., 0].astype(jnp.int32)
            else:
                y = jnp.argmax(label, axis=-1)
            out["accuracy_correct"] = jnp.sum(yhat == y).astype(jnp.float32)
        elif m == MetricsType.METRICS_CATEGORICAL_CROSSENTROPY:
            logp = jnp.log(jnp.clip(pf, 1e-10, 1.0))
            batch = pf.size // pf.shape[-1]
            out["cce_loss"] = -jnp.sum(label.astype(jnp.float32) * logp) / batch
        elif m == MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY:
            y = label.reshape(pf.shape[:-1] + (-1,))[..., 0].astype(jnp.int32)
            logp = jnp.log(jnp.clip(pf, 1e-10, 1.0))
            nll = -jnp.take_along_axis(logp, y[..., None], axis=-1)
            out["sparse_cce_loss"] = jnp.mean(nll)
        elif m == MetricsType.METRICS_MEAN_SQUARED_ERROR:
            d = pf - label.astype(jnp.float32)
            out["mse_loss"] = jnp.mean(jnp.sum(d * d, axis=-1))
        elif m == MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR:
            d = pf - label.astype(jnp.float32)
            out["rmse_loss"] = jnp.sqrt(jnp.mean(jnp.sum(d * d, axis=-1)))
        elif m == MetricsType.METRICS_MEAN_ABSOLUTE_ERROR:
            d = jnp.abs(pf - label.astype(jnp.float32))
            out["mae_loss"] = jnp.mean(jnp.sum(d, axis=-1))
    return out
