"""FFModel.fit callbacks (signature: ``on_epoch_end(epoch, logs, model)``).

The reference has no fault-tolerance mechanism (SURVEY.md §5: "failure
detection / elastic recovery: absent"); checkpoint-based recovery is a
TPU-native addition here. ``PeriodicCheckpoint`` + ``FFModel.
restore_checkpoint`` give preemption-safe training — the standard
requirement on TPU pods, which are preemptible by design.
"""
from __future__ import annotations

from typing import Optional


class PeriodicCheckpoint:
    """Save params/optimizer/state/strategy every N epochs, with
    retention (align ``every_epochs`` with the total epoch count to
    capture the final epoch). Resume with
    ``FFModel.restore_checkpoint(directory)`` — restored arrays re-place
    under the CURRENT strategy, so resume works across strategy changes.

    Multi-controller safe: every process participates in the save (the
    cross-host shard gather is a collective); process 0 writes the files
    (``CheckpointManager.save``).
    """

    def __init__(self, directory: str, every_epochs: int = 1,
                 max_to_keep: int = 3):
        self.directory = directory
        self.every = max(1, every_epochs)
        self.max_to_keep = max_to_keep
        self.saved_steps = []

    def on_epoch_end(self, epoch: int, logs=None, model=None):
        if model is None or (epoch + 1) % self.every:
            return
        model.save_checkpoint(self.directory,
                              max_to_keep=self.max_to_keep)
        self.saved_steps.append(model._step)
