"""Device-resident metric accumulation for the async-dispatch train loop.

The reference FlexFlow gets step-level overlap for free from Legion's
asynchronous task launches; the JAX port loses it the moment the host
calls ``np.asarray`` on a per-step metric — that is a device sync, so
the host can never run more than one step ahead and the XLA
async-dispatch pipeline stays one deep. :class:`MetricsBuffer` restores
the overlap:

  - each step's metric dict (tiny device scalars, including the fused
    ``all_finite`` flag the jitted step computes — see
    ``Executor.make_train_step``) is *pushed* without any host fetch;
    the values stay device-resident;
  - a bounded in-flight window (``FFConfig.async_dispatch_steps``,
    default 8) keeps the host from racing unboundedly ahead: pushing
    step N only blocks on the step leaving the window (N - window),
    which on an in-order device stream bounds in-flight work to
    ``window`` steps;
  - :meth:`flush` fetches every pending step in **one**
    ``jax.device_get`` and folds them, in push order, into the attached
    :class:`~flexflow_tpu.runtime.metrics.PerfMetrics` — numerically
    identical (bit-exact) to the old per-step-fetch loop, just batched;
  - the NaN screen becomes a host check of the fetched ``all_finite``
    flags at flush points: the first non-finite step index is kept
    (:attr:`first_bad_step`) and :meth:`raise_if_poisoned` raises
    :class:`NonFiniteMetrics` — callers (the resilience supervisor,
    ``FFModel.save_checkpoint``) flush + screen **before any checkpoint
    save**, preserving the invariant that a poisoned state never
    reaches a checkpoint.

Sync-every-step fallback (``FF_SYNC_EVERY_STEP=1`` or
``async_dispatch_steps <= 0``): every push flushes immediately — the
old loop's semantics (errors and NaNs surface at the step that caused
them), but still converting each metric exactly once.

Observability: host-blocked milliseconds (window blocks + flush
fetches) accumulate into the ``ff_host_blocked_ms_total`` gauge, and
each flush records a ``metrics_buffer.flush`` span when tracing is on.
"""
from __future__ import annotations

import math
import os
import time
from collections import deque
from typing import Any, Dict, Optional

import jax

from ..obs import events as obs_events
from ..obs.events import _env_on
from ..obs.metrics_registry import REGISTRY

ENV_SYNC = "FF_SYNC_EVERY_STEP"

#: metric key carrying the fused in-jit loss-finiteness flag; stripped
#: from the dicts folded into PerfMetrics
ALL_FINITE_KEY = "all_finite"


def sync_every_step_forced() -> bool:
    """Is the sync-every-step fallback forced by the environment?"""
    return _env_on(os.environ.get(ENV_SYNC))


class NonFiniteMetrics(RuntimeError):
    """A flushed step reported a non-finite loss/metric. ``step`` is the
    global train-step index of the FIRST bad step in the flushed run —
    the rollback attribution the supervisor needs."""

    def __init__(self, step: int, value: float):
        super().__init__(f"non-finite loss {value} at step {step}")
        self.step = step
        self.value = value


class MetricsBuffer:
    """Deferred, device-resident per-step metric accumulator.

    ``window <= 0`` means sync-every-step (each push flushes
    immediately). ``pm`` is the :class:`PerfMetrics` flushes fold into;
    drivers may swap it per epoch (``buf.pm = pm``). ``max_pending``
    bounds MEMORY the way ``window`` bounds in-flight compute: a driver
    that reaches no flush point for a long stretch (``verbose=False``
    fits, a huge ``checkpoint_every``) still folds every
    ``max_pending`` steps instead of retaining an epoch's worth of
    per-step device scalars."""

    def __init__(self, window: int = 8, pm=None, max_pending: int = 512):
        self.window = int(window)
        self.max_pending = max(1, int(max_pending))
        self.pm = pm
        # (global step index, device metric dict, batch size)
        self._pending: deque = deque()
        self.steps_flushed = 0
        self.flushes = 0
        self.blocked_ms = 0.0
        self._gauge_reported_ms = 0.0
        self.first_bad_step: Optional[int] = None
        self.first_bad_value: float = float("nan")

    @classmethod
    def for_config(cls, config, pm=None) -> "MetricsBuffer":
        """Resolve the window from config + environment: the env
        override is read here (not at import) so tests and debug
        sessions can toggle it between fits."""
        window = int(getattr(config, "async_dispatch_steps", 8))
        if sync_every_step_forced():
            window = 0
        return cls(window=window, pm=pm)

    # ------------------------------------------------------------------
    @property
    def sync(self) -> bool:
        return self.window <= 0

    @property
    def pending(self) -> int:
        return len(self._pending)

    @property
    def poisoned(self) -> bool:
        return self.first_bad_step is not None

    def raise_if_poisoned(self) -> None:
        if self.first_bad_step is not None:
            raise NonFiniteMetrics(self.first_bad_step,
                                   self.first_bad_value)

    # ------------------------------------------------------------------
    def push(self, step_idx: int, bm: Dict[str, Any],
             batch_size: int) -> None:
        """Record one step's device metric dict. No host fetch in async
        mode; in sync mode this flushes (old-loop semantics)."""
        self._pending.append((int(step_idx), bm, int(batch_size)))
        if self.sync or len(self._pending) >= self.max_pending:
            self.flush()
            return
        if len(self._pending) > self.window:
            # bound in-flight work: block on the step LEAVING the
            # window; earlier steps completed before it (in-order
            # stream), later ones are the window we keep open
            leaving = self._pending[len(self._pending) - self.window - 1]
            v = leaving[1].get("loss")
            if v is None and leaving[1]:
                v = next(iter(leaving[1].values()))
            if hasattr(v, "block_until_ready"):
                # hot path: accumulate blocked time locally; the
                # registry gauge is only touched at flush time
                t0 = time.perf_counter()
                v.block_until_ready()
                self.blocked_ms += (time.perf_counter() - t0) * 1000.0

    def flush(self) -> int:
        """Fetch every pending step in one ``jax.device_get``, fold
        into ``pm`` in push order, update the NaN screen. Returns the
        number of steps folded."""
        if not self._pending:
            return 0
        entries = list(self._pending)
        self._pending.clear()
        t0 = time.perf_counter()
        fetched = jax.device_get([bm for _, bm, _ in entries])
        blocked = time.perf_counter() - t0
        for (step_idx, _, bsz), vals in zip(entries, fetched):
            vals = dict(vals)
            ok = vals.pop(ALL_FINITE_KEY, None)
            loss = vals.get("loss")
            if ok is None:
                # step fn without the fused flag (e.g. a custom step):
                # fall back to screening the fetched loss
                ok = loss is None or math.isfinite(float(loss))
            if self.pm is not None:
                self.pm.update(vals, bsz)
            if not bool(ok) and self.first_bad_step is None:
                self.first_bad_step = step_idx
                self.first_bad_value = float(loss) if loss is not None \
                    else float("nan")
        self.blocked_ms += blocked * 1000.0
        self.flushes += 1
        self.steps_flushed += len(entries)
        # gauge updated once per flush (not per step): the hot loop's
        # only host costs are a deque append and the window block
        REGISTRY.gauge(
            "ff_host_blocked_ms_total",
            "Cumulative host milliseconds blocked on device sync "
            "(metric flushes + in-flight window bounds)"
        ).inc(self.blocked_ms - self._gauge_reported_ms)
        self._gauge_reported_ms = self.blocked_ms
        obs_events.record_span(
            "metrics_buffer.flush", t0, blocked,
            steps=len(entries), window=self.window,
            blocked_ms=round(blocked * 1000.0, 3))
        return len(entries)
