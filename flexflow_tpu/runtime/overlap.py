"""Communication–computation overlap: the schedulable half.

The search prices overlap (``search/costmodel.py`` ``overlap_mode`` +
``GraphCostEvaluator``'s hidden/exposed sync split); this module makes
it *executable*: gradient sync is lowered as size-bucketed groups whose
optimizer updates launch as each bucket's backward slice completes,
instead of one monolithic update after the full backward pass.

Mechanism — schedule shaping, never math:

  - weighted layers are grouped into **size-bucketed** groups in
    reverse program order (= backward completion order): consecutive
    layers join a bucket until ``FFConfig.overlap_bucket_mb`` of
    gradient bytes accumulate; a single giant parameter gets a bucket
    of its own, many tiny parameters coalesce into one (fewer, larger
    launch points — the classic DDP bucketing trade);
  - inside the jitted step, each bucket's grads pass through one
    ``jax.lax.optimization_barrier`` **chained to the previous
    bucket's update** (the launch token). The barrier is identity —
    bit-exact by construction — but the token chain pins a TOTAL
    per-device launch order (the invariant the plan verifier's
    overlapped-ordering check enforces) and hands XLA's latency-hiding
    scheduler dependency cuts it can interleave: bucket k's gradient
    all-reduce + update run while buckets k+1.. are still in backward;
  - **ZeRO prefetch** (``FFConfig.zero_prefetch``): with a sharded
    optimizer state (PR 10's per-parameter assignment), each bucket's
    update implies a param all-gather. Depth >= 1 chains the UPDATED
    params into the next bucket's launch token, so the gather is
    scheduled one bucket ahead of downstream use; depth 0 chains only
    the raw grads (gathers free to sink to the step end).

The serial path — today's single ``optimizer.update`` after the full
backward — is the bit-exact-preserved default: ``FFConfig.overlap`` is
``"auto"``, which defers to the ``FF_OVERLAP`` env var and resolves OFF
when unset. ``tools/overlap_parity_smoke.py`` pins FF_OVERLAP=1 vs
serial to identical loss histories on every push.

Ineligible configurations fall back to the serial path silently (the
schedule builder returns None): pipelined regions (their params stack
under template keys the per-layer bucketing cannot address) and
optimizers with non-splittable state trees. Bank / place-group members
are excluded per-layer (their weights live under group keys and update
in the unchained tail); the plan verifier REJECTS a hand-built or
imported schedule that names them.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["GradBucket", "OverlapSchedule", "overlap_enabled",
           "build_overlap_schedule", "overlapped_update"]

#: default gradient-bucket size (MiB) when FFConfig carries no knob
DEFAULT_BUCKET_MB = 4


def overlap_enabled(cfg=None) -> bool:
    """Resolve the overlap opt-in: ``FFConfig.overlap`` "on"/"off" wins;
    "auto" (and no config at all) honors the ``FF_OVERLAP`` env var and
    defaults OFF — the serial path stays the bit-exact default."""
    mode = str(getattr(cfg, "overlap", "auto") or "auto").lower()
    if mode in ("on", "true", "1", "yes"):
        return True
    if mode in ("off", "false", "0", "no"):
        return False
    return os.environ.get("FF_OVERLAP", "").lower() \
        in ("1", "true", "yes", "on")


@dataclasses.dataclass
class GradBucket:
    """One grad-sync launch group. ``order`` is the launch position
    (0 = first, the deepest layers — backward produces their grads
    first); ``members`` are executable layer names whose weights update
    together; ``nbytes`` the bucket's total gradient payload."""
    order: int
    members: List[str]
    nbytes: int

    def to_json(self) -> Dict[str, Any]:
        return {"order": self.order, "members": list(self.members),
                "nbytes": int(self.nbytes)}


@dataclasses.dataclass
class OverlapSchedule:
    """The executable bucket schedule + its audit/verifier record."""
    buckets: List[GradBucket]
    bucket_bytes: int
    zero_prefetch: int

    def record(self) -> Dict[str, Any]:
        """JSON form carried as ``strategy.overlap`` — what the plan
        verifier's overlapped-ordering check and the strategy audit
        record consume."""
        return {"enabled": True,
                "bucket_bytes": int(self.bucket_bytes),
                "zero_prefetch": int(self.zero_prefetch),
                "buckets": [b.to_json() for b in self.buckets]}


def _weight_bytes(layer) -> int:
    import numpy as np
    from ..dtypes import itemsize
    total = 0
    for w in layer.weights or ():
        total += int(np.prod(w.shape)) * itemsize(w.dtype)
    return total


def build_overlap_schedule(program, strategy, config
                           ) -> Optional[OverlapSchedule]:
    """Build the bucketed grad-sync schedule for one compiled program,
    or None when overlap is off / the configuration is ineligible
    (pipelined region). Members are layers with weights addressable
    under their own name in the params tree — bank / place-group
    members (weights stacked under group keys) are excluded and update
    in the unchained tail."""
    if not overlap_enabled(config):
        return None
    if getattr(strategy, "pipeline", None) is not None:
        # stage-stacked params are not per-layer addressable; the GPipe
        # scan owns its own schedule — serial fallback
        from ..obs import events as obs_events
        obs_events.counter("overlap.pipeline_fallbacks")
        return None
    rec = getattr(strategy, "overlap", None)
    if rec and rec.get("buckets"):
        # schedule imported with the strategy (or built by a previous
        # executor over the same strategy object): honor it VERBATIM —
        # the plan verifier checks it against THIS program at compile,
        # same contract as an imported zero assignment
        buckets = [GradBucket(int(b.get("order", i)),
                              list(b.get("members") or ()),
                              int(b.get("nbytes", 0)))
                   for i, b in enumerate(rec["buckets"])]
        buckets.sort(key=lambda b: b.order)
        return OverlapSchedule(
            buckets,
            int(rec.get("bucket_bytes", DEFAULT_BUCKET_MB << 20)),
            max(0, int(rec.get("zero_prefetch", 1))))
    grouped: set = set()
    for bk in getattr(strategy, "banks", None) or ():
        grouped.update(bk.members)
    for pg in getattr(strategy, "place_groups", None) or ():
        grouped.update(pg.members)
    try:
        cap_mb = float(getattr(config, "overlap_bucket_mb",
                               DEFAULT_BUCKET_MB))
    except (TypeError, ValueError):
        cap_mb = float(DEFAULT_BUCKET_MB)
    if cap_mb <= 0:
        cap_mb = float(DEFAULT_BUCKET_MB)
    cap = max(1, int(cap_mb * (1 << 20)))
    prefetch = max(0, int(getattr(config, "zero_prefetch", 1)))

    from ..ops import ensure_weight_specs
    weighted: List[Tuple[str, int]] = []
    for layer in program.layers:
        if layer.name in grouped:
            continue
        if not ensure_weight_specs(layer):
            continue
        weighted.append((layer.name, _weight_bytes(layer)))
    if not weighted:
        return None

    buckets: List[GradBucket] = []
    members: List[str] = []
    acc = 0
    # reverse program order = backward completion order: the deepest
    # layer's grads materialize first and launch first
    for name, nb in reversed(weighted):
        if members and acc + nb > cap:
            buckets.append(GradBucket(len(buckets), members, acc))
            members, acc = [], 0
        members.append(name)
        acc += nb
    if members:
        buckets.append(GradBucket(len(buckets), members, acc))
    return OverlapSchedule(buckets, cap, prefetch)


# ---------------------------------------------------------------------------
# the barrier-chained bucketed update
# ---------------------------------------------------------------------------

def _subtree(tree: Dict[str, Any], names: Sequence[str]) -> Dict[str, Any]:
    return {k: tree[k] for k in names if k in tree}


def _state_subtree(opt_state: Dict[str, Any], names: Sequence[str]
                   ) -> Dict[str, Any]:
    keep = set(names)
    return {slot: {k: v for k, v in layers.items() if k in keep}
            for slot, layers in opt_state.items()}


def _splittable_state(opt_state) -> bool:
    """The bucketed update needs a {slot: {layer: {w: leaf}}} state tree
    it can partition by layer; anything else (custom optimizers) takes
    the serial path."""
    if not isinstance(opt_state, dict):
        return False
    return all(isinstance(layers, dict) for layers in opt_state.values())


def _pin_state(new_state, constraints, names) -> Any:
    """Per-bucket ZeRO pin: keep each updated moment on its assigned
    sharded placement (the lookup mirrors the executor's full-tree
    ``tree.map`` pin — same constraint objects, applied per leaf)."""
    import jax
    if constraints is None:
        return new_state
    out = {}
    for slot, layers in new_state.items():
        c_layers = constraints.get(slot, {}) \
            if isinstance(constraints, dict) else {}
        new_layers = {}
        for lname, ws in layers.items():
            c_ws = c_layers.get(lname, {}) \
                if isinstance(c_layers, dict) else {}
            if isinstance(ws, dict):
                new_layers[lname] = {
                    w: (jax.lax.with_sharding_constraint(leaf, c_ws[w])
                        if isinstance(c_ws, dict) and w in c_ws else leaf)
                    for w, leaf in ws.items()}
            else:
                new_layers[lname] = ws
        out[slot] = new_layers
    return out


def overlapped_update(optimizer, params, grads, opt_state, step,
                      schedule: OverlapSchedule, constraints=None):
    """The overlap path's replacement for the single
    ``optimizer.update`` call: per-bucket updates in launch order,
    chained by ``optimization_barrier`` tokens. Identity math — every
    leaf sees exactly the serial path's update — so the result is
    bit-exact with the serial step (pinned by
    ``tools/overlap_parity_smoke.py`` and ``tests/test_overlap.py``).

    ``constraints`` is the executor's ``opt_state_constraints`` pytree
    (ZeRO): applied per-bucket so each bucket's reduce-scatter/update/
    all-gather cluster is independently schedulable.
    """
    import jax

    if not _splittable_state(opt_state):
        new_params, new_state = optimizer.update(params, grads,
                                                 opt_state, step)
        if constraints is not None:
            new_state = jax.tree.map(jax.lax.with_sharding_constraint,
                                     new_state, constraints)
        return new_params, new_state

    claimed: set = set()
    new_params: Dict[str, Any] = {}
    new_state: Dict[str, Any] = {slot: {} for slot in opt_state}
    tokens: List[Any] = []
    for bucket in schedule.buckets:
        names = [n for n in bucket.members if n in params]
        if not names:
            continue
        claimed.update(names)
        sub_g = _subtree(grads, names)
        leaves, treedef = jax.tree.flatten(sub_g)
        if leaves:
            # the tokens ride as extra barrier operands: their outputs
            # are discarded, but the barrier op stays live through the
            # grad outputs, so every token must materialize before this
            # bucket's grads clear — the per-device total launch order
            barred = jax.lax.optimization_barrier(
                tuple(leaves) + tuple(tokens))
            leaves = list(barred[:len(leaves)])
            sub_g = jax.tree.unflatten(treedef, leaves)
        sub_p = _subtree(params, names)
        sub_s = _state_subtree(opt_state, names)
        np_, ns_ = optimizer.update(sub_p, sub_g, sub_s, step)
        ns_ = _pin_state(ns_, constraints, names)
        new_params.update(np_)
        for slot, layers in ns_.items():
            new_state.setdefault(slot, {}).update(layers)
        # launch tokens for the next bucket: depth >= 1 chains EVERY
        # updated param of this bucket (under ZeRO, each re-gathered
        # full param — the prefetch: every gather is scheduled one
        # bucket ahead of use, not just one representative leaf);
        # depth 0 chains one barred grad only, leaving gathers free to
        # sink to the step end
        if schedule.zero_prefetch >= 1:
            new_toks = [x for x in jax.tree.leaves(np_)
                        if hasattr(x, "size")]
            if new_toks:
                tokens = new_toks
        elif leaves:
            tokens = [leaves[0]]

    # unchained tail: params the schedule does not claim (bank /
    # place-group / pipeline-template group keys, importless extras) —
    # one standard update, exactly the serial semantics
    tail = [k for k in params if k not in claimed]
    if tail:
        np_, ns_ = optimizer.update(
            _subtree(params, tail), _subtree(grads, tail),
            _state_subtree(opt_state, tail), step)
        ns_ = _pin_state(ns_, constraints, tail)
        new_params.update(np_)
        for slot, layers in ns_.items():
            new_state.setdefault(slot, {}).update(layers)
    # non-dict slots (unsplittable leaves an exotic optimizer might
    # carry) were filtered by _splittable_state above; preserve slot
    # set exactly
    for slot in opt_state:
        new_state.setdefault(slot, {})
    return new_params, new_state
