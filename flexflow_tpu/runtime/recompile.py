"""Dynamic recompilation (reference ``RecompileState``,
``include/flexflow/recompile.h:26``, ``FFModel::recompile_on_condition``,
``src/runtime/model.cc:2422``).

The reference evaluates a user trigger each iteration and, when it fires,
runs an alter function that mutates the model (used for MoE cache swaps).
TPU analog: the alter function may mutate the FFModel/config/layers; the
executor is then rebuilt so the next step re-jits — XLA recompilation is
the analog of Legion re-mapping the task graph.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY


class RecompileState:
    """trigger() -> bool evaluated once per training iteration; when true,
    alter(ff) runs and the jitted step is invalidated."""

    def __init__(self, trigger: Callable[["RecompileState"], bool],
                 alter: Callable[["RecompileState"], None], ff=None):
        self.trigger = trigger
        self.alter = alter
        self.ff = ff
        self.recompilations = 0
        # free-form slots the reference exposes for trigger bookkeeping
        self.last_metric: Optional[float] = None
        self.iteration = 0

    def step(self, ff) -> bool:
        """Evaluate once per iteration; returns True if a recompile ran."""
        self.iteration += 1
        if not self.trigger(self):
            return False
        self.alter(self)
        self.recompilations += 1
        # recompile events: an instant in the trace (the next step's
        # span shows phase="compile" again) + a scrapeable counter
        obs_events.instant("runtime.recompile", iteration=self.iteration,
                           recompilations=self.recompilations)
        obs_events.counter("executor.recompiles")
        REGISTRY.counter(
            "ff_recompiles_total",
            "Dynamic recompilations (recompile_on_condition)").inc()
        # invalidate jitted steps; params/opt state survive (the graph may
        # have changed shape-compatibly — the user's responsibility, as in
        # the reference)
        if ff.executor is not None:
            ff.executor._train_step = None
            ff.executor._eval_step = None
            ff.executor._forward_fn = None
        return True
