"""ZeRO-1-style optimizer-state sharding (beyond-reference capability).

The reference keeps full optimizer state on every data-parallel replica
(``src/runtime/optimizer_kernel.cu`` allocates V/M per GPU at full weight
size). On TPU the idiomatic ZeRO-1 is a *sharding annotation*: place each
moment tensor sharded over the mesh axes its weight is replicated on, and
GSPMD turns the update into reduce-scatter(grad) + sharded update +
all-gather(param delta) automatically — no hand-written partitioning of
the optimizer loop.

Memory effect: Adam's m/v (2x params) and SGD momentum (1x) shrink by the
data-parallel degree. Enabled by ``FFConfig.shard_optimizer_states``
(flag ``--shard-optimizer-states`` / ``--zero``).
"""
from __future__ import annotations

from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def _spec_tuple(x) -> list:
    spec = getattr(getattr(x, "sharding", None), "spec", None)
    out = list(spec) if spec is not None else []
    out += [None] * (x.ndim - len(out))
    return out


def zero_sharding(x, axis_sizes) -> "P | None":
    """ZeRO spec for one state leaf: shard the largest dim that is not
    already sharded over the largest free (unused-by-this-leaf) mesh
    axes that divide it. None when nothing can be (or need be) sharded."""
    if getattr(x, "ndim", 0) == 0:
        return None
    spec = _spec_tuple(x)
    used = set()
    for s in spec:
        if s is None:
            continue
        used.update((s,) if isinstance(s, str) else tuple(s))
    free = sorted(((a, sz) for a, sz in axis_sizes.items()
                   if a not in used and sz > 1),
                  key=lambda t: -t[1])
    if not free:
        return None
    # pick the dim that absorbs the LARGEST total degree from the free
    # axes (not just the largest dim — e.g. shape (12, 8) with free
    # {4, 2} shards dim 1 by 8, not dim 0 by 4)
    best_dim, best_axes, best_deg = None, None, 1
    for d in range(x.ndim):
        if spec[d] is not None:
            continue
        axes, rem, deg = [], x.shape[d], 1
        for a, sz in free:
            if rem % sz == 0:
                axes.append(a)
                rem //= sz
                deg *= sz
        if deg > best_deg or (deg == best_deg and best_dim is not None
                              and x.shape[d] > x.shape[best_dim]):
            best_dim, best_axes, best_deg = d, axes, deg
    if best_dim is None or not best_axes:
        return None
    spec[best_dim] = tuple(best_axes) if len(best_axes) > 1 \
        else best_axes[0]
    return P(*spec)


def shard_optimizer_state(opt_state: Any, dmesh) -> Any:
    """Re-place every optimizer-state leaf with its ZeRO sharding (leaves
    with no free axis or no divisible dim stay as initialized)."""
    mesh = dmesh.mesh
    axis_sizes = dict(dmesh.axis_sizes)

    def reshard(x):
        spec = zero_sharding(x, axis_sizes)
        if spec is None:
            return x
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(reshard, opt_state)


def state_constraints(opt_state: Any):
    """Pytree of NamedShardings matching the current placements — the
    executor pins the updated state to these inside the jitted step so
    XLA cannot silently replicate it back."""
    return jax.tree.map(lambda x: x.sharding, opt_state)
