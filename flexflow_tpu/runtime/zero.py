"""ZeRO-1-style optimizer-state sharding (beyond-reference capability).

The reference keeps full optimizer state on every data-parallel replica
(``src/runtime/optimizer_kernel.cu`` allocates V/M per GPU at full weight
size). On TPU the idiomatic ZeRO-1 is a *sharding annotation*: place each
moment tensor sharded over the mesh axes its weight is replicated on, and
GSPMD turns the update into reduce-scatter(grad) + sharded update +
all-gather(param delta) automatically — no hand-written partitioning of
the optimizer loop.

Memory effect: Adam's m/v (2x params) and SGD momentum (1x) shrink by the
data-parallel degree.

Two entry modes (PAPERS.md, arXiv 2004.13336):

  - **uniform** (``FFConfig.shard_optimizer_states``, flag
    ``--shard-optimizer-states`` / ``--zero``): every leaf takes its
    :func:`zero_sharding` spec — the pre-search-era all-or-nothing
    behavior, pinned bit-identical;
  - **per-parameter** (``FFConfig.zero_policy``, ``search/zero_plan.py``):
    the cost model scores each parameter's update path (replicated
    all-reduce vs reduce-scatter + sharded update + all-gather) and the
    adopted :class:`ZeroAssignment` names exactly which leaves shard and
    onto which axes. The assignment serializes with the strategy, is
    statically checked by ``analysis/plan_verifier`` (a moment sharded
    over its weight's own mesh axis is a compile-time error), and rides
    the checkpoint manifest so a partially-sharded state round-trips
    restores into any world size or assignment.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def _spec_tuple(x) -> list:
    spec = getattr(getattr(x, "sharding", None), "spec", None)
    out = list(spec) if spec is not None else []
    out += [None] * (x.ndim - len(out))
    return out


def _entries_of(spec, rank: int) -> List[Optional[Tuple[str, ...]]]:
    """Normalize a PartitionSpec / tuple / JSON-list spec to per-dim
    axis tuples (None = unsharded), padded to ``rank``."""
    out: List[Optional[Tuple[str, ...]]] = []
    for e in tuple(spec or ()):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(tuple(e))
        else:
            out.append((e,))
    out += [None] * (rank - len(out))
    return out[:rank]


def spec_axes(spec) -> Tuple[str, ...]:
    """Every mesh axis a spec consumes (flattened, in order)."""
    axes: List[str] = []
    for e in _entries_of(spec, len(tuple(spec or ()))):
        if e:
            axes.extend(e)
    return tuple(axes)


def spec_degree(spec, axis_sizes: Dict[str, int]) -> int:
    """Total shard degree of a spec (product of its axes' sizes) —
    THE shared definition (analysis/plan_verifier and search/zero_plan
    both price from it)."""
    deg = 1
    for a in spec_axes(spec):
        deg *= axis_sizes.get(a, 1)
    return deg


def zero_spec(shape: Sequence[int], weight_spec,
              axis_sizes: Dict[str, int]) -> Optional[P]:
    """ZeRO spec for one state leaf of ``shape`` whose weight is placed
    by ``weight_spec``: shard the dim that absorbs the LARGEST total
    degree from the free (unused-by-this-leaf) mesh axes that divide it.
    None when nothing can be (or need be) sharded.

    Shape-level core of :func:`zero_sharding` — usable at search/verify
    time with no live array behind it. By construction the returned
    spec never reuses an axis the weight's own spec consumes (the
    invariant ``analysis/plan_verifier``'s zero check enforces on
    serialized assignments).
    """
    shape = tuple(int(s) for s in shape)
    ndim = len(shape)
    if ndim == 0:
        return None
    spec = _entries_of(weight_spec, ndim)
    used: set = set()
    for e in spec:
        if e:
            used.update(e)
    free = sorted(((a, sz) for a, sz in axis_sizes.items()
                   if a not in used and sz > 1),
                  key=lambda t: -t[1])
    if not free:
        return None
    # pick the dim that absorbs the LARGEST total degree from the free
    # axes (not just the largest dim — e.g. shape (12, 8) with free
    # {4, 2} shards dim 1 by 8, not dim 0 by 4)
    best_dim, best_axes, best_deg = None, None, 1
    for d in range(ndim):
        if spec[d] is not None:
            continue
        axes, rem, deg = [], shape[d], 1
        for a, sz in free:
            if rem % sz == 0:
                axes.append(a)
                rem //= sz
                deg *= sz
        if deg > best_deg or (deg == best_deg and best_dim is not None
                              and shape[d] > shape[best_dim]):
            best_dim, best_axes, best_deg = d, axes, deg
    if best_dim is None or not best_axes:
        return None
    out = [e if e is None else (e[0] if len(e) == 1 else tuple(e))
           for e in spec]
    out[best_dim] = tuple(best_axes) if len(best_axes) > 1 \
        else best_axes[0]
    return P(*out)


def zero_sharding(x, axis_sizes) -> "P | None":
    """ZeRO spec for one live state leaf: shard the largest dim that is
    not already sharded over the largest free (unused-by-this-leaf) mesh
    axes that divide it. None when nothing can be (or need be) sharded."""
    if getattr(x, "ndim", 0) == 0:
        return None
    return zero_spec(x.shape, tuple(_spec_tuple(x)), axis_sizes)


def opt_slots(optimizer) -> int:
    """Optimizer-state leaves per parameter: Adam-family keeps two
    moments, momentum-SGD one, plain SGD none. Unknown optimizers are
    costed at two (conservative). Shared by the ZeRO planner
    (``search/zero_plan.py``) and the plan verifier's memory envelope."""
    if optimizer is None:
        return 2
    name = type(optimizer).__name__.lower()
    if "adam" in name or "lamb" in name:
        return 2
    if "sgd" in name:
        return 1 if getattr(optimizer, "momentum", 0.0) else 0
    return 2


# ---------------------------------------------------------------------------
# per-parameter assignment (arXiv 2004.13336 in the search space)
# ---------------------------------------------------------------------------
class ZeroAssignment:
    """Per-parameter optimizer-state sharding decisions.

    ``decisions`` maps layer name -> weight name -> a record dict::

        {"spec": <PartitionSpec JSON form or None>,   # None = replicate
         "degree": int,            # total absorbed shard degree
         "bytes_saved": float,     # per-device opt-state bytes saved
         "overhead_s": float,      # predicted marginal collective cost
         "replicated_s": float}    # predicted replicated-update cost

    The uniform ``--zero`` flag is representable as the "all" assignment
    (:meth:`uniform`), which reproduces :func:`zero_sharding` leaf for
    leaf — the pre-per-parameter behavior. Serializes with the strategy
    (``search/serialization.py``) and into the checkpoint meta.
    """

    def __init__(self, decisions: Optional[Dict[str, Dict[str, Dict]]]
                 = None, policy: str = "auto"):
        self.decisions: Dict[str, Dict[str, Dict]] = decisions or {}
        self.policy = policy

    # -- queries -------------------------------------------------------
    def spec_for(self, layer: str, wname: str) -> Optional[P]:
        rec = self.decisions.get(layer, {}).get(wname)
        if rec is None or rec.get("spec") is None:
            return None
        return P(*[tuple(e) if isinstance(e, list) else e
                   for e in rec["spec"]])

    def degree_for(self, layer: str, wname: str) -> int:
        rec = self.decisions.get(layer, {}).get(wname)
        return int(rec.get("degree", 1)) if rec else 1

    def sharded_params(self) -> List[Tuple[str, str]]:
        return [(l, w) for l, ws in self.decisions.items()
                for w, rec in ws.items() if rec.get("spec") is not None]

    def __len__(self) -> int:
        return sum(len(ws) for ws in self.decisions.values())

    def __bool__(self) -> bool:
        return len(self.sharded_params()) > 0

    def is_uniform(self) -> bool:
        """True when every recorded parameter shards (no per-parameter
        trade was made) — the audit record distinguishes a genuinely
        non-uniform searched assignment from an all-shard one."""
        return len(self.sharded_params()) == len(self)

    # -- construction --------------------------------------------------
    @classmethod
    def uniform(cls, params_meta: Dict[str, Dict[str, Tuple]],
                strategy, axis_sizes: Dict[str, int]) -> "ZeroAssignment":
        """The "all" assignment: every leaf takes its :func:`zero_spec`
        against its weight's strategy placement (bit-identical to the
        uniform ``--zero`` flag's per-leaf :func:`zero_sharding`)."""
        out: Dict[str, Dict[str, Dict]] = {}
        for lname, ws in params_meta.items():
            os_ = getattr(strategy, "ops", {}).get(lname)
            for wname, shape in ws.items():
                wspec = os_.weights.get(wname) if os_ is not None else None
                sp = zero_spec(shape, wspec, axis_sizes)
                deg = 1
                if sp is not None:
                    for a in spec_axes(sp):
                        if a not in spec_axes(wspec):
                            deg *= axis_sizes.get(a, 1)
                out.setdefault(lname, {})[wname] = {
                    "spec": None if sp is None else
                    [list(e) if isinstance(e, tuple) else e for e in sp],
                    "degree": deg, "bytes_saved": 0.0,
                    "overhead_s": 0.0, "replicated_s": 0.0}
        return cls(out, policy="all")

    # -- serialization -------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {"policy": self.policy, "decisions": self.decisions}

    @classmethod
    def from_json(cls, doc: Optional[Dict[str, Any]]
                  ) -> Optional["ZeroAssignment"]:
        if not doc:
            return None
        return cls(dict(doc.get("decisions", {})),
                   policy=str(doc.get("policy", "auto")))

    def summary(self) -> Dict[str, Any]:
        sharded = self.sharded_params()
        return {
            "policy": self.policy,
            "n_params": len(self),
            "n_sharded": len(sharded),
            "uniform": self.is_uniform(),
            "bytes_saved_total": sum(
                rec.get("bytes_saved", 0.0)
                for ws in self.decisions.values() for rec in ws.values()),
            "overhead_s_total": sum(
                rec.get("overhead_s", 0.0)
                for ws in self.decisions.values()
                for rec in ws.values() if rec.get("spec") is not None),
        }


# ---------------------------------------------------------------------------
# state placement
# ---------------------------------------------------------------------------
def _map_state_leaves(opt_state: Any, fn):
    """Apply ``fn(layer, wname, leaf)`` to every optimizer-state leaf.
    State trees are ``{slot: {layer: {wname: leaf}}}`` (Adam m/v, SGD v);
    unrecognized structures fall back to identity on the odd leaves."""
    if not isinstance(opt_state, dict):
        return opt_state
    out = {}
    for slot, layers in opt_state.items():
        if not isinstance(layers, dict):
            out[slot] = layers
            continue
        new_layers = {}
        for lname, ws in layers.items():
            if not isinstance(ws, dict):
                new_layers[lname] = ws
                continue
            new_layers[lname] = {w: fn(lname, w, leaf)
                                 for w, leaf in ws.items()}
        out[slot] = new_layers
    return out


def shard_optimizer_state(opt_state: Any, dmesh,
                          assignment: Optional[ZeroAssignment] = None
                          ) -> Any:
    """Re-place optimizer-state leaves with their ZeRO shardings.

    ``assignment=None`` is the uniform path (the ``--zero`` flag,
    pinned): every leaf takes its :func:`zero_sharding` spec; leaves
    with no free axis or no divisible dim stay as initialized. With an
    assignment, only the leaves it shards move — everything else keeps
    its replicated placement."""
    mesh = dmesh.mesh
    axis_sizes = dict(dmesh.axis_sizes)

    if assignment is None:
        def reshard(x):
            spec = zero_sharding(x, axis_sizes)
            if spec is None:
                return x
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree.map(reshard, opt_state)

    def place(lname, wname, leaf):
        spec = assignment.spec_for(lname, wname)
        if spec is None:
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return _map_state_leaves(opt_state, place)


def state_constraints(opt_state: Any):
    """Pytree of NamedShardings matching the current placements — the
    executor pins the updated state to these inside the jitted step so
    XLA cannot silently replicate it back."""
    return jax.tree.map(lambda x: x.sharding, opt_state)


def state_sharding_doc(opt_state: Any) -> Dict[str, Any]:
    """Per-leaf sharding record for the checkpoint meta: key-path ->
    PartitionSpec JSON form (None = replicated / unsharded host leaf).
    Restore re-places onto the LIVE model's shardings — this record is
    the audit trail proving what placement the state was saved under,
    and lets tooling reason about a partially-sharded checkpoint
    without loading a byte of it."""
    from jax.tree_util import keystr, tree_flatten_with_path
    leaves, _ = tree_flatten_with_path(opt_state)
    out: Dict[str, Any] = {}
    for path, leaf in leaves:
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        out[keystr(path)] = None if spec is None else [
            list(e) if isinstance(e, tuple) else e for e in spec]
    return out
