"""Checkpoint / resume.

The reference has NO built-in checkpointing (SURVEY.md §5): users hand-roll
NumPy round-trips through ``Parameter.get_weights/set_weights``
(``flexflow_cffi.py:851-886``). The TPU rebuild makes checkpointing a
first-class subsystem on orbax: sharded, async-capable saves of the full
training state (params, optimizer state, mutable op state, step) plus the
searched parallelization strategy, so a resumed run restores both the
weights AND the parallelization decision (the reference's closest analog is
its separate ``--export``/``--import`` strategy files).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def _tree_to_numpy(tree):
    """Fetch a pytree to host numpy.

    Multi-controller: arrays sharded across processes are not locally
    addressable; ``process_allgather`` (a COLLECTIVE — every process
    must call this) assembles the global value on each host. Callers
    then write on process 0 only.
    """
    import jax

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    return jax.tree.map(fetch, tree)


class CheckpointManager:
    """Orbax-backed checkpoint manager with a plain-numpy fallback.

    Layout: ``<dir>/<step>/state`` (orbax PyTree) + ``<dir>/<step>/meta.json``
    (step, strategy document, user metadata).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
        except Exception:  # orbax unavailable: numpy fallback
            self._ocp = None

    # ------------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def all_steps(self):
        out = []
        for d in os.listdir(self.directory) if os.path.isdir(
                self.directory) else []:
            if d.isdigit() and os.path.exists(
                    os.path.join(self.directory, d, "meta.json")):
                out.append(int(d))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------------
    def save(self, step: int, state: Dict[str, Any],
             metadata: Optional[Dict[str, Any]] = None):
        """state: arbitrary pytree (params/opt_state/op state).

        Collective in a multi-controller world: EVERY process must call
        (cross-host shards gather collectively); process 0 writes."""
        import jax
        host_state = _tree_to_numpy(state)  # collective gather
        if jax.process_index() != 0:
            return
        sdir = self._step_dir(step)
        os.makedirs(sdir, exist_ok=True)
        path = os.path.join(sdir, "state")
        # orbax synchronizes across ALL jax processes inside save(); with
        # a single writer that barrier would deadlock — multi-controller
        # saves use the plain local writer (the state is already host
        # numpy here)
        if self._ocp is not None and jax.process_count() == 1:
            with self._ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(path, host_state, force=True)
        else:
            import pickle
            with open(path + ".pkl", "wb") as f:
                pickle.dump(host_state, f)
        with open(os.path.join(sdir, "meta.json"), "w") as f:
            json.dump({"step": step, **(metadata or {})}, f)
        self._gc()

    def restore(self, step: Optional[int] = None):
        """Returns (state, metadata) for `step` (default: latest)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        sdir = self._step_dir(step)
        path = os.path.join(sdir, "state")
        if self._ocp is not None and os.path.isdir(path):
            with self._ocp.PyTreeCheckpointer() as ckptr:
                state = ckptr.restore(path)
        else:
            import pickle
            with open(path + ".pkl", "rb") as f:
                state = pickle.load(f)
        with open(os.path.join(sdir, "meta.json")) as f:
            meta = json.load(f)
        return state, meta

    def _gc(self):
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            import shutil
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)


# ---------------------------------------------------------------------------
# FFModel-level helpers (wired as methods on FFModel)
# ---------------------------------------------------------------------------
def save_model_checkpoint(ff, directory: str, step: Optional[int] = None,
                          max_to_keep: int = 3):
    """Save params + optimizer state + op state + step + strategy."""
    from ..search.serialization import _spec_to_json
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    step = int(step if step is not None else ff._step)
    strategy_doc = None
    if getattr(ff, "strategy", None) is not None:
        strategy_doc = {
            name: {"outputs": [_spec_to_json(s) for s in os_.outputs],
                   "weights": {k: _spec_to_json(v)
                               for k, v in os_.weights.items()}}
            for name, os_ in ff.strategy.ops.items()}
    mgr.save(step,
             {"params": ff.params, "opt_state": ff.opt_state,
              "state": ff.state},
             metadata={"strategy": strategy_doc,
                       "batch_size": ff.config.batch_size})
    return mgr


def restore_model_checkpoint(ff, directory: str,
                             step: Optional[int] = None) -> int:
    """Restore training state into a compiled FFModel; returns the step.
    Restored arrays are re-placed with the model's current shardings (so a
    checkpoint taken under one strategy resumes under another — strategy
    migration the reference cannot do)."""
    import jax
    mgr = CheckpointManager(directory)
    state, meta = mgr.restore(step)

    def replace(tmpl, new):
        return jax.tree.map(
            lambda t, n: jax.device_put(
                np.asarray(n).astype(t.dtype).reshape(t.shape),
                t.sharding if hasattr(t, "sharding") else None),
            tmpl, new)

    ff.params = replace(ff.params, state["params"])
    ff.opt_state = replace(ff.opt_state, state["opt_state"])
    if state.get("state"):
        ff.state = replace(ff.state, state["state"])
    ff._step = int(meta["step"])
    return ff._step
