"""Checkpoint / resume with verified atomic saves.

The reference has NO built-in checkpointing (SURVEY.md §5): users hand-roll
NumPy round-trips through ``Parameter.get_weights/set_weights``
(``flexflow_cffi.py:851-886``). The TPU rebuild makes checkpointing a
first-class subsystem: sharded, async-capable saves of the full training
state (params, optimizer state, mutable op state, step) plus the searched
parallelization strategy, so a resumed run restores both the weights AND
the parallelization decision.

Durability contract (resilience subsystem, ISSUE 3):

  - **atomic**: each step is written into a ``tmp-<step>`` staging dir
    (state payload, then ``manifest.json``, then ``meta.json``, each
    fsynced) and published with one ``os.replace`` rename — a crash at
    any point leaves either the previous complete step or an ignored
    staging dir, never a half-step that lists as valid;
  - **verified**: ``manifest.json`` records every state leaf's shape,
    dtype, and CRC32; restore re-hashes the loaded leaves and refuses a
    silently-corrupted step (:class:`CheckpointCorruption`);
  - **self-healing restore**: ``restore()`` with no explicit step walks
    steps newest-first and falls back past corrupt/partial ones (with a
    warning and a counter) to the newest valid step;
  - **async-capable**: ``save(..., blocking=False)`` does the collective
    host gather in the caller (it must run on every process) and the
    file writes on a background thread, so the train loop overlaps the
    checkpoint I/O (bench's recovery leg pins steady-state overhead).

Multi-host worlds (ISSUE 7) extend the same contract per-world with a
**two-phase commit** (format v2, ``manifest.json`` carries
``"format": "multihost"``):

  1. **stage**: every rank pickles ONLY the state blocks its own devices
     hold (no collective gather — each leaf is split by the sharding's
     owner map, replicated leaves are written once by their lowest
     owning rank) into ``tmp-<step>/shard-<rank>.pkl`` + an fsynced
     ``shard-<rank>.ok.json`` sidecar recording the file's CRC32;
  2. **barrier** (bounded, ``resilience/coord.py`` — a dead rank raises
     :class:`~flexflow_tpu.resilience.coord.RankFailure` instead of
     hanging the save);
  3. **commit**: rank 0 alone writes ``manifest.json`` naming every
     shard file + CRC, then ``meta.json``, then publishes the step with
     one atomic rename. A crash at ANY point — any rank, either phase —
     leaves either a fully-restorable committed step or cleanly-ignored
     ``tmp-*`` staging debris; a torn-but-listed step cannot exist.

Restore in a multi-host world reaches **quorum**: each rank publishes
the set of steps it can locally verify (manifest + every shard CRC) to
the coordination KV store, and all ranks deterministically adopt the
newest step EVERY rank verified, falling back past steps any rank finds
corrupt. A world of a different size (elastic shrink/grow) restores the
same files: every rank assembles the full host state from all shard
files and re-places it through ``parallel/reshard.place_host``.
Shard files live under the checkpoint directory, which multi-host
deployments must put on storage every rank can read (tests use /tmp).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY

log = logging.getLogger("flexflow_tpu")


class CheckpointCorruption(RuntimeError):
    """A checkpoint step failed integrity verification on restore."""


def _tree_to_numpy(tree):
    """Fetch a pytree to host numpy.

    Multi-controller: arrays sharded across processes are not locally
    addressable; ``process_allgather`` (a COLLECTIVE — every process
    must call this) assembles the global value on each host. Callers
    then write on process 0 only.
    """
    import jax

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    return jax.tree.map(fetch, tree)


def _flat_leaves(tree) -> List[Tuple[str, np.ndarray]]:
    """(key-path, numpy leaf) pairs in deterministic tree order."""
    from jax.tree_util import keystr, tree_flatten_with_path
    leaves, _ = tree_flatten_with_path(tree)
    return [(keystr(path), np.asarray(leaf)) for path, leaf in leaves]


def _crc32(arr: np.ndarray) -> int:
    """CRC32 of a leaf's raw bytes without materializing a copy: crc32
    reads the contiguous array buffer directly (matters on multi-GB
    states — this runs on every save AND restore). Exotic dtypes the
    buffer protocol refuses (e.g. ml_dtypes bf16) fall back to
    tobytes()."""
    a = np.ascontiguousarray(arr)
    try:
        buf = a.data
    except (ValueError, BufferError):
        buf = a.tobytes()
    return zlib.crc32(buf) & 0xFFFFFFFF


def _manifest_of(host_state) -> Dict[str, Any]:
    """Per-leaf integrity manifest: shape/dtype/CRC32 of the raw bytes."""
    leaves = {}
    for key, arr in _flat_leaves(host_state):
        leaves[key] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "crc32": _crc32(arr),
        }
    return {"version": 1, "leaves": leaves}


def _verify_manifest(state, manifest: Dict[str, Any], where: str) -> None:
    """Raise :class:`CheckpointCorruption` on any leaf mismatch."""
    want = manifest.get("leaves", {})
    got = dict(_flat_leaves(state))
    if set(want) != set(got):
        missing = sorted(set(want) - set(got))[:4]
        extra = sorted(set(got) - set(want))[:4]
        raise CheckpointCorruption(
            f"{where}: leaf set mismatch (missing={missing}, "
            f"unexpected={extra})")
    for key, rec in want.items():
        arr = got[key]
        if list(arr.shape) != list(rec["shape"]) \
                or str(arr.dtype) != rec["dtype"]:
            raise CheckpointCorruption(
                f"{where}: leaf {key} is {arr.dtype}{list(arr.shape)}, "
                f"manifest says {rec['dtype']}{rec['shape']}")
        crc = _crc32(arr)
        if crc != rec["crc32"]:
            raise CheckpointCorruption(
                f"{where}: leaf {key} CRC32 {crc:#010x} != manifest "
                f"{rec['crc32']:#010x} (bit rot or truncated write)")


class ShardBlocks:
    """One leaf of the multi-host shard tree: the global array metadata
    plus the blocks THIS rank owns. Blocks are ``(index, ndarray)``
    where ``index`` is a per-dim ``[start, stop]`` list into the global
    shape. Picklable by construction (plain python + numpy)."""

    __slots__ = ("shape", "dtype", "blocks")

    def __init__(self, shape, dtype, blocks):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = str(dtype)
        self.blocks = blocks

    def __getstate__(self):
        return (self.shape, self.dtype, self.blocks)

    def __setstate__(self, s):
        self.shape, self.dtype, self.blocks = s


def _norm_index(idx, shape) -> List[List[int]]:
    """A shard's index tuple as concrete [start, stop] per dim."""
    out = []
    for r, dim in zip(idx, shape):
        if isinstance(r, slice):
            out.append([int(r.start or 0),
                        int(dim if r.stop is None else r.stop)])
        else:  # integer index — never produced by shardings we emit
            out.append([int(r), int(r) + 1])
    # rank-0 dims beyond the index tuple are unsharded
    out.extend([0, int(dim)] for dim in shape[len(idx):])
    return out


def _owned_blocks(x) -> ShardBlocks:
    """The blocks of leaf ``x`` this process must persist. Each distinct
    shard index is owned by exactly one device — the lowest
    ``(process_index, id)`` among the devices holding it — so replicated
    leaves are written once (by rank 0's lowest device), sharded leaves
    exactly partition across the world, and no byte is written twice."""
    import jax
    if not isinstance(x, jax.Array) or not hasattr(x, "sharding"):
        arr = np.asarray(x)
        blocks = []
        if jax.process_index() == 0:
            blocks = [(_norm_index((), arr.shape), arr)]
        return ShardBlocks(arr.shape, arr.dtype, blocks)
    shape = x.shape
    owner: Dict[str, Any] = {}
    for dev, idx in x.sharding.devices_indices_map(shape).items():
        key = json.dumps(_norm_index(idx, shape))
        cur = owner.get(key)
        rank = (dev.process_index, dev.id)
        if cur is None or rank < cur:
            owner[key] = rank
    me = jax.process_index()
    blocks = []
    for shard in x.addressable_shards:
        nidx = _norm_index(shard.index, shape)
        key = json.dumps(nidx)
        if owner.get(key) == (shard.device.process_index,
                              shard.device.id) \
                and shard.device.process_index == me:
            blocks.append((nidx, np.asarray(shard.data)))
    return ShardBlocks(shape, np.dtype(x.dtype), blocks)


def _assemble_blocks(leaves) -> np.ndarray:
    """Merge one leaf's ShardBlocks from every rank file into the global
    host array."""
    first = leaves[0]
    out = np.empty(first.shape, dtype=np.dtype(first.dtype))
    filled = 0
    for lf in leaves:
        for idx, block in lf.blocks:
            sl = tuple(slice(a, b) for a, b in idx)
            out[sl] = np.asarray(block).reshape(
                tuple(b - a for a, b in idx))
            filled += int(np.prod([b - a for a, b in idx]) or 1)
    if filled < int(np.prod(first.shape) or 1):
        raise CheckpointCorruption(
            f"shard blocks cover {filled} of "
            f"{int(np.prod(first.shape) or 1)} elements of a "
            f"{first.dtype}{list(first.shape)} leaf — missing shard "
            f"data (wrong world size at save, or a lost shard file)")
    return out


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


class CheckpointManager:
    """Orbax-backed checkpoint manager with a plain-numpy fallback.

    Layout: ``<dir>/<step>/state`` (orbax PyTree) or ``state.pkl``
    (numpy fallback) + ``<dir>/<step>/manifest.json`` (per-leaf
    shape/dtype/CRC32) + ``<dir>/<step>/meta.json`` (step, strategy
    document, user metadata). In-progress saves stage under
    ``<dir>/tmp-<step>`` and are published by rename.
    """

    #: below this total leaf size the plain numpy writer is used even
    #: when orbax is available: orbax's fixed per-save machinery
    #: (tensorstore setup, barriers, metadata commits — ~200 ms) earns
    #: its keep on large sharded states, not on a few MB, and the
    #: manifest provides integrity either way (bench's recovery leg
    #: pins the steady-state async overhead at <= 5%)
    ORBAX_MIN_BYTES = 64 << 20

    #: bound on joining an in-flight async writer thread (see
    #: :meth:`wait`) — generous for slow network filesystems, finite so
    #: wedged storage surfaces as TimeoutError instead of a hang
    WAIT_TIMEOUT_S = 600.0

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False, writer: str = "auto"):
        if writer not in ("auto", "orbax", "numpy"):
            raise ValueError(
                f"writer must be 'auto', 'orbax', or 'numpy', "
                f"got {writer!r}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.writer = writer
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None
        self._restore_seq = 0  # multi-host quorum-round sequencer
        self._save_seq = 0     # multi-host save-barrier sequencer
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
        except Exception:  # orbax unavailable: numpy fallback
            self._ocp = None

    # ------------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def all_steps(self):
        """Steps with a complete, *readable* meta.json. Orphaned step
        dirs (no meta — a pre-hardening partial save) and truncated
        metas are skipped with a warning instead of listing as valid
        and blowing up restore later."""
        out = []
        if not os.path.isdir(self.directory):
            return out
        for d in os.listdir(self.directory):
            if not d.isdigit():
                continue  # tmp-<step> staging dirs and strangers
            meta = os.path.join(self.directory, d, "meta.json")
            try:
                with open(meta) as f:
                    json.load(f)
            except (OSError, ValueError) as e:
                log.warning(
                    "checkpoint %s/%s: unreadable meta.json (%s) — "
                    "skipping step", self.directory, d, e)
                continue
            out.append(int(d))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------------
    def save(self, step: int, state: Dict[str, Any],
             metadata: Optional[Dict[str, Any]] = None,
             blocking: Optional[bool] = None):
        """state: arbitrary pytree (params/opt_state/op state).

        Collective in a multi-controller world: EVERY process must call
        (with the same ``blocking``). Multi-host saves take the
        two-phase sharded path — each rank stages only its own blocks,
        no cross-host gather ever happens. ``blocking=False`` (or
        ``async_save=True`` at construction) returns after the local
        shard extraction and runs the writes (and, multi-host, the
        commit barriers) on a background thread — call :meth:`wait` (or
        any later save/restore) to join."""
        import jax
        multihost = jax.process_count() > 1
        if multihost:
            # local shard extraction — pure host work, no collectives
            host_state = jax.tree.map(_owned_blocks, state)
        else:
            host_state = _tree_to_numpy(state)
        # one write in flight at a time (bounded: a wedged writer
        # thread must surface as an error, not hang every later save)
        self.wait(timeout_s=self.WAIT_TIMEOUT_S)
        if blocking is None:
            blocking = not self.async_save
        meta = dict(metadata or {})
        write = self._write_multihost if multihost else self._write_step
        if blocking:
            write(step, host_state, meta)
        else:
            def run():
                try:
                    write(step, host_state, meta)
                except BaseException as e:  # surfaced by wait()
                    self._pending_error = e
            t = threading.Thread(target=run, name=f"ckpt-save-{step}",
                                 daemon=True)
            self._pending = t
            t.start()

    def wait(self, timeout_s: Optional[float] = None) -> None:
        """Join an in-flight async save; re-raise its error, if any.

        Bounded: ``timeout_s`` (default :data:`WAIT_TIMEOUT_S`) caps the
        join — a writer thread wedged on dead storage raises
        ``TimeoutError`` instead of hanging every later save/restore
        (and the train loop with them) forever."""
        t = self._pending
        if t is not None:
            t.join(self.WAIT_TIMEOUT_S if timeout_s is None
                   else timeout_s)
            if t.is_alive():
                raise TimeoutError(
                    f"checkpoint writer thread {t.name!r} still running "
                    f"after {timeout_s or self.WAIT_TIMEOUT_S:.0f}s — "
                    f"storage wedged?")
            self._pending = None
        if self._pending_error is not None:
            err, self._pending_error = self._pending_error, None
            raise err

    def _write_step(self, step: int, host_state, metadata: Dict[str, Any]):
        t0 = time.perf_counter()
        tmp = os.path.join(self.directory, f"tmp-{step}")
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        path = os.path.join(tmp, "state")
        manifest = _manifest_of(host_state)
        total_bytes = sum(
            int(np.prod(rec["shape"]) or 1) * np.dtype(rec["dtype"]).itemsize
            for rec in manifest["leaves"].values())
        # orbax synchronizes across ALL jax processes inside save(); with
        # a single writer that barrier would deadlock — multi-controller
        # saves use the plain local writer (the state is already host
        # numpy here). Small states skip orbax too (ORBAX_MIN_BYTES).
        import jax
        use_orbax = (self._ocp is not None and jax.process_count() == 1
                     and self.writer != "numpy"
                     and (self.writer == "orbax"
                          or total_bytes >= self.ORBAX_MIN_BYTES))
        if use_orbax:
            with self._ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(path, host_state, force=True)
        else:
            import pickle
            with open(path + ".pkl", "wb") as f:
                pickle.dump(host_state, f)
                f.flush()
                os.fsync(f.fileno())
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # meta last: its presence inside the staging dir marks the
        # payload complete; the rename below publishes everything at once
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, **metadata}, f)
            f.flush()
            os.fsync(f.fileno())
        sdir = self._step_dir(step)
        if os.path.isdir(sdir):
            import shutil
            shutil.rmtree(sdir, ignore_errors=True)
        os.replace(tmp, sdir)
        _fsync_dir(self.directory)
        self._gc()
        # fault-injection hook (resilience/faults.py): checkpoint
        # corruption clauses target the just-published step
        from ..resilience import faults
        if faults.active():
            faults.maybe_corrupt_checkpoint(step, sdir)
        from ..resilience import status
        status.record_checkpoint(step)
        REGISTRY.counter("ff_checkpoint_saves_total",
                         "Completed checkpoint saves").inc()
        REGISTRY.gauge("ff_checkpoint_last_step",
                       "Step of the newest completed checkpoint"
                       ).set(float(step))
        obs_events.record_span("ckpt.save", t0,
                               time.perf_counter() - t0, step=step)

    # ------------------------------------------------------------------
    # multi-host two-phase commit (format v2)
    # ------------------------------------------------------------------
    def _write_multihost(self, step: int, shard_tree,
                         metadata: Dict[str, Any]) -> None:
        """Stage this rank's shard + sidecar, bounded-barrier, then rank
        0 alone commits manifest + meta + atomic rename. Runs on EVERY
        rank (possibly on the async writer thread)."""
        import jax
        from ..resilience import coord, faults
        c = coord.ensure_started()
        rank, world = jax.process_index(), jax.process_count()
        t0 = time.perf_counter()
        # barrier ids must be fresh per save: saves are collective and
        # serialized (wait()), so a per-manager counter agrees across
        # ranks even when the same step is ever re-saved
        self._save_seq += 1
        tag = f"{step}-{self._save_seq}"
        tmp = os.path.join(self.directory, f"tmp-{step}")
        if rank == 0:
            if os.path.isdir(tmp):
                import shutil
                # stale debris from a killed save (possibly a different
                # world size) must not leak into this step's manifest
                shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
        c.barrier(f"ckpt-begin-{tag}")
        # ---- phase 1: stage -----------------------------------------
        import pickle
        shard = os.path.join(tmp, f"shard-{rank}.pkl")
        with open(shard, "wb") as f:
            pickle.dump(shard_tree, f)
            f.flush()
            os.fsync(f.fileno())
        crc = _file_crc32(shard)
        with open(os.path.join(tmp, f"shard-{rank}.ok.json"), "w") as f:
            json.dump({"rank": rank, "crc32": crc,
                       "bytes": os.path.getsize(shard),
                       "epoch": c.epoch}, f)
            f.flush()
            os.fsync(f.fileno())
        if faults.active():
            faults.maybe_crash_after_stage(step)
        c.barrier(f"ckpt-stage-{tag}")
        # ---- phase 2: commit (rank 0 only) --------------------------
        sdir = self._step_dir(step)
        if rank == 0:
            shards = {}
            for r in range(world):
                ok = os.path.join(tmp, f"shard-{r}.ok.json")
                with open(ok) as f:
                    rec = json.load(f)
                shards[f"shard-{r}.pkl"] = {"crc32": rec["crc32"],
                                            "bytes": rec["bytes"]}
            manifest = {"version": 2, "format": "multihost",
                        "world_size": world, "epoch": c.epoch,
                        "shards": shards}
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, "world_size": world,
                           **metadata}, f)
                f.flush()
                os.fsync(f.fileno())
            if os.path.isdir(sdir):
                import shutil
                shutil.rmtree(sdir, ignore_errors=True)
            os.replace(tmp, sdir)
            _fsync_dir(self.directory)
            self._gc()
        # every rank leaves only once the step is committed — "resume
        # from the last committed step" means the same step on all ranks
        c.barrier(f"ckpt-commit-{tag}")
        if faults.active():
            faults.maybe_corrupt_shard(
                step, os.path.join(sdir, f"shard-{rank}.pkl"))
        from ..resilience import status
        status.record_checkpoint(step)
        REGISTRY.counter("ff_checkpoint_saves_total",
                         "Completed checkpoint saves").inc()
        REGISTRY.gauge("ff_checkpoint_last_step",
                       "Step of the newest completed checkpoint"
                       ).set(float(step))
        obs_events.record_span("ckpt.save", t0,
                               time.perf_counter() - t0, step=step,
                               multihost=True)

    def _verified_steps(self) -> List[int]:
        """Steps THIS rank can verify cheaply (manifest present, every
        listed shard file's CRC matches; single-process format steps
        verify by full load)."""
        out = []
        for s in self.all_steps():
            sdir = self._step_dir(s)
            mpath = os.path.join(sdir, "manifest.json")
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
                if manifest.get("format") == "multihost":
                    for fname, rec in manifest["shards"].items():
                        p = os.path.join(sdir, fname)
                        if _file_crc32(p) != rec["crc32"]:
                            raise CheckpointCorruption(
                                f"step {s}: {fname} CRC mismatch")
                else:
                    self._load_step(s, verify=True)
            except Exception as e:  # noqa: BLE001 — a probe
                log.warning("checkpoint step %d fails local "
                            "verification (%s)", s, e)
                from ..resilience import status
                status.record("corrupt_checkpoints_skipped")
                REGISTRY.counter(
                    "ff_checkpoint_corrupt_skipped_total",
                    "Restore fallbacks past corrupt/partial steps").inc()
                obs_events.counter("ckpt.corrupt_skipped")
                continue
            out.append(s)
        return out

    def _quorum_step(self) -> Optional[int]:
        """Newest step EVERY rank verifies, agreed through the
        coordination KV store; None when no step survives quorum.
        Collective — every rank must call (same restore sequence)."""
        from ..resilience import coord
        mine = self._verified_steps()
        c = coord.get()
        if c is None or c.world <= 1:
            return mine[-1] if mine else None
        self._restore_seq += 1
        prefix = f"ff/restore/e{c.epoch}/s{self._restore_seq}/"
        c.kv.set(prefix + str(c.rank), ",".join(map(str, mine)))
        c.barrier(f"restore-{self._restore_seq}")
        common: Optional[set] = None
        for _, csv in c.kv.dir_get(prefix):
            steps = {int(t) for t in csv.split(",") if t}
            common = steps if common is None else (common & steps)
        if not common:
            return None
        return max(common)

    # ------------------------------------------------------------------
    def restore(self, step: Optional[int] = None, verify: bool = True):
        """Returns (state, metadata).

        Explicit ``step``: load that step or raise (corruption
        included). Default (latest): walk steps newest-first, skipping
        corrupt or partial ones with a warning, and return the newest
        valid step — the auto-resume entry point must survive a torn or
        bit-rotted newest checkpoint.

        Multi-host worlds make the default restore COLLECTIVE: every
        rank must call it, and all adopt the quorum step (the newest one
        every rank verifies — see :meth:`_quorum_step`)."""
        self.wait(timeout_s=self.WAIT_TIMEOUT_S)
        if step is not None:
            return self._load_step(step, verify=verify)
        import jax
        if jax.process_count() > 1:
            s = self._quorum_step()
            if s is None:
                raise FileNotFoundError(
                    f"no checkpoint step in {self.directory} survives "
                    f"all-rank quorum verification")
            # quorum already CRC-verified exactly these files on this
            # rank — re-hashing every shard byte on the load would read
            # the whole checkpoint off shared storage twice
            return self._load_step(s, verify=False)
        candidates = self.all_steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        for s in reversed(candidates):
            try:
                return self._load_step(s, verify=verify)
            # a corrupt payload can surface as nearly anything (CRC
            # mismatch, UnpicklingError, orbax metadata errors, ...);
            # the self-healing walk treats any load failure as "this
            # step is gone" and keeps falling back
            except Exception as e:  # noqa: BLE001
                last_err = e
                log.warning(
                    "checkpoint step %d unusable (%s) — falling back to "
                    "the previous step", s, e)
                from ..resilience import status
                status.record("corrupt_checkpoints_skipped")
                REGISTRY.counter(
                    "ff_checkpoint_corrupt_skipped_total",
                    "Restore fallbacks past corrupt/partial steps").inc()
                obs_events.counter("ckpt.corrupt_skipped")
        raise FileNotFoundError(
            f"no restorable checkpoint in {self.directory} "
            f"(all {len(candidates)} step(s) corrupt; last error: "
            f"{last_err})")

    def _load_step(self, step: int, verify: bool = True):
        t0 = time.perf_counter()
        sdir = self._step_dir(step)
        mpath = os.path.join(sdir, "manifest.json")
        manifest = None
        if os.path.exists(mpath):
            with open(mpath) as f:
                manifest = json.load(f)
        if manifest is not None and manifest.get("format") == "multihost":
            state = self._load_multihost(sdir, manifest, verify=verify)
        else:
            path = os.path.join(sdir, "state")
            if self._ocp is not None and os.path.isdir(path):
                with self._ocp.PyTreeCheckpointer() as ckptr:
                    state = ckptr.restore(path)
            else:
                import pickle
                with open(path + ".pkl", "rb") as f:
                    state = pickle.load(f)
            if verify and manifest is not None:
                _verify_manifest(state, manifest,
                                 f"checkpoint step {step}")
        with open(os.path.join(sdir, "meta.json")) as f:
            meta = json.load(f)
        obs_events.record_span("ckpt.restore", t0,
                               time.perf_counter() - t0, step=step)
        return state, meta

    def _load_multihost(self, sdir: str, manifest: Dict[str, Any],
                        verify: bool = True):
        """Assemble the full host state from every rank's shard file —
        readable by a world of ANY size (the elastic shrink/relaunch
        resume path), since each shard carries its global indices."""
        import pickle

        import jax
        trees = []
        for fname, rec in sorted(manifest["shards"].items()):
            p = os.path.join(sdir, fname)
            if verify and _file_crc32(p) != rec["crc32"]:
                raise CheckpointCorruption(
                    f"{sdir}: {fname} CRC32 != manifest (bit rot or "
                    f"torn shard)")
            with open(p, "rb") as f:
                trees.append(pickle.load(f))
        return jax.tree.map(lambda *ls: _assemble_blocks(ls), *trees)

    def verify_step(self, step: int) -> bool:
        """True iff ``step`` loads and passes manifest verification."""
        try:
            self._load_step(step, verify=True)
            return True
        except Exception:  # noqa: BLE001 — a probe, not a loader
            return False

    def _gc(self):
        import shutil
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)
        # corrupt step dirs (unreadable meta — never restorable) and
        # stale tmp-<step> staging dirs from killed saves would
        # otherwise leak their full-state payloads forever. Safe here:
        # _gc runs after this save's own staging dir was renamed, and
        # the manager keeps one write in flight at a time.
        valid = {str(s) for s in steps}
        for d in os.listdir(self.directory):
            if (d.isdigit() and d not in valid) or d.startswith("tmp-"):
                shutil.rmtree(os.path.join(self.directory, d),
                              ignore_errors=True)


# ---------------------------------------------------------------------------
# FFModel-level helpers (wired as methods on FFModel)
# ---------------------------------------------------------------------------
def save_model_checkpoint(ff, directory: str, step: Optional[int] = None,
                          max_to_keep: int = 3,
                          extra_metadata: Optional[Dict[str, Any]] = None,
                          manager: Optional[CheckpointManager] = None,
                          blocking: Optional[bool] = None):
    """Save params + optimizer state + op state + step + strategy.
    ``extra_metadata`` rides in ``meta.json`` (the supervisor stores the
    dataloader position there); ``manager`` reuses a caller-held
    :class:`CheckpointManager` (required for async saves, whose
    in-flight write the manager tracks)."""
    from ..search.serialization import _spec_to_json
    if blocking is False and manager is None:
        # a throwaway manager's in-flight write could never be joined:
        # its errors would vanish with the object and concurrent saves
        # could race _gc/rename on the directory
        raise ValueError(
            "save_model_checkpoint(blocking=False) requires a caller-"
            "held `manager` so the async write can be awaited (wait())")
    mgr = manager or CheckpointManager(directory, max_to_keep=max_to_keep)
    step = int(step if step is not None else ff._step)
    strategy_doc = None
    if getattr(ff, "strategy", None) is not None:
        strategy_doc = {
            name: {"outputs": [_spec_to_json(s) for s in os_.outputs],
                   "weights": {k: _spec_to_json(v)
                               for k, v in os_.weights.items()}}
            for name, os_ in ff.strategy.ops.items()}
    meta = {"strategy": strategy_doc, "batch_size": ff.config.batch_size}
    # per-leaf optimizer-state shardings + the per-parameter ZeRO
    # assignment (runtime/zero.py): the manifest-level record of what
    # placement each opt leaf was saved under. Restore re-places onto
    # the LIVE model's shardings (so a partially-sharded state restores
    # into ANY world size or assignment — elastic shrink included);
    # this record is the audit trail that makes that round-trip
    # inspectable without loading a byte of state.
    if getattr(ff, "opt_state", None):
        from .zero import state_sharding_doc
        try:
            meta["opt_shardings"] = state_sharding_doc(ff.opt_state)
        except Exception:  # noqa: BLE001 — metadata is best-effort
            pass
    zero_a = getattr(getattr(ff, "strategy", None), "zero", None)
    if zero_a is not None:
        meta["zero"] = zero_a.to_json()
    if extra_metadata:
        meta.update(extra_metadata)
    mgr.save(step,
             {"params": ff.params, "opt_state": ff.opt_state,
              "state": ff.state},
             metadata=meta, blocking=blocking)
    return mgr


def restore_model_checkpoint(ff, directory: str,
                             step: Optional[int] = None,
                             with_meta: bool = False):
    """Restore training state into a compiled FFModel; returns the step
    (or ``(step, meta)`` with ``with_meta=True``).
    Restored arrays are re-placed with the model's current shardings (so a
    checkpoint taken under one strategy — or one MESH — resumes under
    another: strategy migration and the elastic re-plan's reshard both
    ride this path). Placement goes through the reshard planner's
    host→device step (``parallel/reshard.place_host``): each device is
    handed ONLY its own shard of a sharded leaf, so restoring a large
    sharded state never materializes per-device full replicas — the
    memory-peaky part of the old whole-array ``device_put``
    (``FF_NAIVE_RESHARD=1`` restores it)."""
    import jax
    from ..parallel.reshard import place_host
    mgr = CheckpointManager(directory)
    state, meta = mgr.restore(step)

    def replace(tmpl, new):
        return jax.tree.map(
            lambda t, n: place_host(
                np.asarray(n).astype(t.dtype).reshape(t.shape),
                t.sharding if hasattr(t, "sharding") else None),
            tmpl, new)

    ff.params = replace(ff.params, state["params"])
    ff.opt_state = _restore_opt_state(ff, state["opt_state"], replace)
    if state.get("state"):
        ff.state = replace(ff.state, state["state"])
    ff._step = int(meta["step"])
    if with_meta:
        return ff._step, meta
    return ff._step


def _restore_opt_state(ff, saved, replace):
    """Restore the optimizer state with the quantized-sync residual
    slot (ops/quantized_collectives.RESIDUAL_SLOT) handled out of band:
    residuals are per-participant error-feedback state whose leading
    dim is the SYNC DEGREE, so a checkpoint from a different world
    sum-folds onto the live degree (``refit_residual`` — withheld
    gradient mass is preserved exactly) and re-places via
    ``reshard.place_host``; a checkpoint without residuals restores
    into zeros, one with extras drops them. Everything else keeps the
    congruent-tree fast path."""
    from ..ops.quantized_collectives import RESIDUAL_SLOT, refit_residual
    from ..parallel.reshard import place_host
    live = ff.opt_state
    if not isinstance(live, dict) or not isinstance(saved, dict):
        return replace(live, saved)
    live_res = live.get(RESIDUAL_SLOT)
    saved = dict(saved)
    saved_res = saved.pop(RESIDUAL_SLOT, None)
    live_rest = {k: v for k, v in live.items() if k != RESIDUAL_SLOT}
    out = replace(live_rest, saved)
    if live_res is None:
        return out
    placed: Dict[str, Dict[str, Any]] = {}
    for lname, ws in live_res.items():
        for wname, tmpl in ws.items():
            src = (saved_res or {}).get(lname, {}).get(wname)
            if src is None:
                arr = np.zeros(tmpl.shape, np.float32)
            else:
                arr = refit_residual(
                    np.asarray(src, np.float32).reshape(
                        (-1,) + tuple(tmpl.shape[1:])),
                    int(tmpl.shape[0]))
            placed.setdefault(lname, {})[wname] = place_host(
                arr.astype(np.dtype(tmpl.dtype)),
                tmpl.sharding if hasattr(tmpl, "sharding") else None)
    out[RESIDUAL_SLOT] = placed
    return out
