"""Parameter initializers.

Reference parity: ``src/runtime/initializer.cc`` + ``initializer_kernel.cu``
(Glorot/Zero/Constant/Uniform/Normal as GPU tasks) — here pure jax.random,
executed device-side at compile time with per-weight folded keys.
"""
from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ffconst import InitializerType


def _fan_in_out(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv OIHW: fan_in = I*kh*kw, fan_out = O*kh*kw
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


def initialize_host(spec, key_ints, np_dtype):
    """Host-side twin of :func:`initialize`: numpy Philox keyed by the
    integer path ``key_ints`` (deterministic across runs/platforms).

    Used for bulk parameter materialization (executor.py): jax's eager
    threefry generates ~50 MB/s per tensor un-jitted and a single jitted
    whole-init program takes minutes to SPMD-compile on a many-device
    mesh, while numpy Philox streams ~1 GB/s — the round-4 north-star
    profile showed 230 s of its 301 s compile in eager init dispatch.
    The reference initializes on-accelerator (initializer_kernel.cu);
    here init is a one-time host cost and the arrays are placed with
    their target shardings in one ``device_put``."""
    import numpy as np
    kind = spec.initializer
    shape = tuple(spec.shape)
    args = spec.init_args
    if kind == InitializerType.ZERO:
        return np.zeros(shape, np_dtype)
    if kind == InitializerType.ONE:
        return np.ones(shape, np_dtype)
    if kind == InitializerType.CONSTANT:
        return np.full(shape, args.get("value", 0.0), np_dtype)
    # Philox keys are 2x uint64: word 0 = seed mixed with the path tag,
    # word 1 = the (sub-path, index) pair — all path components are
    # < 2^32 in practice, so the packing is collision-free
    seed, tag, a, b = (tuple(key_ints) + (0, 0, 0, 0))[:4]
    mask = (1 << 64) - 1
    key = np.array([(seed ^ (tag * 0x9E3779B97F4A7C15)) & mask,
                    ((a << 32) ^ (b & 0xFFFFFFFF)) & mask], np.uint64)
    gen = np.random.Generator(np.random.Philox(key=key))
    if kind == InitializerType.UNIFORM:
        lo, hi = args.get("min", -0.05), args.get("max", 0.05)
        return gen.uniform(lo, hi, shape).astype(np_dtype)
    if kind == InitializerType.NORMAL:
        mean, std = args.get("mean", 0.0), args.get("stddev", 0.05)
        return (mean + std * gen.standard_normal(shape)).astype(np_dtype)
    if kind == InitializerType.GLOROT_UNIFORM:
        fan_in, fan_out = _fan_in_out(shape)
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return gen.uniform(-limit, limit, shape).astype(np_dtype)
    raise ValueError(kind)


def initialize(spec, rng, jnp_dtype):
    """Materialize one WeightSpec."""
    kind = spec.initializer
    shape = spec.shape
    args = spec.init_args
    if kind == InitializerType.ZERO:
        return jnp.zeros(shape, jnp_dtype)
    if kind == InitializerType.ONE:
        return jnp.ones(shape, jnp_dtype)
    if kind == InitializerType.CONSTANT:
        return jnp.full(shape, args.get("value", 0.0), jnp_dtype)
    if kind == InitializerType.UNIFORM:
        lo, hi = args.get("min", -0.05), args.get("max", 0.05)
        return jax.random.uniform(rng, shape, jnp_dtype, lo, hi)
    if kind == InitializerType.NORMAL:
        mean, std = args.get("mean", 0.0), args.get("stddev", 0.05)
        return mean + std * jax.random.normal(rng, shape, jnp_dtype)
    if kind == InitializerType.GLOROT_UNIFORM:
        fan_in, fan_out = _fan_in_out(shape)
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, jnp_dtype, -limit, limit)
    raise ValueError(kind)
