"""Parameter initializers.

Reference parity: ``src/runtime/initializer.cc`` + ``initializer_kernel.cu``
(Glorot/Zero/Constant/Uniform/Normal as GPU tasks) — here pure jax.random,
executed device-side at compile time with per-weight folded keys.
"""
from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ffconst import InitializerType


def _fan_in_out(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv OIHW: fan_in = I*kh*kw, fan_out = O*kh*kw
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


def initialize(spec, rng, jnp_dtype):
    """Materialize one WeightSpec."""
    kind = spec.initializer
    shape = spec.shape
    args = spec.init_args
    if kind == InitializerType.ZERO:
        return jnp.zeros(shape, jnp_dtype)
    if kind == InitializerType.ONE:
        return jnp.ones(shape, jnp_dtype)
    if kind == InitializerType.CONSTANT:
        return jnp.full(shape, args.get("value", 0.0), jnp_dtype)
    if kind == InitializerType.UNIFORM:
        lo, hi = args.get("min", -0.05), args.get("max", 0.05)
        return jax.random.uniform(rng, shape, jnp_dtype, lo, hi)
    if kind == InitializerType.NORMAL:
        mean, std = args.get("mean", 0.0), args.get("stddev", 0.05)
        return mean + std * jax.random.normal(rng, shape, jnp_dtype)
    if kind == InitializerType.GLOROT_UNIFORM:
        fan_in, fan_out = _fan_in_out(shape)
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, jnp_dtype, -limit, limit)
    raise ValueError(kind)
