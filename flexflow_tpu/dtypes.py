"""DataType ↔ jnp dtype mapping.

TPU-first policy: DT_HALF maps to bfloat16 (the MXU-native 16-bit type),
not IEEE fp16; DT_DOUBLE falls back to float32 unless jax x64 is enabled.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .ffconst import DataType

_TO_JNP = {
    DataType.DT_BOOLEAN: jnp.bool_,
    DataType.DT_INT32: jnp.int32,
    DataType.DT_INT64: jnp.int32,   # x64 disabled by default; widen if enabled
    DataType.DT_HALF: jnp.bfloat16,
    DataType.DT_BFLOAT16: jnp.bfloat16,
    DataType.DT_FLOAT: jnp.float32,
    DataType.DT_DOUBLE: jnp.float32,
}

_FROM_NP = {
    np.dtype(np.bool_): DataType.DT_BOOLEAN,
    np.dtype(np.int32): DataType.DT_INT32,
    np.dtype(np.int64): DataType.DT_INT64,
    np.dtype(np.float16): DataType.DT_HALF,
    np.dtype(np.float32): DataType.DT_FLOAT,
    np.dtype(np.float64): DataType.DT_DOUBLE,
}


def to_jnp(dt: DataType):
    return _TO_JNP[DataType(dt)]


def from_numpy_dtype(dtype) -> DataType:
    dtype = np.dtype(dtype)
    if dtype == jnp.bfloat16:
        return DataType.DT_BFLOAT16
    return _FROM_NP.get(dtype, DataType.DT_FLOAT)


def itemsize(dt: DataType) -> int:
    return np.dtype(to_jnp(dt)).itemsize
