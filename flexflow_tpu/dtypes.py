"""DataType ↔ jnp dtype mapping.

TPU-first policy: DT_HALF maps to bfloat16 (the MXU-native 16-bit type),
not IEEE fp16; DT_DOUBLE falls back to float32 unless jax x64 is enabled.
The narrow wire dtypes (DT_INT8 / DT_FLOAT8_*) are the quantized-
collective payload types (ops/quantized_collectives.py) — fp8 maps to
the ml_dtypes types jax ships (e4m3 is the "fn" finite-only variant,
the accelerator-native encoding).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .ffconst import DataType

_TO_JNP = {
    DataType.DT_BOOLEAN: jnp.bool_,
    DataType.DT_INT32: jnp.int32,
    DataType.DT_INT64: jnp.int32,   # x64 disabled by default; widen if enabled
    DataType.DT_HALF: jnp.bfloat16,
    DataType.DT_BFLOAT16: jnp.bfloat16,
    DataType.DT_FLOAT: jnp.float32,
    DataType.DT_DOUBLE: jnp.float32,
    DataType.DT_INT8: jnp.int8,
    DataType.DT_FLOAT8_E4M3: jnp.float8_e4m3fn,
    DataType.DT_FLOAT8_E5M2: jnp.float8_e5m2,
}

_FROM_NP = {
    np.dtype(np.bool_): DataType.DT_BOOLEAN,
    np.dtype(np.int8): DataType.DT_INT8,
    np.dtype(np.int32): DataType.DT_INT32,
    np.dtype(np.int64): DataType.DT_INT64,
    np.dtype(np.float16): DataType.DT_HALF,
    np.dtype(np.float32): DataType.DT_FLOAT,
    np.dtype(np.float64): DataType.DT_DOUBLE,
}


def to_jnp(dt: DataType):
    return _TO_JNP[DataType(dt)]


def from_numpy_dtype(dtype) -> DataType:
    dtype = np.dtype(dtype)
    if dtype == jnp.bfloat16:
        return DataType.DT_BFLOAT16
    if dtype == np.dtype(jnp.float8_e4m3fn):
        return DataType.DT_FLOAT8_E4M3
    if dtype == np.dtype(jnp.float8_e5m2):
        return DataType.DT_FLOAT8_E5M2
    return _FROM_NP.get(dtype, DataType.DT_FLOAT)


def itemsize(dt: DataType) -> int:
    return np.dtype(to_jnp(dt)).itemsize
