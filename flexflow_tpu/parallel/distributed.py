"""Multi-host execution: the jax.distributed control plane.

Reference parity: the reference trains across nodes via Legion control
replication + GASNet launch and per-operator NCCL communicators
(``/root/reference/MULTI-NODE.md``, ``src/runtime/model.cc:3129-3168``
``ncclInitCommunicator``, ``include/flexflow/config.h:157`` numNodes).
TPU-native redesign: one controller process per host joins a single
global device world via ``jax.distributed.initialize``; after that,
``jax.devices()`` is the global view and GSPMD + XLA collectives carry
cross-host traffic over ICI (within a slice) or DCN (across slices) —
there are no per-op communicators to create, so the whole NCCL plumbing
layer collapses into this one rendezvous.

Launch convention (the analog of the reference's ``mpirun`` wrapper):
set ``FF_COORDINATOR_ADDRESS`` / ``FF_NUM_PROCESSES`` / ``FF_PROCESS_ID``
(or pass ``--coordinator-address`` / ``--process-id`` / ``--nodes``) on
each host, or rely on jax's own cloud-TPU auto-detection by setting only
``FF_DISTRIBUTED=auto``.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("flexflow_tpu")

_initialized_here = False


def is_initialized() -> bool:
    """True when a jax.distributed client exists (ours or ambient).

    Detection order (tests/test_distributed.py pins the degradation):
    the public ``jax.distributed.is_initialized`` when this jax has it,
    then the private ``jax._src.distributed`` global state, then our own
    ``_initialized_here`` flag — so a jax upgrade that drops either API
    degrades to the flag (correct for every world WE joined) instead of
    silently reporting single-process."""
    import jax
    try:  # public API (newer jax)
        fn = getattr(jax.distributed, "is_initialized", None)
        if fn is not None and fn():
            return True
    except Exception:  # pragma: no cover - public-API drift
        pass
    try:  # private fallback: sees worlds initialized by the host program
        from jax._src import distributed as _jd
        if getattr(_jd.global_state, "client", None) is not None:
            return True
    except Exception:  # pragma: no cover - private-API drift
        pass
    return _initialized_here


def client():
    """The live distributed-runtime client (KV store + barriers), or
    None outside a multi-process world. The coordination layer
    (``resilience/coord.py``) builds heartbeats and bounded barriers on
    this."""
    try:
        from jax._src import distributed as _jd
        return getattr(_jd.global_state, "client", None)
    except Exception:  # pragma: no cover - private-API drift
        return None


def _enable_cpu_collectives() -> None:
    """Multi-process CPU worlds need a cross-process collectives backend
    (the XLA CPU client ships gloo for exactly this); without it every
    multi-controller computation dies with "Multiprocess computations
    aren't implemented on the CPU backend". Must run before the CPU
    client is created — maybe_initialize calls it right before
    ``jax.distributed.initialize`` (which has the same constraint).
    TPU/GPU backends ignore the option; jax versions without the flag
    (or with gloo compiled out) just proceed."""
    import jax
    impl = os.environ.get("FF_CPU_COLLECTIVES", "gloo")
    if not impl or impl == "none":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception:  # pragma: no cover - old jax or no gloo build
        log.warning("distributed: could not enable CPU collectives "
                    "(%s); multi-process CPU worlds will not work", impl)


def maybe_initialize(config=None) -> bool:
    """Join the multi-host world if configured; returns True when running
    multi-process after the call. Idempotent — safe to call from every
    ``FFModel.compile``.

    Resolution order: explicit config flags, then ``FF_*`` env vars, then
    (``FF_DISTRIBUTED=auto``) jax's own cluster auto-detection.
    """
    global _initialized_here
    import jax

    if is_initialized():
        return jax.process_count() > 1

    addr = os.environ.get("FF_COORDINATOR_ADDRESS", "")
    nproc = int(os.environ.get("FF_NUM_PROCESSES", "0"))
    pid = int(os.environ.get("FF_PROCESS_ID", "-1"))
    auto = os.environ.get("FF_DISTRIBUTED", "") == "auto"
    if config is not None:
        addr = getattr(config, "coordinator_address", "") or addr
        if getattr(config, "process_id", -1) >= 0:
            pid = config.process_id
        if getattr(config, "num_nodes", 1) > 1 and nproc == 0:
            nproc = config.num_nodes

    if not addr and not auto:
        return False

    kwargs = {}
    if addr:
        kwargs = dict(coordinator_address=addr, num_processes=nproc,
                      process_id=pid)
    _enable_cpu_collectives()
    try:
        jax.distributed.initialize(**kwargs)
        _initialized_here = True
    except RuntimeError as e:  # already initialized by the host program
        if "already" not in str(e).lower():
            raise
    log.info("distributed: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1


def process_count() -> int:
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def local_row_range(sharding, global_shape) -> tuple:
    """[lo, hi) rows of the leading dim owned by THIS process under
    ``sharding`` — which rows of a host-resident global batch this
    process must materialize (replicated layouts return the full range).
    """
    idx_map = sharding.addressable_devices_indices_map(tuple(global_shape))
    lo, hi = global_shape[0], 0
    for idx in idx_map.values():
        r = idx[0] if idx else slice(None)
        lo = min(lo, r.start if r.start is not None else 0)
        hi = max(hi, r.stop if r.stop is not None else global_shape[0])
    return (0, global_shape[0]) if lo >= hi else (lo, hi)


def put_global(value, sharding):
    """device_put that works in both single- and multi-process worlds.

    Multi-process: each process contributes its addressable shard of the
    host-resident global array (``jax.make_array_from_process_local_data``
    — the TPU-native analog of the reference dataloader's per-node
    zero-copy partition, ``src/dataloader/dataloader.cc``).
    """
    import jax
    if sharding is None:
        return jax.device_put(value)
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    # row-contribution fast path only applies when the cross-process
    # partitioning is on the leading (batch) dim — true for all loader
    # shardings; anything else goes through device_put (each process
    # holds the full host value)
    idx_map = sharding.addressable_devices_indices_map(tuple(value.shape))
    only_rows = all(
        all(r.start in (None, 0) and r.stop in (None, s)
            for r, s in zip(idx[1:], value.shape[1:]))
        for idx in idx_map.values())
    if not only_rows:
        return jax.device_put(value, sharding)
    lo, hi = local_row_range(sharding, value.shape)
    return jax.make_array_from_process_local_data(
        sharding, value[lo:hi], value.shape)
