from .machine import DeviceMesh, MachineSpec  # noqa: F401
from .ptensor import ParallelDim, ParallelTensorShape  # noqa: F401
from .strategy import OpSharding, ShardingStrategy  # noqa: F401
