"""Per-op concurrent device-subset placement ("op banks").

Reference analog: ``MachineView`` (``include/flexflow/machine_view.h:14-62``,
``src/runtime/machine_view.cc``) — each op may run on its own device slice
(``start_device_id`` + dim/stride), so e.g. DLRM places its embedding
tables on disjoint GPU subsets running *concurrently*
(``examples/cpp/DLRM/strategies/dlrm_strategy_16embs_16gpus.pb``).

TPU-native realization: inside one SPMD program, "op A on chips 0..3
while op B runs on chips 4..7" is expressed by *stacking* a group of K
independent, same-signature ops along a leading bank dim and sharding
that dim over dedicated mesh axes. Each device subset then computes only
its own members' work (a vmap whose mapped dim is bank-sharded), which
is exactly concurrent subset placement — but it is a sharding, so XLA
still schedules/fuses it and GSPMD inserts the one all-gather where the
outputs rejoin the rest of the graph. The flat-device-order view of each
member's subset is exposed as a reference-parity ``MachineView``.

Wins vs whole-mesh placement (what the reference's DLRM strategies buy):
  - weights are *distributed*, not replicated: per-device table memory
    is divided by the bank degree;
  - the dense embedding-gradient update (the HBM-bound step cost) is
    divided by the bank degree — each subset updates only its tables;
  - member lookups run concurrently on disjoint subsets.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import OperatorType


@dataclasses.dataclass(frozen=True)
class MachineView:
    """Reference-parity device-subset view (``machine_view.h:14-62``):
    the devices ``start_device_id + i*stride`` for ``i < num_parts``,
    in the mesh's flat device order. Subsets that are not an arithmetic
    progression (possible when the bank axes are non-adjacent in the
    mesh's axis order) carry their exact ids in ``explicit_ids``."""
    start_device_id: int
    num_parts: int
    stride: int = 1
    explicit_ids: Optional[Tuple[int, ...]] = None

    @property
    def device_ids(self) -> Tuple[int, ...]:
        if self.explicit_ids is not None:
            return self.explicit_ids
        return tuple(self.start_device_id + i * self.stride
                     for i in range(self.num_parts))


def _ids_to_view(ids: "np.ndarray") -> MachineView:
    """Compress a sorted flat-id array to start/num/stride when it is
    an arithmetic progression, else keep the exact ids."""
    stride = int(ids[1] - ids[0]) if len(ids) > 1 else 1
    if len(ids) > 2 and not np.all(np.diff(ids) == stride):
        return MachineView(int(ids[0]), len(ids), 1,
                           explicit_ids=tuple(int(i) for i in ids))
    return MachineView(int(ids[0]), len(ids), stride)


@dataclasses.dataclass
class BankSpec:
    """K independent ops placed on disjoint device subsets. ``members``
    is ordered: the stacked bank dim is sharded in contiguous blocks,
    so member k lives at bank coordinate ``k // (K / bank_degree)``.
    ``axes`` are the mesh axes forming the bank dim; their sizes
    multiply to ``bank_degree``, which must divide K.

    ``padded=False``: members share an exact signature (v1).
    ``padded=True``: members share a signature FAMILY — same op type,
    inputs and outputs, differing only in weight shapes (heterogeneous
    embedding tables: different vocab sizes). Weights are zero-padded
    to the per-name max shape before stacking; lookups never touch the
    padding (ids are bounded by each member's true vocab), so banked
    and unbanked runs stay numerically identical. This is the
    reference's MachineView placement for NON-identical ops
    (machine_view.h:14-62) — the r4 'banks v1 is narrow' gap."""
    members: List[str]                  # layer names, bank index = position
    axes: Tuple[str, ...]               # mesh axes carrying the bank dim
    batch_axes: Tuple[str, ...] = ()    # leftover axes for dp inside subsets
    param_name: str = "__bank__"
    padded: bool = False

    def bank_degree(self, dmesh) -> int:
        d = 1
        for a in self.axes:
            d *= dmesh.axis_sizes[a]
        return d

    def machine_views(self, dmesh) -> Dict[str, MachineView]:
        """Per-member flat-device-order subset, for describe/export and
        reference-strategy parity checks. The mesh is laid out
        axis-major (``DeviceMesh`` reshapes ``jax.devices()``), so a
        member's subset is the set of flat ids whose coordinates along
        ``self.axes`` equal the member's bank coordinate."""
        names = list(dmesh.axis_sizes.keys())
        sizes = [dmesh.axis_sizes[a] for a in names]
        B = self.bank_degree(dmesh)
        if len(self.members) % B != 0:
            raise ValueError(f"bank degree {B} must divide member "
                             f"count {len(self.members)}")
        grid = np.arange(int(np.prod(sizes))).reshape(sizes)
        # bank coordinate of every flat device id
        coord = np.zeros_like(grid)
        mult = 1
        for a in reversed(self.axes):
            idx = names.index(a)
            ax_coord = np.indices(grid.shape)[idx]
            coord = coord + ax_coord * mult
            mult *= sizes[idx]
        out: Dict[str, MachineView] = {}
        per = len(self.members) // B
        for k, m in enumerate(self.members):
            ids = np.sort(grid[coord == (k // per)].ravel())
            out[m] = _ids_to_view(ids)
        return out


# Ops safe to bank in v1: pure, stateless, rng-free, single-input/
# single-output, with all weights vmappable. The reference's headline
# use-case (DLRM embedding tables) plus the linear family.
_BANKABLE = {OperatorType.OP_EMBEDDING, OperatorType.OP_LINEAR}


# params that only size the WEIGHT (never the output): members of a
# padded family may differ in them
_PAD_FREE_PARAMS = {OperatorType.OP_EMBEDDING: ("num_entries",)}


def _signature(layer, family: bool = False):
    """Two layers may share a bank iff their signatures match: same op,
    same params, same input/output shapes+dtypes (so their emits are
    vmappable over a stacked leading dim). With ``family=True``,
    weight-sizing params (``_PAD_FREE_PARAMS``) are excluded — members
    then differ only in weight shape and are pad-stackable."""
    skip = _PAD_FREE_PARAMS.get(layer.op_type, ()) if family else ()
    return (layer.op_type,
            tuple(sorted((k, v) for k, v in layer.params.items()
                         if not callable(v) and k not in skip)),
            tuple((tuple(t.shape), t.dtype) for t in layer.inputs),
            tuple((tuple(t.shape), t.dtype) for t in layer.outputs))


def find_bank_groups(layers: Sequence,
                     allow_padded: bool = True) -> List[List]:
    """Groups of >= 2 mutually independent bankable layers sharing a
    signature (or, with ``allow_padded``, a signature family — see
    :class:`BankSpec`). Independence: no member's output (transitively)
    feeds another member — guaranteed here by requiring every member's
    inputs to be produced before the FIRST member (or be graph inputs),
    which also lets the executor emit the whole group at the first
    member's position."""
    by_sig: Dict[tuple, List] = {}
    produced_at: Dict[int, int] = {}    # tensor guid -> producer index
    for i, l in enumerate(layers):
        for t in l.outputs:
            produced_at[t.guid] = i
    pos = {l.name: i for i, l in enumerate(layers)}
    for l in layers:
        if l.op_type not in _BANKABLE:
            continue
        if len(l.outputs) != 1 or len(l.inputs) != 1:
            continue
        by_sig.setdefault(_signature(l, family=allow_padded), []).append(l)
    groups = []
    for sig, ls in by_sig.items():
        if len(ls) < 2:
            continue
        first = min(pos[l.name] for l in ls)
        ok = [l for l in ls
              if all(produced_at.get(t.guid, -1) < first
                     for t in l.inputs)]
        if len(ok) >= 2:
            groups.append(sorted(ok, key=lambda l: pos[l.name]))
    return groups


def group_is_padded(group: Sequence) -> bool:
    """True when the group's members differ in exact signature (weight
    shapes) and need pad-stacking."""
    return len({_signature(l) for l in group}) > 1


@dataclasses.dataclass
class PlaceGroup:
    """K mutually-independent ops of ARBITRARY (mixed) types, each
    placed on its own contiguous block of the ``axis`` coordinates —
    member k owns coords [k*P/K, (k+1)*P/K). The executor lowers the
    group as one shard_map region that ``lax.switch``es on the block
    coordinate, so each device EXECUTES only its member's branch
    (MPMD-inside-SPMD) and the members run concurrently; outputs rejoin
    by an exact masked psum over the axis.

    Complements :class:`BankSpec`: banks distribute both compute AND
    weights for signature-family groups (stacking); a PlaceGroup
    handles heterogeneous op types, trading replicated weights for
    generality — the compute-placement half of the reference's
    arbitrary per-op MachineView (machine_view.h:14-62)."""
    members: List[str]
    axis: str

    def machine_views(self, dmesh) -> Dict[str, MachineView]:
        names = list(dmesh.axis_sizes.keys())
        sizes = [dmesh.axis_sizes[a] for a in names]
        P_ = dmesh.axis_sizes[self.axis]
        K = len(self.members)
        if P_ % K != 0:
            raise ValueError(f"place axis {self.axis} size {P_} must "
                             f"divide into {K} members")
        grid = np.arange(int(np.prod(sizes))).reshape(sizes)
        ax = names.index(self.axis)
        coord = np.indices(grid.shape)[ax]
        out: Dict[str, MachineView] = {}
        per = P_ // K
        for k, m in enumerate(self.members):
            ids = np.sort(grid[(coord >= k * per)
                               & (coord < (k + 1) * per)].ravel())
            out[m] = _ids_to_view(ids)
        return out


def choose_bank_axes(dmesh, k_members: int,
                     reserved: Sequence[str] = ()
                     ) -> Optional[Tuple[Tuple[str, ...], Tuple[str, ...]]]:
    """Pick mesh axes for the bank dim: the largest realizable degree
    that divides K (so members spread evenly), leaving the remaining
    axes for batch parallelism inside each subset. Returns
    ``(bank_axes, batch_axes)`` or None."""
    reserved = tuple(reserved)
    best = None
    for d in sorted(dmesh.valid_degrees(), reverse=True):
        if d <= 1 or k_members % d != 0:
            continue
        ax = dmesh.allocate_axes(d, reserved)
        if ax is not None:
            best = ax
            break
    if best is None:
        return None
    batch = tuple(a for a in dmesh.axis_sizes
                  if a not in best and a not in reserved)
    return tuple(best), batch


def rejoin_stack(out, bank_spec, batch_spec, strategy):
    """Explicitly rejoin a banked output stack with the rest of the
    graph: gather ONLY the bank dim (an all-gather over the bank axes,
    batch sharding untouched) through the reshard planner, so the
    downstream per-member reads (``out[k]``) are local indexing instead
    of a GSPMD-chosen gather rewrite — the rewrite miscompiles on CPU
    when a pipeline region reshards the same value again (NaN in the
    banks x pipeline composition). ``FF_NAIVE_RESHARD=1`` keeps the
    implicit (pre-planner) rejoin."""
    from jax.sharding import PartitionSpec as P
    from .reshard import naive_reshard, planner_for
    if naive_reshard():
        return out
    pad = [None] * (out.ndim - 2)
    src = P(bank_spec, batch_spec, *pad)
    dst = P(None, batch_spec, *pad)
    return planner_for(strategy).apply(out, src, dst)


def shard_stack(xs, member_t, bank_in_sp, strategy):
    """Explicitly transition the stacked member inputs onto the bank
    layout. Stacking shifts every member dim right by one, so a
    batch-sharded member input lands at ``P(None, dp, ...)`` while the
    bank wants ``P(bank, batch, ...)`` — an axis MOVE, which the
    planner lowers as one all-to-all at constant per-device memory (the
    arXiv 2112.01075 primitive) instead of GSPMD's gather rewrite,
    which miscompiles this transition on CPU when a pipeline region
    reshards the value again downstream. ``FF_NAIVE_RESHARD=1`` keeps
    the bare constraint."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .reshard import (naive_reshard, norm_spec, planner_for,
                          tensor_spec)
    if naive_reshard():
        return jax.lax.with_sharding_constraint(
            xs, NamedSharding(strategy.dmesh.mesh, bank_in_sp))
    mem = norm_spec(tensor_spec(strategy, member_t), xs.ndim - 1)
    src = P(None, *[tuple(d) if d else None for d in mem])
    return planner_for(strategy).apply(xs, src, bank_in_sp)
