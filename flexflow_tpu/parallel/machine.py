"""TPU machine model: device mesh + interconnect description.

Replaces the reference's ``MachineView``/``MachineModel`` hierarchy
(``include/flexflow/machine_view.h``, ``simulator.h:212-605``). The
reference models sockets/PCIe/NVLink/NIC; a TPU slice is a torus of chips
joined by ICI with DCN between slices, so the model is: per-axis ICI
bandwidth/latency, DCN bandwidth, HBM capacity/bandwidth, and peak MXU
FLOP/s — the constants the execution simulator uses to cost collectives.

The mesh is factorized into *atomic axes* (prime factors of the device
count). A search-assigned parallel degree d is realized as a subset of
atomic axes whose sizes multiply to d; this is how a per-op "degree" in the
reference maps onto one global GSPMD mesh.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _prime_factors(n: int) -> List[int]:
    out, p = [], 2
    while p * p <= n:
        while n % p == 0:
            out.append(p)
            n //= p
        p += 1
    if n > 1:
        out.append(n)
    return sorted(out, reverse=True)


# Per-generation hardware constants (public figures; bf16 FLOP/s).
TPU_GENERATIONS = {
    # name: (peak bf16 TFLOP/s, HBM GiB, HBM GB/s, ICI GB/s per link (one dir))
    "v4": (275.0, 32.0, 1228.0, 50.0),
    "v5e": (197.0, 16.0, 819.0, 50.0),
    "v5p": (459.0, 95.0, 2765.0, 100.0),
    "v6e": (918.0, 32.0, 1640.0, 90.0),
    "cpu-sim": (0.2, 8.0, 50.0, 5.0),
}


@dataclasses.dataclass
class MachineSpec:
    """Description of the target machine for both execution and simulation."""
    num_devices: int = 1
    generation: str = "v5e"
    # physical ICI topology, e.g. (4, 8) for v5e-32; product may exceed
    # num_devices for partial slices
    ici_shape: Optional[Tuple[int, ...]] = None
    num_slices: int = 1                     # multi-slice via DCN
    num_hosts: int = 1                      # controller hosts (DCN NICs)
    dcn_bandwidth_gbps: float = 25.0        # per-host DCN
    ici_latency_us: float = 1.0
    dcn_latency_us: float = 10.0
    # machine-file overrides of the per-generation constants
    # (``--machine-model-file``, parallel/topology.py:load_machine_file)
    ici_bandwidth_override: Optional[float] = None
    peak_flops_override: Optional[float] = None
    # cross-host-within-slice fabric override (bytes/s, us): unset on
    # TPU pods (ICI spans hosts inside a slice), set by reference-style
    # machine files whose inter-host fabric is a NIC
    host_bandwidth_override: Optional[float] = None
    host_latency_override_us: Optional[float] = None
    # explicit fabric (parallel/topology.py GraphTopology): big-switch,
    # degraded-link, or custom connection matrices — the reference's
    # NetworkedMachineModel (simulator.h:381-515). None = derive from
    # ici_shape (+ multi-slice DCN when num_slices > 1).
    topology_override: Optional[object] = None

    @property
    def peak_flops(self) -> float:
        if self.peak_flops_override is not None:
            return self.peak_flops_override
        return TPU_GENERATIONS[self.generation][0] * 1e12

    @property
    def hbm_bytes(self) -> float:
        return TPU_GENERATIONS[self.generation][1] * (1 << 30)

    @property
    def hbm_bandwidth(self) -> float:
        return TPU_GENERATIONS[self.generation][2] * 1e9

    @property
    def ici_bandwidth(self) -> float:
        if self.ici_bandwidth_override is not None:
            return self.ici_bandwidth_override
        return TPU_GENERATIONS[self.generation][3] * 1e9

    @property
    def topology(self):
        """The physical fabric: an explicit ``topology_override`` when
        set, a multi-slice ICI+DCN graph when ``num_slices > 1`` with a
        known ``ici_shape``, a plain ICI torus when single-slice, else
        None. Memoized per spec: the topology carries route/distance
        caches that must persist across the search's thousands of
        task-graph builds (rebuilding it per build cost ~35 s of Dijkstra
        on the 64-device two-slice north-star). The memo is keyed on
        every field the fabric derives from, so mutating the spec after
        construction (dataclass fields are writable) invalidates it
        instead of silently pinning the stale fabric into search costs."""
        if self.topology_override is not None:
            return self.topology_override
        if self.ici_shape is None:
            return None
        key = (tuple(self.ici_shape), self.num_slices, self.num_hosts,
               self.ici_bandwidth, self.dcn_bandwidth)
        cached = self.__dict__.get("_topology_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
        if self.num_slices > 1:
            from .topology import GraphTopology
            topo = GraphTopology.multi_slice_torus(
                tuple(self.ici_shape), self.num_slices,
                ici_bw=self.ici_bandwidth, dcn_bw=self.dcn_bandwidth,
                hosts_per_slice=max(
                    1, self.num_hosts // max(1, self.num_slices)))
        else:
            from .topology import TorusTopology
            topo = TorusTopology(tuple(self.ici_shape))
        object.__setattr__(self, "_topology_cache", (key, topo))
        return topo

    @property
    def tier_graph(self):
        """The machine's bandwidth-tier ladder
        (:class:`~flexflow_tpu.parallel.topology.TierGraph`): ici /
        host / dcn with per-tier bandwidth+latency — what the placement
        search, cost model and plan verifier query instead of a single
        flat number. Memoized per spec, keyed on every field the ladder
        derives from (same invalidation discipline as ``topology``)."""
        from .topology import TierGraph
        key = (self.num_devices, self.num_slices, self.num_hosts,
               self.ici_bandwidth, self.dcn_bandwidth,
               self.ici_latency_us, self.dcn_latency_us,
               self.host_bandwidth_override,
               self.host_latency_override_us)
        cached = self.__dict__.get("_tier_graph_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
        tg = TierGraph.from_machine_spec(self)
        object.__setattr__(self, "_tier_graph_cache", (key, tg))
        return tg

    @classmethod
    def from_file(cls, path: str) -> "MachineSpec":
        """Load a machine description (``--machine-model-file``); see
        ``parallel/topology.py:load_machine_file`` for the formats."""
        from .topology import load_machine_file
        return load_machine_file(path)

    @property
    def dcn_bandwidth(self) -> float:
        """Inter-slice (per-host NIC) bandwidth in bytes/s."""
        return self.dcn_bandwidth_gbps * 1e9

    @property
    def devices_per_slice(self) -> int:
        """Devices reachable over ICI alone; collectives of larger degree
        must cross DCN (the cost model's slice boundary)."""
        return max(1, self.num_devices // max(1, self.num_slices))

    @classmethod
    def detect(cls, devices=None) -> "MachineSpec":
        import logging

        import jax
        devices = devices or jax.devices()
        kind = devices[0].device_kind.lower().replace(" ", "")
        gen = None
        # device_kind spellings seen in the wild: "TPU v4", "TPU v5e",
        # "TPU v5 lite" (= v5e), "TPU v5p", "TPU v6 lite" (= v6e/Trillium)
        for g, names in (("v6e", ("v6e", "v6lite")),
                         ("v5p", ("v5p",)),
                         ("v5e", ("v5e", "v5lite")),
                         ("v4", ("v4",))):
            if any(n in kind for n in names):
                gen = g
                break
        if devices[0].platform == "cpu":
            gen = "cpu-sim"
        log = logging.getLogger("flexflow_tpu")
        if gen is None:
            gen = "v5e"
            log.warning(
                "MachineSpec.detect: unknown device kind %r (platform %r); "
                "defaulting cost-model constants to %s — pass an explicit "
                "MachineSpec or a machine-model file if this is wrong",
                devices[0].device_kind, devices[0].platform, gen)
        else:
            log.info("MachineSpec.detect: %d x %s (device_kind=%r)",
                     len(devices), gen, devices[0].device_kind)
        # each controller process hosts one DCN island (a slice, or a
        # CPU-sim process); ICI never spans jax processes in this model
        n_proc = jax.process_count()
        n_slices = n_proc if n_proc > 1 and len(devices) % n_proc == 0 else 1
        return cls(num_devices=len(devices), generation=gen,
                   num_slices=n_slices)


class DeviceMesh:
    """Factorized global mesh. Axis names are ``x0, x1, ...`` sized by the
    prime factorization of the device count (largest factor first)."""

    def __init__(self, spec: MachineSpec, devices=None,
                 mesh_shape: Optional[Sequence[int]] = None,
                 seq: int = 0):
        import jax
        from jax.sharding import Mesh
        self.spec = spec
        devices = devices if devices is not None else jax.devices()
        devices = devices[: spec.num_devices]
        self.dcn_axis: Optional[str] = None
        # dedicated sequence-parallel (context) axis: carved as the
        # TRAILING axis so its devices are contiguous (fastest fabric —
        # ring-attention hops belong on ICI). Reserved: the general
        # search never shards batch/params over it (allocate_axes /
        # valid_degrees exclude it); only ring attention consumes it.
        self.seq_axis: Optional[str] = None
        n = len(devices)
        seq = int(seq or 0)
        if seq > 1:
            if n % seq != 0:
                raise ValueError(
                    f"--seq-parallel {seq} does not divide {n} devices")
            n_rest = n // seq
        else:
            seq, n_rest = 0, n
        slices = spec.num_slices if (spec.num_slices > 1
                                     and n % spec.num_slices == 0) else 1
        if seq and slices > 1 and (n_rest % slices != 0):
            raise ValueError(
                f"--seq-parallel {seq} does not compose with "
                f"{slices} slices over {n} devices (the seq axis must "
                f"stay inside a slice)")
        if mesh_shape is not None:
            factors = [int(s) for s in mesh_shape if int(s) > 1] or [1]
            if seq and int(np.prod(factors)) * seq == n:
                # an explicit mesh_shape describes the non-seq axes
                self.axis_sizes: Dict[str, int] = {
                    f"x{i}": f for i, f in enumerate(factors)}
            else:
                self.axis_sizes = {
                    f"x{i}": f for i, f in enumerate(factors)}
                seq = 0
        elif slices > 1:
            # leading "dcn" axis spans slices/hosts: jax.devices() orders
            # devices process-major, so the reshape puts each slice's
            # devices contiguous along the inner (ICI) axes
            inner = _prime_factors(n_rest // slices) or [1]
            self.axis_sizes = {"dcn": slices,
                               **{f"x{i}": f for i, f in enumerate(inner)}}
            self.dcn_axis = "dcn"
        else:
            factors = _prime_factors(n_rest) or [1]
            self.axis_sizes = {f"x{i}": f for i, f in enumerate(factors)}
        if seq:
            self.axis_sizes["seq"] = seq
            self.seq_axis = "seq"
        arr = np.asarray(devices).reshape(tuple(self.axis_sizes.values()))
        self.mesh = Mesh(arr, tuple(self.axis_sizes.keys()))

    @property
    def seq_degree(self) -> int:
        """Size of the dedicated sequence axis (1 = no seq axis)."""
        return self.axis_sizes.get("seq", 1) if self.seq_axis else 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axis_sizes.keys())

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.axis_sizes.values()))) if self.axis_sizes else 1

    @property
    def axis_tiers(self) -> Dict[str, str]:
        """Physical tier of each atomic mesh axis ("ici" / "host" /
        "dcn"), derived from the axis block strides against the spec's
        slice/host structure: devices are flat slice-major, host-major,
        chip-minor, and an axis whose stride reaches past
        ``devices_per_slice`` hops slices (DCN), past chips-per-host
        hops hosts. Memoized — the mesh is immutable after build."""
        cached = self.__dict__.get("_axis_tiers")
        if cached is not None:
            return cached
        spec = self.spec
        per_slice = max(1, spec.devices_per_slice)
        hosts_per_slice = max(1, spec.num_hosts
                              // max(1, spec.num_slices))
        chips_per_host = max(1, per_slice // hosts_per_slice)
        tiers: Dict[str, str] = {}
        names = list(self.axis_sizes.keys())
        sizes = [self.axis_sizes[a] for a in names]
        for i, a in enumerate(names):
            stride = 1
            for s in sizes[i + 1:]:
                stride *= s
            reach = stride * sizes[i]          # devices the axis spans
            if reach > per_slice and spec.num_slices > 1:
                tiers[a] = "dcn"
            elif reach > chips_per_host:
                tiers[a] = "host"
            else:
                tiers[a] = "ici"
        self.__dict__["_axis_tiers"] = tiers
        return tiers

    def axes_by_tier(self, innermost_first: bool = True
                     ) -> List[Tuple[str, int]]:
        """(axis, size) pairs ordered by physical tier (innermost =
        fastest fabric first when ``innermost_first``) — the allocation
        order placement-aware axis assignment uses."""
        from .topology import TIER_RANK
        tiers = self.axis_tiers
        items = list(self.axis_sizes.items())
        ranked = sorted(
            range(len(items)),
            key=lambda i: (TIER_RANK.get(tiers[items[i][0]], 99), i))
        if not innermost_first:
            ranked = ranked[::-1]
        return [items[i] for i in ranked]

    def allocate_axes(self, degree: int, used: Sequence[str],
                      prefer: Optional[str] = None
                      ) -> Optional[Tuple[str, ...]]:
        """Pick unused atomic axes whose sizes multiply to exactly `degree`.

        Greedy largest-first subset-product; returns None if impossible.
        This is the analog of the reference's machine-view enumeration
        (``FFModel::register_all_machine_views``) constrained to one mesh.

        ``prefer`` orders candidates by physical tier: ``"inner"`` takes
        the fastest fabric first (per-step per-op collectives belong on
        ICI), ``"outer"`` the slowest first (once-per-step gradient sync
        can afford the DCN axis). ``None`` keeps declaration order —
        bit-identical to the historical behavior.
        """
        if degree == 1:
            return ()
        if prefer in ("inner", "outer"):
            items = self.axes_by_tier(innermost_first=(prefer == "inner"))
        else:
            items = list(self.axis_sizes.items())
        avail = [(a, s) for a, s in items
                 if a not in used and a != self.seq_axis]
        picked: List[str] = []
        rem = degree

        def search(i: int, rem: int) -> bool:
            if rem == 1:
                return True
            if i >= len(avail):
                return False
            a, s = avail[i]
            if rem % s == 0:
                picked.append(a)
                if search(i + 1, rem // s):
                    return True
                picked.pop()
            return search(i + 1, rem)

        if search(0, rem):
            return tuple(picked)
        return None

    def valid_degrees(self) -> List[int]:
        """All degrees realizable as subset products of atomic axes
        (the reserved seq axis, when present, is not in the pool)."""
        degs = {1}
        for a, s in self.axis_sizes.items():
            if a == self.seq_axis:
                continue
            degs |= {d * s for d in degs}
        return sorted(degs)

    @property
    def sharding_axes(self) -> Tuple[str, ...]:
        """Axes the general search may shard over (all but ``seq``)."""
        return tuple(a for a in self.axis_sizes if a != self.seq_axis)

    @property
    def sharding_devices(self) -> int:
        """Device count across the general sharding axes."""
        return max(1, self.num_devices // self.seq_degree)
