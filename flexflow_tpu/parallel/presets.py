"""Hand-built strategy presets: data/tensor/sequence/expert parallel.

These are the canonical strategies the search explores combinations of —
direct analogs of the reference's programmatic parallelization xfers
(``substitution.cc:61-110``: partition_linear_combine, partition_attention
etc.), expressed as PartitionSpec assignments. They also serve as golden
strategies for numerics tests (TP output must equal DP output).

Megatron-style transformer sharding:
  - attention: shard the head axis of wq/wk/wv (column-parallel), shard wo
    on the head axis (row-parallel) → one all-reduce per attention block;
  - FFN: column-parallel up-projection, row-parallel down-projection;
  - sequence parallelism (optional): activations outside the matmuls are
    sharded along the sequence dim over the tp axes.
Expert parallelism: each expert's weights placed on its own mesh slice via
sharding the (stacked) expert dim — here experts are separate Linear ops,
so EP = round-robin weight placement + sharded group_by outputs.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

from jax.sharding import PartitionSpec as P

from ..ffconst import OperatorType
from .machine import DeviceMesh
from .strategy import OpSharding, ShardingStrategy

Axes = Union[str, Tuple[str, ...], None]


def _norm(axes) -> Axes:
    if axes is None:
        return None
    if isinstance(axes, str):
        return axes
    axes = tuple(axes)
    if len(axes) == 0:
        return None
    return axes[0] if len(axes) == 1 else axes


def _size(dmesh: DeviceMesh, axes: Axes) -> int:
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    s = 1
    for a in axes:
        s *= dmesh.axis_sizes[a]
    return s


def transformer_strategy(layers, input_tensors, dmesh: DeviceMesh,
                         dp_axes, tp_axes, sp: bool = False
                         ) -> ShardingStrategy:
    """Megatron-style dp×tp (+optional sequence-parallel) strategy for
    transformer-shaped graphs built from MHA + Linear + norms."""
    dp, tp = _norm(dp_axes), _norm(tp_axes)
    tp_size = _size(dmesh, tp)
    st = ShardingStrategy(dmesh)
    for t in input_tensors:
        if t.shape and t.shape[0] % _size(dmesh, dp) == 0:
            st.inputs[t.name] = P(dp)

    prev_linear_col = False  # was the previous Linear column-parallel?
    for layer in layers:
        ot = layer.op_type
        rank = len(layer.outputs[0].shape) if layer.outputs else 0
        act_tail = [None] * max(rank - 1, 0)
        act_spec = P(dp, *act_tail) if rank >= 1 else P()
        seq_ok = (sp and rank >= 3 and layer.outputs
                  and layer.outputs[0].shape[1] % tp_size == 0)
        seq_spec = P(dp, tp, *act_tail[1:]) if seq_ok else act_spec
        if ot == OperatorType.OP_MULTIHEAD_ATTENTION:
            heads = layer.params["num_heads"]
            if heads % tp_size == 0:
                w = {"wq": P(None, tp, None), "wk": P(None, tp, None),
                     "wv": P(None, tp, None), "wo": P(tp, None, None),
                     "bq": P(tp, None), "bk": P(tp, None), "bv": P(tp, None),
                     "bo": P()}
            else:
                w = {}
            st.set_op(layer.name, [act_spec], w)
            prev_linear_col = False
        elif ot == OperatorType.OP_LINEAR:
            out_dim = layer.params["out_dim"]
            in_dim = layer.inputs[0].shape[-1]
            col = (out_dim % tp_size == 0 and not prev_linear_col)
            if col:
                w = {"kernel": P(None, tp), "bias": P(tp)}
                spec = P(dp, *act_tail[:-1], tp) if rank >= 2 else act_spec
                st.set_op(layer.name, [spec], w)
                prev_linear_col = True
            else:
                w = ({"kernel": P(tp, None), "bias": P()}
                     if in_dim % tp_size == 0 else {})
                st.set_op(layer.name, [act_spec], w)
                prev_linear_col = False
        elif ot == OperatorType.OP_EMBEDDING:
            # column-shard the table's feature dim over tp
            w = ({"kernel": P(None, tp)}
                 if layer.params["out_dim"] % tp_size == 0 else {})
            st.set_op(layer.name, [act_spec], w)
            prev_linear_col = False
        elif ot in (OperatorType.OP_LAYERNORM, OperatorType.OP_RMSNORM,
                    OperatorType.OP_DROPOUT, OperatorType.OP_EW_ADD):
            st.set_op(layer.name, [seq_spec], {})
            prev_linear_col = False
        else:
            st.set_op(layer.name,
                      [act_spec if o.shape and
                       o.shape[0] % _size(dmesh, dp) == 0 else None
                       for o in layer.outputs], {})
            prev_linear_col = False
    return st


def pipeline_strategy(layers, input_tensors, dmesh: DeviceMesh,
                      n_stages: int, n_microbatches: int = 0,
                      pp_axis: Optional[str] = None,
                      dp_axes: Optional[Sequence[str]] = None,
                      n_chunks: int = 1, tp: int = 1,
                      tp_axis: Optional[str] = None,
                      ragged: str = "auto"
                      ) -> ShardingStrategy:
    """dp×pp(×tp) strategy through the product path: the maximal
    repeated-block region (found by ``find_pipeline_region``) becomes
    ``n_stages`` GPipe stages over the ``pp`` mesh axis; everything
    outside the region is batch-sharded over the dp axes. With
    ``tp > 1`` stage-internal attention/FFN layers are Megatron-split
    over ``tp_axis`` (one psum per attention block + one per FFN pair,
    executed as explicit collectives inside the GPipe shard_map).
    Raises ValueError when the graph has no pipelinable region, no mesh
    axis of size ``n_stages``, or (tp > 1) no tp-able stage structure.

    The reference only reserves the enum for this (``ffconst.h:159``);
    here it composes with dp and tp (the analog of per-op machine-view
    composition, ``substitution.cc:1898``) and is schedulable by the
    search (``search.pipeline_score``)."""
    from .pipeline_lowering import assign_tp_roles, find_pipeline_region
    used: list = []
    if pp_axis is None:
        pp_axis = next((a for a, s in dmesh.axis_sizes.items()
                        if s == n_stages), None)
        if pp_axis is None:
            raise ValueError(
                f"no mesh axis of size {n_stages} for pipeline stages "
                f"(mesh {dict(dmesh.axis_sizes)}); pass --mesh-shape")
    used.append(pp_axis)
    if tp > 1 and tp_axis is None:
        tp_axis = next((a for a, s in dmesh.axis_sizes.items()
                        if s == tp and a not in used), None)
        if tp_axis is None:
            raise ValueError(
                f"no free mesh axis of size {tp} for stage-internal "
                f"tensor parallelism (mesh {dict(dmesh.axis_sizes)})")
    if tp_axis is not None:
        used.append(tp_axis)
    if dp_axes is None:
        dp_axes = tuple(a for a in dmesh.axis_names if a not in used)
    dp = _norm(dp_axes)
    dp_size = _size(dmesh, dp)
    from .pipeline_lowering import find_ragged_pipeline_region
    if ragged == "force" and (n_chunks > 1 or tp > 1):
        raise ValueError(
            "--pipeline-ragged force does not compose with "
            "--pipeline-chunks > 1 or in-stage tp (v1); drop one")
    uniform = None
    if ragged != "force":
        uniform = find_pipeline_region(layers, n_stages, n_microbatches,
                                       n_chunks)
    rag = None
    if ragged in ("auto", "force") and n_chunks <= 1 and tp <= 1:
        # ragged schedule: unequal per-stage block counts, embedding/
        # head absorbed into stage 0 / S-1 (gpipe_ragged). Not composed
        # with interleaving or in-stage tp in v1.
        rag = find_ragged_pipeline_region(layers, n_stages,
                                          n_microbatches)
    if uniform is None:
        region = rag
    elif rag is None:
        region = uniform
    else:
        # auto: prefer ragged only when it pipelines MORE BLOCKS (the
        # uniform finder drops indivisible trailing blocks into
        # replicated pre/post execution). On a tie the uniform schedule
        # wins — it supports interleaving/tp and the established stacked
        # layout; ``ragged="force"`` still gets edge absorption alone.
        region = rag if (rag.end - rag.start) \
            > (uniform.end - uniform.start) else uniform
    if region is None:
        ragged_tried = ragged in ("auto", "force") \
            and n_chunks <= 1 and tp <= 1
        raise ValueError(
            f"graph has no repeated-block region divisible into "
            f"{n_stages} identical stages"
            + (f" x {n_chunks} chunks" if n_chunks > 1 else "")
            + (" (ragged fallback found none either)" if ragged_tried
               else " (ragged fallback not applicable with "
                    "interleaving/tp)" if ragged != "off" else ""))
    region.pp_axis = pp_axis
    region.dp_axes = tuple(dp_axes)
    if tp > 1:
        roles = assign_tp_roles(region.template, tp)
        if not roles:
            raise ValueError(
                "tp > 1 requested but the stage template has no "
                "Megatron-splittable structure (attention heads or "
                "paired Linears divisible by tp)")
        region.tp_axis = tp_axis
        region.tp_roles = roles
    st = ShardingStrategy(dmesh)
    st.pipeline = region
    for t in input_tensors:
        if t.shape and t.shape[0] % dp_size == 0:
            st.inputs[t.name] = P(dp)
    region_names = {l.name for l in layers[region.start:region.end]}
    for layer in layers:
        if layer.name in region_names:
            continue  # sharded via the GPipe shard_map, not constraints
        outs = [P(dp, *([None] * (len(o.shape) - 1)))
                if o.shape and o.shape[0] % dp_size == 0 else None
                for o in layer.outputs]
        st.set_op(layer.name, outs, {})
    return st


def expert_parallel_strategy(layers, input_tensors, dmesh: DeviceMesh,
                             dp_axes, ep_axes) -> ShardingStrategy:
    """DP + expert parallelism for MoE graphs built by ``FFModel.moe``:
    expert Linears' weights are sharded over the ep axes on the output dim
    (each device holds 1/ep of every expert — "expert-slicing"), and
    group_by outputs stay replicated across dp so each expert shard sees
    all its tokens. A placement-style EP (expert e on device e) needs
    per-op device subsets, which arrive with the pipeline executor."""
    dp, ep = _norm(dp_axes), _norm(ep_axes)
    ep_size = _size(dmesh, ep)
    st = ShardingStrategy(dmesh)
    for t in input_tensors:
        if t.shape and t.shape[0] % _size(dmesh, dp) == 0:
            st.inputs[t.name] = P(dp)
    for layer in layers:
        rank = len(layer.outputs[0].shape) if layer.outputs else 0
        tail = [None] * max(rank - 1, 0)
        act_spec = P(dp, *tail) if rank >= 1 else P()
        if layer.op_type == OperatorType.OP_GROUP_BY:
            # expert buffers: replicated (each is (C, D), consumed by its
            # expert's dense)
            st.set_op(layer.name, [None] * len(layer.outputs), {})
        elif (layer.op_type == OperatorType.OP_LINEAR
              and layer.inputs[0].owner_layer is not None
              and layer.inputs[0].owner_layer.op_type
              == OperatorType.OP_GROUP_BY):
            out_dim = layer.params["out_dim"]
            w = {"kernel": P(None, ep), "bias": P(ep)} \
                if out_dim % ep_size == 0 else {}
            st.set_op(layer.name, [None], w)
        elif layer.op_type in (OperatorType.OP_AGGREGATE,
                               OperatorType.OP_AGG_SPEC):
            st.set_op(layer.name, [act_spec], {})
        else:
            st.set_op(layer.name,
                      [act_spec if o.shape and
                       o.shape[0] % _size(dmesh, dp) == 0 else None
                       for o in layer.outputs], {})
    return st
