"""Hierarchical axis placement + per-collective reduction-tree selection.

Following PAPERS.md "Synthesizing Optimal Parallelism Placement and
Reduction Strategies on Hierarchical Systems" (arXiv 2110.10548), the
search no longer scores collectives against a flat mesh: every atomic
mesh axis has a *placement* — the hardware tier it spans (``ici`` /
``host`` / ``dcn``, :class:`~flexflow_tpu.parallel.topology.TierGraph`)
— and every collective gets a *reduction-tree shape* chosen per
(collective kind, tier path, payload):

  - ``ring``              — the classic flat ring, every round paying
                            the path's bottleneck (outermost) tier;
  - ``halving_doubling``  — recursive halving/doubling: same bandwidth
                            term, ``log2(d)`` latency rounds instead of
                            ``d-1`` (wins on latency-bound payloads);
  - ``two_phase`` / ``three_phase`` — the paper's hierarchical trees:
                            e.g. an all-reduce lowers to intra-tier
                            reduce-scatter → inter-tier all-reduce on
                            the tier-reduced volume → intra-tier
                            all-gather, so only ``1/d_inner`` of the
                            bytes ever cross the slow fabric.

:class:`AxisPlacement` is the queryable placement assignment
(axis → tier) the search state carries; :func:`choose_reduction_tree`
is the per-collective selector the cost model calls. Per-tier costs
answer from the calibrated tables when a tier-keyed entry exists
(``search/calibration.py``), else from the tier's machine-model
constants. Single-tier machines degenerate exactly to the flat-mesh
behavior, so every existing single-slice prediction is bit-identical.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from .topology import (Tier, TierGraph, TIER_ORDER, TIER_RANK,
                       effective_tier_bandwidth)

__all__ = ["AxisPlacement", "Phase", "TreeChoice",
           "choose_reduction_tree", "tree_algorithms",
           "wire_byte_scale", "WIRE_ITEMSIZE", "QSYNC_CHUNK"]


#: algorithms the selector enumerates (per-collective search space)
TREE_ALGORITHMS = ("ring", "halving_doubling", "two_phase",
                   "three_phase")

#: quantized-collective wire dtypes and their payload itemsize
#: (ops/quantized_collectives.py owns the kernels; this table owns the
#: byte accounting the cost model prices against)
WIRE_ITEMSIZE = {"int8": 1, "float8_e4m3": 1, "float8_e5m2": 1}

#: elements per quantization scale (one fp32 scale rides per chunk)
QSYNC_CHUNK = 1024


def wire_byte_scale(wire: Optional[str]) -> float:
    """Wire-bytes / logical-fp32-bytes ratio of one quantized leg: the
    narrow payload plus the per-chunk fp32 scales that ride with it.
    ``None`` (full precision) is 1.0."""
    if not wire:
        return 1.0
    return (WIRE_ITEMSIZE[wire] + 4.0 / QSYNC_CHUNK) / 4.0


def tree_algorithms() -> Tuple[str, ...]:
    return TREE_ALGORITHMS


@dataclasses.dataclass(frozen=True)
class Phase:
    """One staged collective of a reduction tree: ``collective`` over
    ``degree`` participants confined to ``tier``, moving
    ``volume_bytes`` per group. ``wire`` is the leg's wire dtype when a
    quantized-collectives plan narrows it (``None`` = the element
    dtype, full precision)."""
    collective: str
    tier: str
    degree: int
    volume_bytes: float
    wire: Optional[str] = None

    def to_json(self) -> Dict:
        out = {"collective": self.collective, "tier": self.tier,
               "degree": self.degree,
               "volume_bytes": float(self.volume_bytes)}
        if self.wire:
            out["wire"] = self.wire
        return out


@dataclasses.dataclass
class TreeChoice:
    """The selected reduction tree for one collective site."""
    algo: str                      # one of TREE_ALGORITHMS
    phases: List[Phase]
    cost_s: float
    flat_cost_s: float             # the flat-ring baseline at the same site

    def describe(self) -> List[str]:
        return [f"{p.collective}[{p.tier} x{p.degree}]"
                for p in self.phases]

    def to_json(self) -> Dict:
        return {"algo": self.algo,
                "phases": [p.to_json() for p in self.phases],
                "cost_s": float(self.cost_s),
                "flat_cost_s": float(self.flat_cost_s)}


class AxisPlacement:
    """The search state's axis-placement assignment: mesh axis → tier,
    plus the tier ladder to price against. Built from a
    :class:`~flexflow_tpu.parallel.machine.DeviceMesh` (physical
    placement) and queried as (tier, degree) *paths* for collectives."""

    def __init__(self, axis_tiers: Dict[str, str],
                 axis_sizes: Dict[str, int], tier_graph: TierGraph):
        self.axis_tiers = dict(axis_tiers)
        self.axis_sizes = dict(axis_sizes)
        self.tier_graph = tier_graph
        unknown = [t for t in self.axis_tiers.values()
                   if t not in tier_graph.names]
        if unknown:
            raise ValueError(
                f"axis placement names tiers {sorted(set(unknown))} "
                f"absent from the machine's tier graph "
                f"{list(tier_graph.names)}")

    @classmethod
    def from_dmesh(cls, dmesh) -> Optional["AxisPlacement"]:
        spec = getattr(dmesh, "spec", None)
        if spec is None:
            return None
        try:
            return cls(dmesh.axis_tiers, dict(dmesh.axis_sizes),
                       spec.tier_graph)
        except Exception:  # noqa: BLE001 — placement is best-effort
            return None

    @property
    def multi_tier(self) -> bool:
        return len({t for t in self.axis_tiers.values()}) > 1

    def tier_of(self, axis: str) -> str:
        return self.axis_tiers.get(axis, self.tier_graph.tiers[0].name)

    # ------------------------------------------------------------------
    def path_for_axes(self, axes: Sequence[str]
                      ) -> List[Tuple[Tier, int]]:
        """(tier, degree) path of a collective spanning ``axes``,
        ordered innermost tier first; axes of one tier fold into one
        leg (they form one contiguous sub-torus of that fabric)."""
        per_tier: Dict[str, int] = {}
        for a in axes:
            per_tier[self.tier_of(a)] = (per_tier.get(self.tier_of(a), 1)
                                         * self.axis_sizes.get(a, 1))
        out = []
        for name in sorted(per_tier, key=lambda t: TIER_RANK.get(t, 99)):
            if per_tier[name] > 1:
                out.append((self.tier_graph.tier(name), per_tier[name]))
        return out

    def path_for_degree(self, degree: int, prefer: str = "inner"
                        ) -> List[Tuple[Tier, int]]:
        """The (tier, degree) path a degree-``degree`` collective takes
        under this placement policy: axes consumed innermost-first
        (``prefer="inner"`` — per-op collectives) or outermost-first
        (``"outer"`` — e.g. pricing a flat/legacy allocation). When the
        degree does not factor exactly over a prefix, the remainder
        folds into the last consumed tier (conservative)."""
        if degree <= 1:
            return []
        ranked = sorted(self.axis_sizes.items(),
                        key=lambda kv: TIER_RANK.get(self.tier_of(kv[0]), 99))
        if prefer == "outer":
            ranked = ranked[::-1]
        per_tier: Dict[str, int] = {}
        rem = degree
        for a, s in ranked:
            if rem <= 1:
                break
            take = math.gcd(rem, s)
            if take > 1:
                t = self.tier_of(a)
                per_tier[t] = per_tier.get(t, 1) * take
                rem //= take
        if rem > 1:                      # non-factoring remainder
            last = (list(per_tier) or [self.tier_graph.tiers[0].name])[-1]
            per_tier[last] = per_tier.get(last, 1) * rem
        out = []
        for name in sorted(per_tier, key=lambda t: TIER_RANK.get(t, 99)):
            out.append((self.tier_graph.tier(name), per_tier[name]))
        return out

    def to_json(self) -> Dict[str, str]:
        return dict(self.axis_tiers)


# ----------------------------------------------------------------------
# reduction-tree selection
# ----------------------------------------------------------------------

def bandwidth_multiplier(collective: str, degree: int) -> float:
    """Ring-algebra bytes multiplier of one collective: the fraction of
    ``volume`` each participant moves is ``multiplier x (d-1)/d``. THE
    shared table — ``_leg``, ``_ring_tree`` and the legacy
    ``OpCostModel._ring_cost`` all price from it, so the placed costs
    and the flat baseline they are compared against can never drift."""
    return {"all_reduce": 2.0, "all_gather": 1.0,
            "reduce_scatter": 1.0, "all_to_all": 1.0 / max(degree, 1),
            "permute": 1.0 / max(degree, 1),
            # ring-attention rotation: (d-1) neighbor exchanges, each
            # moving the FULL per-hop payload (``volume``) — times the
            # callers' shared (d-1)/d fraction this yields exactly
            # (d-1) x volume / bw, the serial ring-hop traffic
            "ppermute": float(degree)}[collective]


def tree_bandwidth_cost(phases: Sequence[Phase],
                        tier_graph: TierGraph) -> float:
    """Bandwidth-only (latency-free) cost of a tree — the per-byte
    MARGINAL a coalesced per-step collective pays, used for gradient
    sync where XLA's combiner amortizes the per-leg latency rounds
    across the whole step (see ``OpCostModel.weight_sync_cost``)."""
    total = 0.0
    for p in phases:
        if p.degree <= 1 or p.volume_bytes <= 0:
            continue
        tier = tier_graph.tier(p.tier)
        total += (bandwidth_multiplier(p.collective, p.degree)
                  * (p.degree - 1) / p.degree
                  * p.volume_bytes * wire_byte_scale(p.wire)
                  / effective_tier_bandwidth(tier))
    return total


def _leg(cost_model, collective: str, degree: int, volume: float,
         tier: Tier, rounds: Optional[int] = None) -> float:
    """Cost of one tree leg confined to ``tier``: the calibrated
    tier-keyed tables answer first (``MeshCalibration.collective_time``
    with a tier), else the analytic ring algebra at the tier's
    bandwidth/latency. ``rounds`` overrides the latency round count
    (halving-doubling's log2(d))."""
    if degree <= 1 or volume <= 0:
        return 0.0
    calib = getattr(cost_model, "calib", None)
    if calib is not None:
        t = calib.collective_time(collective, degree, volume,
                                  tier=tier.name)
        if t is not None:
            return float(t)
    frac = (degree - 1) / degree
    mult = bandwidth_multiplier(collective, degree)
    n_lat = rounds if rounds is not None else (degree - 1)
    return mult * frac * volume / effective_tier_bandwidth(tier) \
        + n_lat * tier.latency_s


def _ring_tree(collective, volume, path) -> Tuple[float, List[Phase]]:
    """Flat ring spanning the whole path: every round traverses the
    bottleneck (outermost) tier; latency accumulates per participant.
    Priced analytically (never from a single-tier calibrated entry) so
    the baseline stays comparable across machines."""
    total_deg = 1
    for _, d in path:
        total_deg *= d
    bottleneck = path[-1][0]
    frac = (total_deg - 1) / total_deg
    mult = bandwidth_multiplier(collective, total_deg)
    cost = mult * frac * volume / effective_tier_bandwidth(bottleneck) \
        + (total_deg - 1) * bottleneck.latency_s
    return cost, [Phase(collective, bottleneck.name, total_deg, volume)]


def _halving_tree(cost_model, collective, volume, path
                  ) -> Optional[Tuple[float, List[Phase]]]:
    """Recursive halving/doubling across the whole span: bandwidth term
    at the bottleneck tier, latency log2(d) rounds. Only defined for
    power-of-two degrees and the reduction collectives."""
    total_deg = 1
    for _, d in path:
        total_deg *= d
    if total_deg & (total_deg - 1) or collective not in (
            "all_reduce", "all_gather", "reduce_scatter"):
        return None
    bottleneck = path[-1][0]
    cost = _leg(cost_model, collective, total_deg, volume, bottleneck,
                rounds=max(1, int(math.log2(total_deg))))
    return cost, [Phase(collective, bottleneck.name, total_deg, volume)]


def _hier_tree(cost_model, collective, volume, path
               ) -> Optional[Tuple[float, List[Phase]]]:
    """The paper's hierarchical tree over a 2- or 3-tier path.

    ``all_reduce``: reduce-scatter innermost → (recursive) all-reduce on
    the tier-reduced volume per outer tier → all-gather innermost — the
    DCN leg carries ``1/d_inner`` of the bytes. ``all_gather`` /
    ``reduce_scatter`` / ``all_to_all``: per-tier staged legs, each
    outer leg on the already-aggregated (or not-yet-inflated) volume.
    """
    if len(path) < 2:
        return None
    phases: List[Phase] = []
    cost = 0.0
    if collective == "all_reduce":
        # recursive: rs@inner on V → all-reduce of the REMAINING path on
        # V/d_inner (itself hierarchical on 3-tier paths) → ag@inner on
        # V.  Only 1/d_inner of the bytes ever reach each outer tier.
        (t_in, d_in) = path[0]
        cost += _leg(cost_model, "reduce_scatter", d_in, volume, t_in)
        phases.append(Phase("reduce_scatter", t_in.name, d_in, volume))
        v = volume / d_in
        rest = path[1:]
        if len(rest) > 1:
            inner = _hier_tree(cost_model, "all_reduce", v, rest)
            cost += inner[0]
            phases.extend(inner[1])
        else:
            (t, d) = rest[0]
            cost += _leg(cost_model, "all_reduce", d, v, t)
            phases.append(Phase("all_reduce", t.name, d, v))
        cost += _leg(cost_model, "all_gather", d_in, volume, t_in)
        phases.append(Phase("all_gather", t_in.name, d_in, volume))
        return cost, phases
    if collective == "all_gather":
        # staged OUTERMOST first: the slow tier gathers while shards
        # are smallest, so it moves (d_out - 1) x shard bytes instead
        # of the flat ring's (total - 1) x shard. This is GSPMD's
        # hierarchical all-gather on real pods (the partitioner owns
        # the concat order); the repo's OWN tiled-suffix lowering
        # (reshard._tier_staged) cannot realize it and is therefore
        # priced separately and conservatively — see
        # ReshardPlanner._score's bottleneck-ring rule.
        total = 1
        for _, d in path:
            total *= d
        v_local = volume / total
        for (t, d) in path[::-1]:
            group_v = v_local * d       # the leg's gathered payload
            cost += _leg(cost_model, "all_gather", d, group_v, t)
            phases.append(Phase("all_gather", t.name, d, group_v))
            v_local = group_v
        return cost, phases
    if collective == "reduce_scatter":
        # staged INNERMOST first (the all-gather tree's mirror): each
        # outer leg scatters the already-reduced, shrunken payload
        v = volume
        for (t, d) in path:
            cost += _leg(cost_model, "reduce_scatter", d, v, t)
            phases.append(Phase("reduce_scatter", t.name, d, v))
            v = v / d
        return cost, phases
    if collective in ("all_to_all", "permute"):
        for (t, d) in path:
            cost += _leg(cost_model, "all_to_all", d, volume, t)
            phases.append(Phase("all_to_all", t.name, d, volume))
        return cost, phases
    return None


def choose_reduction_tree(cost_model, collective: str, volume: float,
                          path: Sequence[Tuple[Tier, int]]
                          ) -> Optional[TreeChoice]:
    """Pick the cheapest reduction-tree shape for one collective over a
    (tier, degree) path. Returns None for empty/degenerate paths —
    callers keep their flat-mesh pricing (single-tier machines stay
    bit-identical to the historical model through that fallback)."""
    path = [p for p in path if p[1] > 1]
    if not path or volume <= 0:
        return None
    flat_cost, flat_phases = _ring_tree(collective, volume, path)
    cands: List[Tuple[float, str, List[Phase]]] = [
        (flat_cost, "ring", flat_phases)]
    hd = _halving_tree(cost_model, collective, volume, path)
    if hd is not None:
        cands.append((hd[0], "halving_doubling", hd[1]))
    hier = _hier_tree(cost_model, collective, volume, path)
    if hier is not None:
        name = "two_phase" if len(path) == 2 else "three_phase"
        cands.append((hier[0], name, hier[1]))
    cands.sort(key=lambda c: (c[0], TREE_ALGORITHMS.index(c[1])))
    cost, algo, phases = cands[0]
    return TreeChoice(algo=algo, phases=phases, cost_s=cost,
                      flat_cost_s=flat_cost)
