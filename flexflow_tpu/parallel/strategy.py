"""Parallelization strategy: per-op sharding assignment.

The searched artifact. Reference analog: the (PCG, MachineView map) pair
produced by ``Graph::graph_optimize_task`` — here it is a map
layer-name → {output PartitionSpecs, weight PartitionSpecs} over one global
device mesh. The executor turns these into ``NamedSharding`` constraints
inside the jitted step; XLA GSPMD then inserts the ICI collectives the
reference expressed as explicit parallel ops + NCCL cliques.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec as P

from ..ffconst import OperatorType, PARALLEL_OPS
from .machine import DeviceMesh


def _spec_axes(spec) -> List[str]:
    axes: List[str] = []
    for e in spec:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            axes.extend(e)
        else:
            axes.append(e)
    return axes


@dataclasses.dataclass
class OpSharding:
    """Sharding of one op's outputs and weights."""
    outputs: List[Optional[P]] = dataclasses.field(default_factory=list)
    weights: Dict[str, P] = dataclasses.field(default_factory=dict)

    def degree_of(self, dmesh: DeviceMesh, out_idx: int = 0) -> int:
        spec = self.outputs[out_idx]
        if spec is None:
            return 1
        d = 1
        for a in _spec_axes(spec):
            d *= dmesh.axis_sizes[a]
        return d


class ShardingStrategy:
    """Complete strategy for a graph over a mesh."""

    def __init__(self, dmesh: DeviceMesh):
        self.dmesh = dmesh
        self.ops: Dict[str, OpSharding] = {}
        self.inputs: Dict[str, P] = {}   # input tensor name -> spec
        # set by parallel.presets.pipeline_strategy: a PipelineRegion the
        # executor lowers onto the GPipe engine (None = no pipelining)
        self.pipeline = None
        # per-op concurrent device-subset placements (parallel/banks.py
        # BankSpec list) — the reference's MachineView concept
        # (machine_view.h:14-62); member ops run on disjoint subsets
        self.banks: List = []
        # heterogeneous-op placement regions (parallel/banks.py
        # PlaceGroup list): mixed op types on disjoint axis blocks,
        # lowered as a lax.switch shard_map region (MPMD-inside-SPMD)
        self.place_groups: List = []
        # hierarchical placement annotations (parallel/placement.py,
        # arXiv 2110.10548), set by a placement-aware search:
        #   axis_tiers       — mesh axis -> hardware tier ("ici"/"host"/
        #                      "dcn") the adopted placement assigned;
        #   collective_trees — per-collective-site chosen reduction-tree
        #                      records ({site, collective, degree,
        #                      tier_path, algo, phases, cost_s, ...})
        # Both serialize with the strategy and are statically checked by
        # analysis/plan_verifier's placement pass.
        self.axis_tiers: Dict[str, str] = {}
        self.collective_trees: List[Dict] = []
        # per-parameter optimizer-state sharding (runtime/zero.py
        # ZeroAssignment, planned by search/zero_plan.py per arXiv
        # 2004.13336): layer -> weight -> {spec, degree, bytes_saved,
        # overhead_s}. None = fully replicated optimizer state (or the
        # legacy uniform --zero flag, which bypasses the assignment).
        # Serializes with the strategy and is statically checked by
        # analysis/plan_verifier's zero pass.
        self.zero = None
        # quantized gradient collectives (ops/quantized_collectives.py
        # QsyncPlan, arXiv 2506.17615): per-tensor, per-phase wire
        # dtype of each gradient sync — quantize the slow (DCN) legs,
        # keep ICI legs and every replicated-math seam full-precision.
        # None = every sync at the element dtype. Serializes with the
        # strategy (--import honors it verbatim) and is statically
        # checked by analysis/plan_verifier's qsync pass.
        self.qsync = None
        # per-(model, batch-class) serving plans (search/serving_plan.py
        # ServingPlan.to_block() JSON): one sub-strategy per batch
        # bucket + the KV-cache geometry/shard degrees. None for
        # training strategies. Serializes as the artifact's "serving"
        # block and is statically checked by analysis/plan_verifier's
        # serving pass (KV sharding sound, envelope fits at the largest
        # bucket).
        self.serving = None
        # searched per-op kernel-implementation assignment
        # (kernels/registry.py, planned by FFModel._plan_kernels):
        # op kind -> impl for graph-wide kinds ("opt_update": "fused")
        # and layer-name -> impl for attention ops ("attn0": "ring").
        # {} / missing key = the kind's default impl. Serializes as the
        # artifact's "kernel_impls" block (--import honors it verbatim)
        # and is statically checked by analysis/plan_verifier's kernel
        # pass (every chosen impl's availability predicate must hold on
        # the adopted mesh/shapes).
        self.kernel_impls: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def set_op(self, layer_name: str, outputs: Sequence[Optional[P]],
               weights: Optional[Dict[str, P]] = None):
        self.ops[layer_name] = OpSharding(list(outputs), dict(weights or {}))

    def output_sharding(self, layer_name: str, idx: int = 0
                        ) -> Optional[NamedSharding]:
        os = self.ops.get(layer_name)
        if os is None or idx >= len(os.outputs) or os.outputs[idx] is None:
            return None
        return NamedSharding(self.dmesh.mesh, os.outputs[idx])

    def weight_sharding(self, layer_name: str, wname: str) -> NamedSharding:
        os = self.ops.get(layer_name)
        spec = os.weights.get(wname, P()) if os else P()
        return NamedSharding(self.dmesh.mesh, spec)

    def input_sharding(self, tensor_name: str) -> NamedSharding:
        return NamedSharding(self.dmesh.mesh,
                             self.inputs.get(tensor_name, P()))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.dmesh.mesh, P())

    # ------------------------------------------------------------------
    @classmethod
    def data_parallel(cls, layers, input_tensors, dmesh: DeviceMesh
                      ) -> "ShardingStrategy":
        """Canonical pure-DP strategy: batch dim sharded over ALL mesh axes,
        weights replicated. Analog of the reference's
        ``--only-data-parallel`` canonical view (``graph.cc:1939-1964``)."""
        st = cls(dmesh)
        # the reserved seq axis (ring attention's context axis) never
        # carries the batch dim — DP spans the general sharding axes
        axes = dmesh.sharding_axes
        nd = dmesh.sharding_devices
        batch_axes = axes if len(axes) > 1 else (axes[0] if axes else None)
        if nd == 1:
            return st  # single device: everything unsharded
        for t in input_tensors:
            if t.shape and t.shape[0] % nd == 0:
                st.inputs[t.name] = P(batch_axes)
        for layer in layers:
            outs = []
            for o in layer.outputs:
                if o.shape and o.shape[0] % nd == 0:
                    outs.append(P(batch_axes))
                else:
                    outs.append(None)
            st.set_op(layer.name, outs, {})
        return st

    # ------------------------------------------------------------------
    def validate(self) -> List[str]:
        """Check axis-use consistency within each spec (an axis may appear
        at most once per PartitionSpec)."""
        errors = []
        for name, os in self.ops.items():
            for spec in list(os.outputs) + list(os.weights.values()):
                if spec is None:
                    continue
                axes = _spec_axes(spec)
                if len(axes) != len(set(axes)):
                    errors.append(f"{name}: axis reused in {spec}")
                for a in axes:
                    if a not in self.dmesh.axis_sizes:
                        errors.append(f"{name}: unknown axis {a}")
        return errors

    def describe(self) -> str:
        lines = [f"mesh axes: {dict(self.dmesh.axis_sizes)}"]
        if self.axis_tiers:
            lines.append(f"axis tiers: {dict(self.axis_tiers)}")
        for ct in self.collective_trees:
            lines.append(
                f"  tree {ct.get('site')}/{ct.get('collective')}"
                f" x{ct.get('degree')}: {ct.get('algo')} over "
                f"{ct.get('tier_path')}")
        if self.zero is not None:
            s = self.zero.summary()
            lines.append(
                f"zero: {s['n_sharded']}/{s['n_params']} opt states "
                f"sharded ({s['policy']}), "
                f"{s['bytes_saved_total'] / 2**20:.1f} MiB/device saved")
        if self.qsync is not None:
            s = self.qsync.summary()
            lines.append(
                f"qsync: {s['n_quantized']}/{s['n_params']} grad syncs "
                f"quantized ({s['mode']}, wire {s['wire']})")
        if self.kernel_impls:
            lines.append(f"kernel impls: {dict(self.kernel_impls)}")
        for name, os in self.ops.items():
            lines.append(f"  {name}: out={os.outputs} w={os.weights}")
        for bk in self.banks:
            views = bk.machine_views(self.dmesh)
            lines.append(f"  bank over axes {bk.axes}:")
            for m in bk.members:
                lines.append(f"    {m}: devices {views[m].device_ids}")
        return "\n".join(lines)
