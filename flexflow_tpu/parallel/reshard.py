"""Searched, memory-optimal resharding collectives for layout transitions.

Every layout transition in the stack — bank-boundary rejoins
(``parallel/banks.py``), pipeline-region entry/exit
(``parallel/pipeline_lowering.py`` + executor), and the elastic
re-plan's reshard-restored-state path (``resilience/elastic.py`` riding
``runtime/checkpoint.py``) — used to lower through GSPMD's generic
resharding: the partitioner was free to pick gather/scatter rewrites
("involuntary full rematerialization"), which is slow, memory-peaky,
and — on the reshape/concat rewrites this repo's two standing alignment
failures exercised — outright miscompiled on the CPU backend.

Following PAPERS.md "Memory-efficient array redistribution through
portable collective communication" (arXiv 2112.01075), a transition
``src layout → dst layout`` is instead lowered to a short sequence of
portable collective steps with explicit semantics:

  - ``gather``   — all-gather a suffix of a dim's mesh axes (the dim's
                   minor-most shard factors), inflating the local shard;
  - ``alltoall`` — move one mesh axis from one dim's sharding to
                   another's at CONSTANT per-device memory (the paper's
                   key primitive: an all-to-all replaces an
                   allgather+slice pair, cutting both time and peak);
  - ``slice``    — locally slice a dim by new mesh axes (no traffic).

The planner enumerates candidate step orderings (all-to-all-first /
gather-first / the naive gather-everything-then-slice baseline), scores
each for TIME and PEAK TRANSIENT MEMORY with the calibrated collective
tables (``search/calibration.py`` via
``search/costmodel.OpCostModel.reshard_step_cost``), and executes the
winner as ONE ``shard_map`` whose in/out specs pin the src/dst layouts —
GSPMD has no freedom left to fumble the transition. Plans are cached
per (src, dst, mesh, dtype, shape-class) in ``.ffcache`` alongside the
calibration tables, so warm processes never re-plan.

``FF_NAIVE_RESHARD=1`` keeps the pre-planner path (bare
``with_sharding_constraint`` / ``device_put``) as the bench/fallback
baseline. Every planned transition emits an obs span plus the
``ff_reshard_bytes_total`` / ``ff_reshard_plans_total{kind=...}``
counters, and the chosen step sequence is appended to the strategy
audit record when a search wrote one (``obs/audit.py``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import OperatorType
from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

#: ops whose GSPMD partitioning rewrites are the risky ones (reshape /
#: concat re-tiling is where the backward-propagated constraint
#: miscompiled); transitions on their outputs go through the planner
LAYOUT_OPS = frozenset({
    OperatorType.OP_RESHAPE, OperatorType.OP_TRANSPOSE,
    OperatorType.OP_CONCAT, OperatorType.OP_SPLIT, OperatorType.OP_FLAT,
    OperatorType.OP_SLICE, OperatorType.OP_PAD, OperatorType.OP_REVERSE,
    OperatorType.OP_SQUEEZE, OperatorType.OP_UNSQUEEZE,
})


def naive_reshard() -> bool:
    """``FF_NAIVE_RESHARD=1``: keep the pre-planner transition path
    (bare sharding constraints / whole-array device_put) — the bench
    baseline and the escape hatch. Read per call: the flag is consulted
    at trace/restore time, so separate compiles (e.g. the bench's
    paired legs) can flip it per process."""
    return os.environ.get("FF_NAIVE_RESHARD", "").lower() \
        in ("1", "true", "yes", "on")


# ----------------------------------------------------------------------
# layout normalization
# ----------------------------------------------------------------------

def norm_spec(spec, rank: int) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec → per-dim tuples of mesh axes, padded to ``rank``.
    ``None`` (no constraint) normalizes to fully replicated — the only
    layout a transition can assume for an unconstrained value."""
    dims: List[Tuple[str, ...]] = []
    if spec is not None:
        for e in tuple(spec):
            if e is None:
                dims.append(())
            elif isinstance(e, (tuple, list)):
                dims.append(tuple(e))
            else:
                dims.append((e,))
    while len(dims) < rank:
        dims.append(())
    return tuple(dims[:rank])


def _to_partition_spec(norm: Sequence[Tuple[str, ...]]):
    from jax.sharding import PartitionSpec as P
    entries: List[Any] = []
    for d in norm:
        if not d:
            entries.append(None)
        elif len(d) == 1:
            entries.append(d[0])
        else:
            entries.append(tuple(d))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def layout_key(norm: Sequence[Tuple[str, ...]]) -> str:
    return "|".join("+".join(d) if d else "-" for d in norm)


# ----------------------------------------------------------------------
# step vocabulary + plans
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Step:
    """One portable collective in a lowering plan. ``axes`` are mesh
    axes in major→minor order; for ``alltoall`` the axis moves from
    ``src_dim``'s sharding (where it is minor-most) onto ``dim``'s
    (appended minor-most)."""
    kind: str                       # "gather" | "alltoall" | "slice"
    dim: int
    axes: Tuple[str, ...]
    src_dim: int = -1               # alltoall only

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "dim": self.dim,
                "axes": list(self.axes), "src_dim": self.src_dim}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Step":
        return cls(d["kind"], int(d["dim"]), tuple(d["axes"]),
                   int(d.get("src_dim", -1)))


@dataclasses.dataclass
class ReshardPlan:
    """A scored lowering of one src→dst transition."""
    src: Tuple[Tuple[str, ...], ...]
    dst: Tuple[Tuple[str, ...], ...]
    steps: List[Step]
    est_time_s: float = 0.0
    peak_bytes: float = 0.0         # per-device transient working set
    naive_peak_bytes: float = 0.0   # the gather-everything baseline's
    kind: str = "searched"          # "searched" | "naive" | "constraint"

    def describe(self) -> List[str]:
        out = []
        for s in self.steps:
            if s.kind == "alltoall":
                out.append(f"alltoall[{'+'.join(s.axes)}] "
                           f"dim{s.src_dim}->dim{s.dim}")
            else:
                out.append(f"{s.kind}[{'+'.join(s.axes)}] dim{s.dim}")
        return out


def _candidate_steps(src, dst, priority: Sequence[str]
                     ) -> Optional[List[Step]]:
    """Greedy lowering of src→dst under a step-kind priority order.
    Invariants maintained: a dim is only ever gathered over the suffix
    of its axes beyond its common prefix with the target (minor-most
    shard factors — the only relayout ``all_gather(tiled)`` realizes
    exactly), slices append minor-most axes in target order, and an
    all-to-all moves exactly one minor-most axis onto the next axis its
    target dim needs. Returns None when the greedy walk cannot reach
    ``dst`` (caller falls back to the naive candidate)."""
    cur = [list(d) for d in src]
    tgt = [list(d) for d in dst]
    ndim = len(cur)
    steps: List[Step] = []

    def prefix_len(d):
        k = 0
        while k < len(cur[d]) and k < len(tgt[d]) \
                and cur[d][k] == tgt[d][k]:
            k += 1
        return k

    def find_move() -> Optional[Step]:
        for i in range(ndim):
            if len(cur[i]) <= prefix_len(i):
                continue
            a = cur[i][-1]
            for j in range(ndim):
                if j == i or cur[j] != tgt[j][:len(cur[j])]:
                    continue
                if len(cur[j]) < len(tgt[j]) \
                        and tgt[j][len(cur[j])] == a:
                    return Step("alltoall", dim=j, axes=(a,), src_dim=i)
        return None

    def find_gather() -> Optional[Step]:
        for i in range(ndim):
            k = prefix_len(i)
            if len(cur[i]) > k:
                return Step("gather", dim=i, axes=tuple(cur[i][k:]))
        return None

    def find_slice() -> Optional[Step]:
        used = {a for c in cur for a in c}
        for j in range(ndim):
            if cur[j] != tgt[j][:len(cur[j])]:
                continue
            pend = tgt[j][len(cur[j]):]
            take: List[str] = []
            for a in pend:
                if a in used:
                    break
                take.append(a)
            if take:
                return Step("slice", dim=j, axes=tuple(take))
        return None

    finders = {"alltoall": find_move, "gather": find_gather,
               "slice": find_slice}
    while cur != tgt:
        step = None
        for kind in priority:
            step = finders[kind]()
            if step is not None:
                break
        if step is None:
            return None
        steps.append(step)
        if step.kind == "gather":
            del cur[step.dim][len(cur[step.dim]) - len(step.axes):]
        elif step.kind == "slice":
            cur[step.dim].extend(step.axes)
        else:
            cur[step.src_dim].pop()
            cur[step.dim].append(step.axes[0])
        if len(steps) > 8 * ndim + 8:       # safety against livelock
            return None
    return steps


def _tier_staged(steps: Sequence[Step],
                 axis_tiers: Dict[str, str]) -> Optional[List[Step]]:
    """Hierarchical lowering of a candidate: split every gather whose
    axes span more than one hardware tier into per-tier staged gathers
    (minor-most run first — the only order ``all_gather(tiled)``
    realizes), so each leg is ONE portable collective confined to one
    fabric and the cost model prices it at that tier's bandwidth
    (arXiv 2110.10548's per-tier reduction phases). Returns None when
    nothing splits (single-tier plans stay byte-identical)."""
    out: List[Step] = []
    changed = False
    for st in steps:
        if st.kind != "gather" or len(st.axes) < 2:
            out.append(st)
            continue
        # group the axis tuple (major→minor) into consecutive same-tier
        # runs; emit minor-most run first
        runs: List[List[str]] = [[st.axes[0]]]
        for a in st.axes[1:]:
            if axis_tiers.get(a) == axis_tiers.get(runs[-1][-1]):
                runs[-1].append(a)
            else:
                runs.append([a])
        if len(runs) == 1:
            out.append(st)
            continue
        changed = True
        for run in runs[::-1]:
            out.append(Step("gather", dim=st.dim, axes=tuple(run)))
    return out if changed else None


def _naive_steps(src, dst) -> List[Step]:
    """The generic gather/scatter lowering: fully replicate, then slice
    to the destination — what GSPMD's 'full rematerialization' does."""
    steps: List[Step] = []
    for i, axes in enumerate(src):
        if axes:
            steps.append(Step("gather", dim=i, axes=tuple(axes)))
    for j, axes in enumerate(dst):
        if axes:
            steps.append(Step("slice", dim=j, axes=tuple(axes)))
    return steps


# ----------------------------------------------------------------------
# stats (tests + audit introspection)
# ----------------------------------------------------------------------

class ReshardStats:
    """Process-wide reshard accounting, mirrored into the Prometheus
    registry (``ff_reshard_*``). Kept as plain attributes so tests and
    the elastic e2e can assert 'this state went through the planner'."""

    def __init__(self):
        self.lock = threading.Lock()
        self.reset()

    def reset(self):
        # __init__ assigns self.lock before calling reset(), so the
        # lock always exists here — hold it so a concurrent record()
        # never interleaves with a test's reset
        with self.lock:
            self.planned = 0
            self.plan_cache_hits = 0
            self.executed_searched = 0
            self.executed_naive = 0
            self.host_placements = 0
            self.bytes_total = 0.0
            self.last_plans: List[Dict[str, Any]] = []

    def record(self, kind: str, nbytes: float,
               record: Optional[Dict[str, Any]] = None):
        with self.lock:
            if kind == "searched":
                self.executed_searched += 1
            else:
                self.executed_naive += 1
            self.bytes_total += nbytes
            if record is not None:
                self.last_plans.append(record)
                del self.last_plans[:-64]
        REGISTRY.counter(
            "ff_reshard_plans_total",
            "Executed layout-transition lowerings by kind").inc(kind=kind)
        REGISTRY.counter(
            "ff_reshard_bytes_total",
            "Bytes moved through planned layout transitions").inc(
                max(nbytes, 0.0))
        obs_events.counter(f"reshard.{kind}")


STATS = ReshardStats()


# ----------------------------------------------------------------------
# the planner
# ----------------------------------------------------------------------

class ReshardPlanner:
    """Plan + execute layout transitions on one mesh.

    ``cost_model`` is a ``search.costmodel.OpCostModel`` (analytic by
    default; when calibration v2 is enabled the persisted collective
    tables answer first — the planner READS those tables, it never
    writes them, so the ranker-fidelity baseline is untouched).
    """

    def __init__(self, dmesh, cost_model=None,
                 cache_dir: Optional[str] = None, persist: bool = True):
        self.dmesh = dmesh
        self._cm = cost_model
        self._cache_dir = cache_dir or _DEFAULT_DIR
        self._memo: Dict[Tuple, ReshardPlan] = {}
        # persist=False: read the warm disk cache but never write it —
        # the static plan verifier probes seam legality without seeding
        # plans the executor would then count as ITS disk hits, while
        # still reusing already-planned lowerings instead of re-running
        # the candidate search on every verified compile
        self._disk: Optional[Dict[str, Any]] = None
        self._persist = persist
        self.audit_path: Optional[str] = None
        self._audit_records: List[Dict[str, Any]] = []
        # communication–computation overlap (runtime/overlap.py): when
        # resolved on, multi-leg TIER-STAGED plans execute PIPELINED —
        # the tensor splits into chunks on an untouched dim so leg k+1
        # of chunk j runs while leg k of chunk j+1 still occupies the
        # other fabric, instead of the legs running back-to-back.
        # None = resolve from FF_OVERLAP lazily (FFModel.compile sets
        # it from FFConfig.overlap); bit-exact either way — chunking a
        # collective on an untouched dim is pure data movement.
        self.overlap_on: Optional[bool] = None
        self.mesh_key = "x".join(
            f"{a}{s}" for a, s in dmesh.axis_sizes.items())
        # multi-tier meshes key their plans per tier layout: a plan
        # chosen for a flat mesh (or before the hierarchy existed) must
        # not be replayed where tier-staged lowering applies;
        # single-tier meshes keep their warm cache entries verbatim
        tiers = self.axis_tiers
        if tiers:
            self.mesh_key += "|" + ",".join(
                f"{a}={tiers[a]}" for a in sorted(tiers))

    # -- cost model (lazy: most transitions are planned at first trace)
    @property
    def cost_model(self):
        if self._cm is None:
            from ..search.costmodel import OpCostModel
            cm = OpCostModel(self.dmesh.spec, cache_dir=self._cache_dir)
            try:
                from ..search.calibration import (CalibrationTable,
                                                  MeshCalibration)
                import jax
                # attach the persisted tables READ-ONLY: lookups answer
                # from warm entries; misses fall to the analytic model
                # (no microbenchmarks are run from the execution path)
                cm.calib = MeshCalibration(
                    backend=jax.default_backend(),
                    table=CalibrationTable(self._cache_dir))
            except Exception:  # noqa: BLE001 — calibration optional
                pass
            try:
                from .placement import AxisPlacement
                pl = AxisPlacement.from_dmesh(self.dmesh)
                if pl is not None and pl.multi_tier:
                    cm.attach_placement(pl, "hier")
            except Exception:  # noqa: BLE001 — placement optional
                pass
            self._cm = cm
        return self._cm

    @property
    def axis_tiers(self) -> Dict[str, str]:
        """Mesh-axis → tier map for hierarchical step staging; empty on
        single-tier machines and duck-typed meshes without one."""
        try:
            tiers = dict(self.dmesh.axis_tiers)
            return tiers if len(set(tiers.values())) > 1 else {}
        except Exception:  # noqa: BLE001
            return {}

    # -- disk plan cache ------------------------------------------------
    @property
    def _disk_path(self) -> str:
        return os.path.join(self._cache_dir, "reshard_plans.json")

    def _disk_cache(self) -> Dict[str, Any]:
        if self._disk is None:
            try:
                with open(self._disk_path) as f:
                    self._disk = json.load(f)
            except Exception:  # noqa: BLE001
                self._disk = {}
        return self._disk

    def _disk_put(self, key: str, doc: Dict[str, Any]) -> None:
        cache = self._disk_cache()
        cache[key] = doc
        if not self._persist:
            return
        try:
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = self._disk_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cache, f)
            os.replace(tmp, self._disk_path)
        except Exception:  # noqa: BLE001 — persistence is best-effort
            pass

    # -------------------------------------------------------------------
    def _divisible(self, norm, shape) -> bool:
        sizes = self.dmesh.axis_sizes
        for d, axes in enumerate(norm):
            deg = 1
            for a in axes:
                if a not in sizes:
                    return False
                deg *= sizes[a]
            if deg > 1 and (d >= len(shape) or shape[d] % deg != 0):
                return False
        return True

    def _score(self, steps: Sequence[Step], shape, itemsize: int,
               src) -> Tuple[float, float]:
        """(est time s, peak per-device transient bytes) of a plan.
        Peak counts both live buffers of the in-flight step — the
        quantity the paper minimizes and the bench leg gates on."""
        sizes = self.dmesh.axis_sizes
        cm = self.cost_model
        global_bytes = float(int(np.prod(shape)) * itemsize) \
            if shape else float(itemsize)
        deg = 1
        for axes in src:
            for a in axes:
                deg *= sizes[a]
        local = global_bytes / max(deg, 1)
        peak, t = local, 0.0

        def step_cost(kind: str, g: int, vol: float, axes) -> float:
            # a step whose axes CROSS tiers executes as one XLA
            # collective whose decomposition we do not control — price
            # it conservatively as a flat ring at the bottleneck tier
            # (the tier-staged candidate, one fabric per step, gets the
            # per-tier pricing and wins whenever hierarchy pays)
            pl = getattr(cm, "placement", None)
            if pl is not None and axes:
                path = pl.path_for_axes(axes)
                if len(path) > 1:
                    from .placement import _ring_tree
                    return _ring_tree(kind, vol, path)[0]
            return cm.reshard_step_cost(kind, g, vol, axes=axes)

        for st in steps:
            g = 1
            for a in st.axes:
                g *= sizes[a]
            if st.kind == "gather":
                out_local = local * g
                t += step_cost("all_gather", g, out_local, st.axes)
            elif st.kind == "alltoall":
                out_local = local
                t += step_cost("all_to_all", g, local * g, st.axes)
            else:
                out_local = local / g
                t += cm.reshard_step_cost("slice", g, local)
            peak = max(peak, local + out_local)
            local = out_local
        return t, peak

    def plan(self, src_spec, dst_spec, shape, itemsize: int = 4
             ) -> ReshardPlan:
        """Choose the lowering for ``src_spec → dst_spec`` on arrays of
        ``shape``: enumerate candidate step orderings, score each for
        time and peak transient memory, pick the fastest whose peak
        does not exceed the naive baseline's. Cached in memory and on
        disk per (mesh, src, dst, itemsize, shape-class)."""
        rank = len(shape)
        src = norm_spec(getattr(src_spec, "spec", src_spec), rank)
        dst = norm_spec(getattr(dst_spec, "spec", dst_spec), rank)
        if src == dst:
            # no transition needed: the planner VERIFIED no data moves
            return ReshardPlan(src, dst, [], kind="noop")
        if not (self._divisible(src, shape) and
                self._divisible(dst, shape)):
            # a layout the mesh cannot tile evenly: leave the value to
            # GSPMD's constraint semantics rather than mis-slicing it.
            # Checked BEFORE the cache: plans are keyed by shape-CLASS
            # (factor-of-2 band), and a cached divisible-shape plan must
            # never be replayed onto a same-band indivisible shape
            return ReshardPlan(src, dst, [], kind="constraint")
        from ..search.calibration import shape_class
        nbytes = int(np.prod(shape)) * itemsize if shape else itemsize
        key = (self.mesh_key, layout_key(src), layout_key(dst),
               itemsize, shape_class(nbytes))
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        plan = self._plan_uncached(src, dst, shape, itemsize, key)
        self._memo[key] = plan
        return plan

    def _plan_uncached(self, src, dst, shape, itemsize, key
                       ) -> ReshardPlan:
        dkey = "|".join(str(k) for k in key)
        doc = self._disk_cache().get(dkey)
        naive = _naive_steps(src, dst)
        naive_t, naive_peak = self._score(naive, shape, itemsize, src)
        if doc is not None:
            obs_events.counter("reshard.plan_cache_hits")
            with STATS.lock:
                STATS.plan_cache_hits += 1
            steps = [Step.from_json(s) for s in doc["steps"]]
            # re-score the cached steps at THIS shape: the cache key is
            # a factor-of-2 shape-class band, so the persisted numbers
            # may belong to a different same-band shape — peak and
            # naive-peak must be a consistent pair at the actual shape
            # or the peak<=naive gate misfires both ways
            t, peak = self._score(steps, shape, itemsize, src)
            return ReshardPlan(src, dst, steps, est_time_s=t,
                               peak_bytes=peak,
                               naive_peak_bytes=naive_peak,
                               kind=doc.get("kind", "searched"))
        with obs_events.span("reshard.plan", src=layout_key(src),
                             dst=layout_key(dst)):
            candidates: List[Tuple[float, float, List[Step], str]] = []
            tiers = self.axis_tiers
            for prio in (("alltoall", "slice", "gather"),
                         ("alltoall", "gather", "slice"),
                         ("gather", "slice", "alltoall")):
                steps = _candidate_steps(src, dst, prio)
                if steps is not None:
                    t, peak = self._score(steps, shape, itemsize, src)
                    candidates.append((t, peak, steps, "searched"))
                    if tiers:
                        # hierarchical variant: tier-crossing gathers
                        # staged per fabric (one portable collective
                        # per tier leg — the executor-side lowering of
                        # the searched reduction trees)
                        staged = _tier_staged(steps, tiers)
                        if staged is not None:
                            t2, p2 = self._score(staged, shape,
                                                 itemsize, src)
                            candidates.append((t2, p2, staged,
                                               "searched"))
            candidates.append((naive_t, naive_peak, naive, "naive"))
            # fastest plan whose peak transient memory never exceeds
            # the naive baseline's (every candidate qualifies by
            # construction, but keep the guard explicit); at equal
            # predicted cost, prefer the plan with the FEWEST
            # tier-crossing steps — an unstaged tier-crossing gather
            # leaves the hierarchical decomposition to XLA, the staged
            # variant pins it (one portable collective per fabric leg)
            def crossing(steps: Sequence[Step]) -> int:
                if not tiers:
                    return 0
                return sum(1 for st in steps
                           if len({tiers.get(a) for a in st.axes}) > 1)

            ok = [c for c in candidates if c[1] <= naive_peak + 1e-9] \
                or candidates
            ok.sort(key=lambda c: (round(c[0], 9), c[1],
                                   crossing(c[2]), len(c[2])))
            t, peak, steps, kind = ok[0]
        plan = ReshardPlan(src, dst, steps, est_time_s=t,
                           peak_bytes=peak, naive_peak_bytes=naive_peak,
                           kind=kind)
        with STATS.lock:
            STATS.planned += 1
        obs_events.counter("reshard.plans_created")
        self._disk_put(dkey, {"steps": [s.to_json() for s in steps],
                              "time_s": t, "peak_bytes": peak,
                              "kind": kind})
        self._audit(plan, shape)
        return plan

    def _audit(self, plan: ReshardPlan, shape) -> None:
        rec = {"src": layout_key(plan.src), "dst": layout_key(plan.dst),
               "shape": list(shape), "steps": plan.describe(),
               "est_time_s": plan.est_time_s,
               "peak_bytes": plan.peak_bytes,
               "naive_peak_bytes": plan.naive_peak_bytes,
               "kind": plan.kind}
        self._audit_records.append(rec)
        del self._audit_records[:-64]
        obs_events.instant("reshard.plan_chosen", **{
            k: v for k, v in rec.items() if k != "shape"})
        if self.audit_path:
            from ..obs.audit import annotate_strategy_audit
            annotate_strategy_audit(
                self.audit_path, {"reshard_plans":
                                  list(self._audit_records)})

    # -------------------------------------------------------------------
    def execute(self, x, plan: ReshardPlan):
        """Run a plan inside the current trace: one ``shard_map`` whose
        in/out specs pin the src/dst layouts and whose body applies the
        explicit collective steps. Differentiable (all steps have exact
        transposes under shard_map)."""
        import jax
        from jax.sharding import NamedSharding
        from ..utils.jax_compat import shard_map
        mesh = self.dmesh.mesh
        dst_P = _to_partition_spec(plan.dst)
        nbytes = float(getattr(x, "size", 0) or 0) * \
            float(np.dtype(x.dtype).itemsize if hasattr(x, "dtype") else 4)
        if plan.kind in ("constraint", "noop") or not plan.steps:
            # "noop" (planner verified src == dst, nothing moves) counts
            # as searched; "constraint" (mesh can't tile the shape, GSPMD
            # picks the lowering) IS the naive path — account it as such
            STATS.record("naive" if naive_reshard()
                         or plan.kind == "constraint" else "searched",
                         nbytes)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, dst_P))
        src_P = _to_partition_spec(plan.src)
        sizes = self.dmesh.axis_sizes
        steps = list(plan.steps)

        def run_steps(xl):
            for st in steps:
                ax = st.axes if len(st.axes) > 1 else st.axes[0]
                if st.kind == "gather":
                    xl = jax.lax.all_gather(xl, ax, axis=st.dim,
                                            tiled=True)
                elif st.kind == "alltoall":
                    xl = jax.lax.all_to_all(xl, ax, split_axis=st.dim,
                                            concat_axis=st.src_dim,
                                            tiled=True)
                else:
                    idx = 0
                    deg = 1
                    for a in st.axes:
                        idx = idx * sizes[a] + jax.lax.axis_index(a)
                        deg *= sizes[a]
                    blk = xl.shape[st.dim] // deg
                    xl = jax.lax.dynamic_slice_in_dim(
                        xl, idx * blk, blk, st.dim)
            return xl

        pipe = self._pipeline_chunks(plan, tuple(getattr(x, "shape", ())),
                                     nbytes)
        if pipe is None:
            body = run_steps
        else:
            chunk_dim, n_chunks = pipe

            def body(xl):  # noqa: F811 — pipelined variant
                # tier-staged legs pipelined across fabric legs
                # (runtime/overlap.py): chunks are data-independent,
                # so leg k+1 of chunk j overlaps leg k of chunk j+1 on
                # the other fabric. Splitting on an untouched dim
                # commutes with every step — bit-exact with run_steps.
                import jax.numpy as jnp
                parts = jnp.split(xl, n_chunks, axis=chunk_dim)
                return jnp.concatenate([run_steps(p) for p in parts],
                                       axis=chunk_dim)

            from ..obs.metrics_registry import REGISTRY
            REGISTRY.counter(
                "ff_reshard_pipelined_total",
                "Tier-staged reshard plans executed with pipelined "
                "fabric legs").inc()
            obs_events.counter("reshard.pipelined_legs")

        out = shard_map(body, mesh=mesh, in_specs=src_P, out_specs=dst_P,
                        check_vma=False)(x)
        STATS.record("searched", nbytes, record={
            "src": layout_key(plan.src), "dst": layout_key(plan.dst),
            "steps": plan.describe()})
        return out

    def _pipeline_chunks(self, plan: ReshardPlan, shape,
                         nbytes: float) -> Optional[Tuple[int, int]]:
        """(chunk_dim, n_chunks) for pipelined tier-staged execution,
        or None for the serial (default) leg order. Pipelining applies
        only when overlap is on, the plan has >= 2 collective legs on
        >= 2 distinct hardware tiers (the PR 9 tier-staged lowering),
        the payload clears 1 MiB (below that the extra per-leg launch
        latency outweighs the overlap), and some tensor dim is touched
        by NO step and divides into chunks at the shard-local entry
        shape."""
        on = self.overlap_on
        if on is None:
            from ..runtime.overlap import overlap_enabled
            on = overlap_enabled(None)
        if not on or len(plan.steps) < 2 or nbytes < (1 << 20):
            return None
        tiers = self.axis_tiers
        if not tiers:
            return None
        leg_tiers = {tiers.get(a) for st in plan.steps
                     if st.kind != "slice" for a in st.axes}
        if len(leg_tiers) < 2:
            return None
        touched = set()
        for st in plan.steps:
            touched.add(st.dim)
            if st.kind == "alltoall":
                touched.add(st.src_dim)
        for d in range(len(shape)):
            if d in touched:
                continue
            deg = 1
            if d < len(plan.src):
                for a in plan.src[d]:
                    deg *= self.dmesh.axis_sizes.get(a, 1)
            local = shape[d] // max(deg, 1)
            for n in (4, 2):
                if local % n == 0 and local >= n:
                    return d, n
        return None

    def apply(self, x, src_spec, dst_spec):
        """Plan (or load) and execute one transition; the module's
        single entry point for in-graph layout changes. With
        ``FF_NAIVE_RESHARD=1`` this degrades to the bare sharding
        constraint (the pre-planner behavior)."""
        import jax
        from jax.sharding import NamedSharding
        dst_P = _to_partition_spec(
            norm_spec(getattr(dst_spec, "spec", dst_spec),
                      len(x.shape)))
        if naive_reshard():
            nbytes = float(getattr(x, "size", 0) or 0) * \
                float(np.dtype(x.dtype).itemsize
                      if hasattr(x, "dtype") else 4)
            STATS.record("naive", nbytes)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.dmesh.mesh, dst_P))
        itemsize = int(np.dtype(x.dtype).itemsize) \
            if hasattr(x, "dtype") else 4
        plan = self.plan(src_spec, dst_spec, tuple(x.shape), itemsize)
        return self.execute(x, plan)


# ----------------------------------------------------------------------
# executor hook: transition-aware output constraint
# ----------------------------------------------------------------------

def planner_for(strategy) -> ReshardPlanner:
    """The per-strategy planner (created by the executor; built lazily
    here for strategies executed without one, e.g. hand-built tests)."""
    pl = getattr(strategy, "resharder", None)
    if pl is None:
        pl = ReshardPlanner(strategy.dmesh)
        strategy.resharder = pl
    return pl


def tensor_spec(strategy, t):
    """The strategy-assigned PartitionSpec of tensor ``t``: the owning
    layer's output spec, or the graph-input spec (None = unknown /
    unconstrained). The single spec-resolution helper shared by the
    bank-boundary and pipeline-boundary wiring."""
    if t.owner_layer is not None:
        os_ = strategy.ops.get(t.owner_layer.name)
        if os_ is not None and t.owner_idx < len(os_.outputs):
            return os_.outputs[t.owner_idx]
        return None
    return strategy.inputs.get(t.name)


def _input_specs_replicated(strategy, layer) -> bool:
    """True when every input of ``layer`` is unconstrained/replicated
    under ``strategy`` — i.e. the op's output provably carries no
    sharding yet and a sharded output constraint is a genuine
    replicated→sharded transition."""
    for t in layer.inputs:
        spec = tensor_spec(strategy, t)
        if spec is not None and any(norm_spec(spec, len(t.shape))):
            return False
    return True


def constrain_output(o, sharding, strategy, layer):
    """The executor's per-op output constraint. For pure layout ops
    (reshape/transpose/concat/...) whose inputs are replicated and
    whose assigned output spec is sharded, the transition is executed
    EXPLICITLY through the planner (a local slice — no communication)
    instead of a bare ``with_sharding_constraint``: GSPMD's backward
    propagation of a tiled constraint through reshape/concat is the
    documented miscompile the standing alignment failure exercised.
    Everything else keeps the plain constraint (a matching constraint
    on an already-sharded chain is a no-op hint, not a transition)."""
    import jax
    spec = sharding.spec
    rank = len(getattr(o, "shape", ()))
    if naive_reshard() \
            or not any(norm_spec(spec, rank)) \
            or layer.op_type not in LAYOUT_OPS \
            or not _input_specs_replicated(strategy, layer):
        return jax.lax.with_sharding_constraint(o, sharding)
    from jax.sharding import PartitionSpec as P
    return planner_for(strategy).apply(o, P(), spec)


# ----------------------------------------------------------------------
# host→device placement (checkpoint restore / elastic reshard)
# ----------------------------------------------------------------------

def place_host(arr: np.ndarray, sharding) -> Any:
    """Place one host array against a target sharding, shard-by-shard:
    ``jax.make_array_from_callback`` hands each device ONLY its own
    slice, so restoring a sharded leaf never materializes a full
    per-device replica (the memory-peaky part of the old whole-array
    ``device_put`` path). This is the planner's host→device step — the
    route the elastic re-plan's reshard-restored-state takes
    (``resilience/elastic.py`` → ``runtime/checkpoint.py`` → here).
    ``FF_NAIVE_RESHARD=1`` restores the plain ``device_put``."""
    import jax
    nbytes = float(arr.size * arr.itemsize)
    if sharding is None:
        return jax.device_put(arr)
    if getattr(sharding, "is_fully_replicated", False):
        # no per-shard slicing to win: every device needs the whole
        # array either way, and device_put broadcasts one host copy
        if not naive_reshard():
            with STATS.lock:
                STATS.host_placements += 1
        return jax.device_put(arr, sharding)
    if naive_reshard():
        STATS.record("naive", nbytes)
        return jax.device_put(arr, sharding)
    try:
        out = jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])
    except Exception:  # noqa: BLE001 — odd shardings: fall back
        STATS.record("naive", nbytes)
        return jax.device_put(arr, sharding)
    with STATS.lock:
        STATS.host_placements += 1
    STATS.record("searched", nbytes)
    return out
