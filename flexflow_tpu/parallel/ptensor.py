"""ParallelTensor shape machinery.

Reference parity: ``include/flexflow/parallel_tensor.h:36-70`` —
``ParallelDim {size, degree, parallel_idx, is_replica_dim}`` and
``ParallelTensorShape``. Here a dim's ``degree`` is realized as the product
of named mesh axes assigned to that dim; replica dims become replication
over mesh axes (the unnamed remainder of the mesh in GSPMD terms).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..ffconst import DataType


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    size: int                       # logical (global) size of this dim
    degree: int = 1                 # #shards along this dim
    mesh_axes: Tuple[str, ...] = () # mesh axes realizing the degree
    is_replica_dim: bool = False

    def __post_init__(self):
        if self.mesh_axes:
            # degree must match the product of its mesh axes at mesh-bind time
            pass

    @property
    def shard_size(self) -> int:
        if self.size % max(self.degree, 1) != 0:
            raise ValueError(f"size {self.size} not divisible by "
                             f"degree {self.degree}")
        return self.size // max(self.degree, 1)


@dataclasses.dataclass(frozen=True)
class ParallelTensorShape:
    dims: Tuple[ParallelDim, ...]
    dtype: DataType = DataType.DT_FLOAT

    @property
    def num_dims(self) -> int:
        return len(self.dims)

    def global_shape(self) -> Tuple[int, ...]:
        return tuple(d.size for d in self.dims if not d.is_replica_dim)

    def shard_shape(self) -> Tuple[int, ...]:
        return tuple(d.shard_size for d in self.dims if not d.is_replica_dim)

    def total_degree(self) -> int:
        p = 1
        for d in self.dims:
            p *= d.degree
        return p

    def partition_spec(self):
        """→ jax.sharding.PartitionSpec over non-replica dims."""
        from jax.sharding import PartitionSpec as P
        entries = []
        for d in self.dims:
            if d.is_replica_dim:
                continue
            if not d.mesh_axes:
                entries.append(None)
            elif len(d.mesh_axes) == 1:
                entries.append(d.mesh_axes[0])
            else:
                entries.append(tuple(d.mesh_axes))
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    @classmethod
    def from_shape(cls, shape: Sequence[int], dtype: DataType = DataType.DT_FLOAT,
                   degrees: Optional[Sequence[int]] = None,
                   axes: Optional[Sequence[Tuple[str, ...]]] = None
                   ) -> "ParallelTensorShape":
        n = len(shape)
        degrees = list(degrees or [1] * n)
        axes = list(axes or [()] * n)
        return cls(tuple(ParallelDim(int(s), int(dg), tuple(ax))
                         for s, dg, ax in zip(shape, degrees, axes)), dtype)
