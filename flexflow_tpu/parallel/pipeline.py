"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference only reserves an enum/task ids for pipelining
(``OP_PIPELINE``, ``ffconst.h:159``; ``PIPELINE_*_TASK_ID``,
``model.h:190-192``) — no implementation exists (SURVEY.md §2.6). This
module supplies the real thing, TPU-style: stages are a mesh axis ("pp"),
stage parameters are stacked on a leading stage dim sharded over that axis,
and the schedule is a ``lax.scan`` whose per-step activation hand-off is a
``ppermute`` to the next stage — XLA lowers it to neighbor collective-
permutes over ICI. Reverse-mode AD through the scan + ppermute gives the
backward pipeline for free (cotangents flow stage S-1 → 0 through the
transposed permutes), so one ``jax.grad`` of the pipelined loss is a full
1F1B-equivalent-work backward schedule.

Constraints (the standard SPMD-pipeline shape): all stages run the same
``stage_fn`` with shape-preserving activations (e.g. transformer blocks);
embedding/head run outside the pipelined region.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _squeeze_stage(params):
    """Drop the local (length-1) leading stage dim of each leaf."""
    return jax.tree.map(lambda x: x[0], params)


def gpipe(stage_fn: Callable[..., Any], axis_name: str,
          n_microbatches: int, with_step_arg: bool = False):
    """Build the pipelined apply for use INSIDE shard_map over `axis_name`.

    stage_fn(stage_params, x) -> y with y.shape == x.shape.
    With ``with_step_arg``, stage_fn(stage_params, x, t) also receives the
    schedule step t (traced int32) — used e.g. to derive per-microbatch
    dropout rng inside a pipelined region.

    Returned fn(stacked_params_local, xs) where:
      - stacked_params_local: pytree whose leaves have local shape
        (1, ...) — this stage's slice of the (S, ...) stacked params;
      - xs: (M, mb, ...) microbatched input (replicated across stages);
    returns (M, mb, ...) outputs of the final stage (replicated).

    Schedule: T = M + S - 1 steps; at step t stage s computes microbatch
    t - s (bubble steps compute masked garbage that receives no gradient).
    """

    def apply(stacked_params_local, xs):
        S = lax.psum(1, axis_name)
        stage = lax.axis_index(axis_name)
        M = n_microbatches
        params = _squeeze_stage(stacked_params_local)
        # neighbor hand-off, no wraparound: stage s -> s+1
        perm = [(i, i + 1) for i in range(S - 1)]

        outputs0 = jnp.zeros_like(xs)
        state0 = jnp.zeros_like(xs[0])

        def body(carry, t):
            state, outputs = carry
            # stage 0 pulls microbatch t from the local queue; later stages
            # consume the activation handed off by the previous stage
            mb_t = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(stage == 0, mb_t, state)
            y = stage_fn(params, x_in, t) if with_step_arg \
                else stage_fn(params, x_in)
            # final stage owns microbatch t-(S-1) at step t
            out_idx = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1,
                                    jnp.logical_and(out_idx >= 0,
                                                    out_idx < M))
            write_idx = jnp.clip(out_idx, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outputs, write_idx, 0,
                                           keepdims=False)
            upd = jnp.where(valid, y, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd,
                                                      write_idx, 0)
            state = lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(body, (state0, outputs0),
                                   jnp.arange(M + S - 1))
        # broadcast final-stage outputs to every stage (masked psum)
        outputs = lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return apply


class PipelinedBlocks:
    """High-level dp×pp runner for a stack of identical blocks.

    Wraps ``n_stages`` groups of blocks: stage parameters are stacked on a
    leading dim and placed with ``NamedSharding(P('pp', ...))``; input
    batches are split into microbatches; the pipelined apply runs under
    ``shard_map`` over a (dp, pp) mesh and is differentiable end-to-end.
    """

    def __init__(self, mesh: Mesh, stage_fn, n_stages: int,
                 n_microbatches: int, dp_axis: str = "dp",
                 pp_axis: str = "pp"):
        assert pp_axis in mesh.axis_names, (pp_axis, mesh.axis_names)
        pp_size = mesh.shape[pp_axis]
        assert n_stages == pp_size, \
            (f"n_stages ({n_stages}) must equal the '{pp_axis}' axis size "
             f"({pp_size}): one stage per pipeline rank")
        self.mesh = mesh
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.dp_axis = dp_axis
        self.pp_axis = pp_axis

    def shard_params(self, stacked_params):
        """Place (S, ...)-stacked params: stage dim over the pp axis."""
        def put(x):
            spec = P(self.pp_axis, *([None] * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(self.mesh, spec))
        return jax.tree.map(put, stacked_params)

    def microbatch(self, x):
        """(B, ...) -> (M, B/M, ...)"""
        M = self.n_microbatches
        assert x.shape[0] % M == 0, (x.shape, M)
        return x.reshape((M, x.shape[0] // M) + x.shape[1:])

    def apply(self, stacked_params, x):
        """Differentiable pipelined forward of the block stack.
        x: (B, ...) full batch (dp-sharded on the batch dim outside)."""
        xs = self.microbatch(x)
        engine = gpipe(self.stage_fn, self.pp_axis, self.n_microbatches)
        in_param_spec = jax.tree.map(
            lambda v: P(self.pp_axis, *([None] * (v.ndim - 1))),
            stacked_params)
        dp = self.dp_axis if self.dp_axis in self.mesh.axis_names else None
        xs_spec = P(None, dp, *([None] * (xs.ndim - 2)))

        fn = jax.shard_map(
            engine, mesh=self.mesh,
            in_specs=(in_param_spec, xs_spec),
            out_specs=xs_spec,
            check_vma=False)
        ys = fn(stacked_params, xs)
        return ys.reshape((-1,) + ys.shape[2:])


def stack_stage_params(per_stage_params: Sequence[Any]):
    """[stage0_params, stage1_params, ...] -> stacked pytree with leading
    stage dim (the layout ``PipelinedBlocks`` shards over pp)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
