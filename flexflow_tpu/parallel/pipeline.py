"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference only reserves an enum/task ids for pipelining
(``OP_PIPELINE``, ``ffconst.h:159``; ``PIPELINE_*_TASK_ID``,
``model.h:190-192``) — no implementation exists (SURVEY.md §2.6). This
module supplies the real thing, TPU-style: stages are a mesh axis ("pp"),
stage parameters are stacked on a leading stage dim sharded over that axis,
and the schedule is a ``lax.scan`` whose per-step activation hand-off is a
``ppermute`` to the next stage — XLA lowers it to neighbor collective-
permutes over ICI. Reverse-mode AD through the scan + ppermute gives the
backward pipeline for free (cotangents flow stage S-1 → 0 through the
transposed permutes), so one ``jax.grad`` of the pipelined loss is a full
1F1B-equivalent-work backward schedule.

Constraints (the standard SPMD-pipeline shape): all stages run the same
``stage_fn`` with shape-preserving activations (e.g. transformer blocks);
embedding/head run outside the pipelined region.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map


def _squeeze_stage(params):
    """Drop the local (length-1) leading stage dim of each leaf."""
    return jax.tree.map(lambda x: x[0], params)


def gpipe(stage_fn: Callable[..., Any], axis_name: str,
          n_microbatches: int, with_step_arg: bool = False,
          n_chunks: int = 1):
    """Build the pipelined apply for use INSIDE shard_map over `axis_name`.

    stage_fn(stage_params, x) -> y with y.shape == x.shape.
    With ``with_step_arg``, stage_fn(stage_params, x, t) also receives the
    schedule step t (traced int32) — used e.g. to derive per-microbatch
    dropout rng inside a pipelined region.

    Returned fn(stacked_params_local, xs) where:
      - stacked_params_local: pytree whose leaves have local shape
        (1, ...) — this stage's slice of the (S, ...) stacked params —
        or (v, 1, ...) with ``n_chunks = v > 1`` (see below);
      - xs: (M, mb, ...) microbatched input (replicated across stages);
    returns (M, mb, ...) outputs of the final stage (replicated).

    Schedule, ``n_chunks == 1`` (GPipe): T = M + S - 1 steps; at step t
    stage s computes microbatch t - s (bubble steps compute masked
    garbage that receives no gradient).

    Schedule, ``n_chunks = v > 1`` (interleaved / circular, the
    Megatron-interleaved bubble reduction): the block stack is split into
    v*S chunks; device s owns chunks {s, S+s, ..., (v-1)S+s} and the
    activation ring wraps S-1 -> 0, so each microbatch circles the ring v
    times. T = M*v + S - 1 steps and the bubble fraction drops from
    (S-1)/M to (S-1)/(M*v). stage_fn receives ONE chunk's params per
    step. Requires M % S == 0 (round-robin microbatch rotation).
    """
    v = n_chunks

    def apply(stacked_params_local, xs):
        S = lax.psum(1, axis_name)
        stage = lax.axis_index(axis_name)
        M = n_microbatches
        if v == 1:
            params = _squeeze_stage(stacked_params_local)
            # neighbor hand-off, no wraparound: stage s -> s+1
            perm = [(i, i + 1) for i in range(S - 1)]
        else:
            # local leaves are (v, 1, ...): drop the sharded stage dim
            params = jax.tree.map(lambda x: x[:, 0], stacked_params_local)
            perm = [(i, (i + 1) % S) for i in range(S)]  # ring

        outputs0 = jnp.zeros_like(xs)
        state0 = jnp.zeros_like(xs[0])

        def body(carry, t):
            state, outputs = carry
            # local clock: how many chunk-computations this device has
            # started. chunk slot k and microbatch m follow the circular
            # round-robin (v == 1 reduces to m = u, k = 0).
            u = jnp.clip(t - stage, 0, M * v - 1)
            k = (u // S) % v
            m = jnp.clip((u % S) + S * (u // (S * v)), 0, M - 1)
            if v == 1:
                chunk_params = params
            else:
                chunk_params = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, k, 0,
                                                       keepdims=False),
                    params)
            # stage 0 pulls a fresh microbatch on its first chunk; all
            # other (stage, chunk) slots consume the handed-off activation
            mb_t = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
            x_in = jnp.where(jnp.logical_and(stage == 0, k == 0),
                             mb_t, state)
            y = stage_fn(chunk_params, x_in, t) if with_step_arg \
                else stage_fn(chunk_params, x_in)
            # the last chunk of the last stage finishes microbatch m
            out_idx = t - stage
            valid = jnp.logical_and(
                jnp.logical_and(stage == S - 1, k == v - 1),
                jnp.logical_and(out_idx >= 0, out_idx < M * v))
            cur = lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
            upd = jnp.where(valid, y, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd, m, 0)
            state = lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(body, (state0, outputs0),
                                   jnp.arange(M * v + S - 1))
        # broadcast final-stage outputs to every stage (masked psum)
        outputs = lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return apply


class PipelinedBlocks:
    """High-level dp×pp runner for a stack of identical blocks.

    Wraps ``n_stages`` groups of blocks: stage parameters are stacked on a
    leading dim and placed with ``NamedSharding(P('pp', ...))``; input
    batches are split into microbatches; the pipelined apply runs under
    ``shard_map`` over a (dp, pp) mesh and is differentiable end-to-end.
    """

    def __init__(self, mesh: Mesh, stage_fn, n_stages: int,
                 n_microbatches: int, dp_axis: str = "dp",
                 pp_axis: str = "pp", n_chunks: int = 1):
        if pp_axis not in mesh.axis_names:
            raise ValueError(f"pipeline axis {pp_axis!r} is not a mesh "
                             f"axis ({mesh.axis_names})")
        pp_size = mesh.shape[pp_axis]
        if n_stages != pp_size:
            raise ValueError(
                f"n_stages ({n_stages}) must equal the '{pp_axis}' "
                f"axis size ({pp_size}): one stage per pipeline rank")
        if n_chunks > 1 and n_microbatches % n_stages != 0:
            raise ValueError(
                f"interleaved schedule needs M % S == 0, got "
                f"M={n_microbatches} S={n_stages}")
        self.mesh = mesh
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.n_chunks = n_chunks
        self.dp_axis = dp_axis
        self.pp_axis = pp_axis

    def _pp_lead(self):
        return (self.pp_axis,) if self.n_chunks == 1 \
            else (None, self.pp_axis)

    def shard_params(self, stacked_params):
        """Place stacked params: (S, ...) with the stage dim over the pp
        axis, or (v, S, ...) for the interleaved schedule ([k, s] is
        global chunk s + k*S, see ``gpipe(n_chunks=v)``)."""
        lead = self._pp_lead()

        def put(x):
            spec = P(*lead, *([None] * (x.ndim - len(lead))))
            return jax.device_put(x, NamedSharding(self.mesh, spec))
        return jax.tree.map(put, stacked_params)

    def microbatch(self, x):
        """(B, ...) -> (M, B/M, ...)"""
        M = self.n_microbatches
        if x.shape[0] % M != 0:
            raise ValueError(f"batch {x.shape} not divisible into {M} "
                             f"microbatches")
        return x.reshape((M, x.shape[0] // M) + x.shape[1:])

    def apply(self, stacked_params, x):
        """Differentiable pipelined forward of the block stack.
        x: (B, ...) full batch (dp-sharded on the batch dim outside)."""
        xs = self.microbatch(x)
        engine = gpipe(self.stage_fn, self.pp_axis, self.n_microbatches,
                       n_chunks=self.n_chunks)
        lead = self._pp_lead()
        in_param_spec = jax.tree.map(
            lambda v: P(*lead, *([None] * (v.ndim - len(lead)))),
            stacked_params)
        dp = self.dp_axis if self.dp_axis in self.mesh.axis_names else None
        xs_spec = P(None, dp, *([None] * (xs.ndim - 2)))

        fn = shard_map(
            engine, mesh=self.mesh,
            in_specs=(in_param_spec, xs_spec),
            out_specs=xs_spec,
            check_vma=False)
        ys = fn(stacked_params, xs)
        return ys.reshape((-1,) + ys.shape[2:])


def stack_stage_params(per_stage_params: Sequence[Any]):
    """[stage0_params, stage1_params, ...] -> stacked pytree with leading
    stage dim (the layout ``PipelinedBlocks`` shards over pp)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def gpipe_ragged(block_fn: Callable[..., Any], axis_name: str,
                 n_microbatches: int, counts: Sequence[int],
                 prologue_fn: Optional[Callable[..., Any]] = None,
                 epilogue_fn: Optional[Callable[..., Any]] = None):
    """Ragged GPipe: per-stage block counts may differ, and stage 0 /
    stage S-1 may run extra non-block programs (embedding prologue /
    LM-head epilogue) — lifting the uniform-repeated-block restriction
    of ``gpipe`` (the reference never implemented pipelining at all;
    ``ffconst.h:159`` reserves OP_PIPELINE).

    - block_fn(block_params, x, t) -> y, shape-preserving; one template
      block. Stage s applies its ``counts[s]`` blocks per step; stacked
      params are padded to ``cmax = max(counts)`` and masked slots pass
      x through unchanged (SPMD: every scan step costs cmax blocks
      anyway — the win of raggedness is absorbing blocks/prologue/
      epilogue that would otherwise run REPLICATED outside the region).
    - prologue_fn(pro_params, raw_mb, t) -> x: stage 0 turns the raw
      per-microbatch input (e.g. token ids) into the entry activation.
      None = raw_xs already are the entry activations.
    - epilogue_fn(epi_params, y, t) -> out: stage S-1 maps the exit
      activation to the final output (shape may differ from x, e.g.
      vocab logits). None = identity.

    Returned apply(stacked_local, pro_params, epi_params, raw_xs,
    hidden_example, out_example):
      - stacked_local: (1, cmax, ...) leaves — this stage's padded
        block params;
      - raw_xs: pytree of (M, mb, ...) microbatched raw inputs
        (replicated across stages);
      - hidden_example/out_example: shape/dtype exemplars (one
        microbatch) for the ring state and the output buffer.
    Returns (M, mb, ...) outputs of the final stage (replicated).
    """
    M = n_microbatches
    counts = list(counts)
    cmax = max(counts)

    def apply(stacked_local, pro_params, epi_params, raw_xs,
              hidden_example, out_example):
        S = lax.psum(1, axis_name)
        stage = lax.axis_index(axis_name)
        my_count = jnp.asarray(counts, jnp.int32)[stage]
        block_params = jax.tree.map(lambda x: x[0], stacked_local)
        perm = [(i, i + 1) for i in range(S - 1)]

        outputs0 = jnp.zeros((M,) + out_example.shape, out_example.dtype)
        state0 = jnp.zeros(hidden_example.shape, hidden_example.dtype)

        def body(carry, t):
            state, outputs = carry
            m_in = jnp.clip(t, 0, M - 1)
            raw_mb = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, m_in, 0,
                                                   keepdims=False),
                raw_xs)

            def enter_stage0(_):
                if prologue_fn is None:
                    return raw_mb
                return prologue_fn(pro_params, raw_mb, t)

            x_in = lax.cond(stage == 0, enter_stage0,
                            lambda _: state, operand=None)

            def blk(x, scan_in):
                p_k, k = scan_in
                y = block_fn(p_k, x, t)
                return jnp.where(k < my_count, y, x), None

            y, _ = lax.scan(blk, x_in,
                            (block_params,
                             jnp.arange(cmax, dtype=jnp.int32)))

            # the last stage finishes microbatch m = t - (S-1)
            m_out = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1,
                                    jnp.logical_and(m_out >= 0,
                                                    m_out < M))

            def run_epilogue(_):
                out = epilogue_fn(epi_params, y, t) \
                    if epilogue_fn is not None else y
                return out

            out = lax.cond(valid, run_epilogue,
                           lambda _: jnp.zeros(out_example.shape,
                                               out_example.dtype),
                           operand=None)
            mo = jnp.clip(m_out, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outputs, mo, 0, keepdims=False)
            upd = jnp.where(valid, out, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd, mo, 0)
            state = lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(body, (state0, outputs0),
                                   jnp.arange(M + S - 1))
        outputs = lax.psum(
            jnp.where(stage == S - 1, outputs,
                      jnp.zeros_like(outputs)), axis_name)
        return outputs

    return apply
