"""Physical interconnect topology: ICI torus + DCN, with routing.

TPU-native analog of the reference's network/machine-model layer
(``src/runtime/network.cc``, ``include/flexflow/simulator.h:381-499``:
``NetworkedMachineModel``, ``ShortestPathNetworkRoutingStrategy``,
topology generators; file loading in ``src/runtime/machine_model.cc`` via
``--machine-model-file``, format ``machine_config_example``). The
reference models sockets/PCIe/NVLink/NIC graphs with shortest-path
routing; a TPU pod is regular, so the model is exact rather than
generated: chips sit on an N-D torus (e.g. 4x8 for v5e-32) joined by
per-dimension ICI links, hosts own contiguous blocks of chips, and
slices are joined by per-host DCN NICs. Routing is dimension-ordered
with shortest wrap direction — the ICI fabric's actual scheme.

``TorusTopology.ring_links``/``route`` let the task-graph simulator
(``search/tasksim.py``) charge traffic to *physical links*, so it can
tell a 4x8 torus from a flat 32-ring: e.g. concurrent row- and
column-rings do not contend on the torus but alias onto the same links
in a flat model.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

# path-length comparison tolerance; safe because Dijkstra weights are
# normalized to max_bw/bw (dimensionless, >= 1 per hop)
_EPS = 1e-9

Link = Tuple[int, int, int]  # (device, dim, direction ±1) — outgoing port

# flat_ring_links cache bound: device tuples repeat thousands of times
# per search, but a long-lived topology (MachineSpec memo) must not
# accumulate routes without limit across searches
_RING_ROUTE_CACHE_CAP = 4096


def flat_ring_links(topo, devices: Tuple[int, ...]):
    """Flattened ring-collective routes over ``devices``, cached on the
    topology: ``(offsets, links, factors-or-None)`` where ``links`` is
    the concatenated per-participant hop list and ``offsets[i]`` its
    start. Only builder-independent data (raw link tuples, bandwidth
    factors) is cached here — processor-id mapping is per consumer
    (search/tasksim.py), so one shared topology can never serve another
    builder's ids. The cache is bounded at ``_RING_ROUTE_CACHE_CAP``
    entries (cleared wholesale when full; hot tuples repopulate).

    A module function rather than a method so any duck-typed topology
    (``MachineSpec.topology_override`` accepts arbitrary objects with
    ``ring_links``/``link_index``) gets the same caching."""
    cache = topo.__dict__.get("_ring_route_cache")
    if cache is None:
        cache = {}
        topo.__dict__["_ring_route_cache"] = cache
    hit = cache.get(devices)
    if hit is None:
        routes = topo.ring_links(list(devices))
        factor = getattr(topo, "link_factor", None)
        off = [0]
        links: List[Link] = []
        fac: Optional[List[float]] = [] if factor else None
        for hops in routes:
            for link in hops:
                links.append(link)
                if fac is not None:
                    fac.append(float(factor(link)))
            off.append(len(links))
        if len(cache) >= _RING_ROUTE_CACHE_CAP:
            cache.clear()
        hit = (tuple(off), tuple(links),
               tuple(fac) if fac is not None else None)
        cache[devices] = hit
    return hit


@dataclasses.dataclass
class TorusTopology:
    """N-D torus of devices; wrap links exist on dims of size >= 3
    (TPU slices expose wraparound only for full rings)."""
    shape: Tuple[int, ...]

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def coord(self, dev: int) -> Tuple[int, ...]:
        c = []
        for s in reversed(self.shape):
            c.append(dev % s)
            dev //= s
        return tuple(reversed(c))

    def device(self, coord: Sequence[int]) -> int:
        d = 0
        for x, s in zip(coord, self.shape):
            d = d * s + (x % s)
        return d

    def _wrap(self, dim: int) -> bool:
        return self.shape[dim] >= 3

    def hop_distance(self, a: int, b: int) -> int:
        """Total hops of the dimension-ordered route."""
        ca, cb = self.coord(a), self.coord(b)
        hops = 0
        for k, s in enumerate(self.shape):
            d = abs(ca[k] - cb[k])
            hops += min(d, s - d) if self._wrap(k) else d
        return hops

    def route(self, src: int, dst: int) -> List[Link]:
        """Dimension-ordered shortest-wrap route as outgoing links.

        Analog of ``ShortestPathNetworkRoutingStrategy::get_routes``
        (``simulator.h:399``) specialized to the torus, where
        dimension-ordered IS shortest-path."""
        links: List[Link] = []
        cur = list(self.coord(src))
        tgt = self.coord(dst)
        for k, s in enumerate(self.shape):
            while cur[k] != tgt[k]:
                fwd = (tgt[k] - cur[k]) % s
                back = (cur[k] - tgt[k]) % s
                step = 1 if (fwd <= back or not self._wrap(k)) else -1
                if not self._wrap(k) and tgt[k] < cur[k]:
                    step = -1
                links.append((self.device(cur), k, step))
                cur[k] = (cur[k] + step) % s
        return links

    def ring_links(self, devices: Sequence[int]) -> List[List[Link]]:
        """Per-step physical links of a ring collective over ``devices``
        (each participant sends to its successor every step)."""
        n = len(devices)
        return [self.route(devices[i], devices[(i + 1) % n])
                for i in range(n)]

    def link_index(self) -> Dict[Link, int]:
        """Dense numbering of every outgoing port (device, dim, dir)."""
        idx: Dict[Link, int] = {}
        for d in range(self.num_devices):
            for k in range(len(self.shape)):
                for s in (1, -1):
                    idx[(d, k, s)] = len(idx)
        return idx


class GraphTopology:
    """Arbitrary weighted interconnect: a directed connection matrix over
    devices, with weighted shortest-path routing.

    Analog of the reference's ``NetworkedMachineModel`` + connection-
    matrix generators + ``WeightedShortestPathRoutingStrategy``
    (``src/runtime/network.cc:1-586``, ``include/flexflow/
    simulator.h:381-515``). Where the torus model is exact for one
    healthy slice, this expresses what it cannot: big-switch fabrics,
    degraded links, heterogeneous multi-slice pods (ICI inside each
    slice, DCN between them).

    ``conn[(i, j)]`` is the link bandwidth in bytes/s (absent = no
    link). The task simulator charges each link on a route a duration
    scaled by ``link_factor`` — the ratio of the fastest link's
    bandwidth to this link's — so a DCN hop or a degraded link
    serializes traffic proportionally longer. The ``Link`` key is
    ``(src, 0, dst)``: same 3-tuple arity as the torus's
    ``(device, dim, dir)`` ports, so ``link_index``/``ring_links``
    consumers work unchanged.
    """

    def __init__(self, num_devices: int,
                 conn: Dict[Tuple[int, int], float]):
        self.num_devices = num_devices
        self.conn = dict(conn)
        self.max_bw = max(conn.values()) if conn else 1.0
        self._routes_cache: Dict[Tuple[int, int, int], List[List[Link]]] = {}
        self._dist_cache: Dict[int, Dict[int, float]] = {}
        self._rdist_cache: Dict[int, Dict[int, float]] = {}
        # Dijkstra weight: dimensionless time factor max_bw/bw (>= 1 per
        # hop, the same normalization as link_factor). Raw per-byte
        # weights (1/bw ~ 1e-11 for real ICI bandwidths) would sit at
        # the same scale as any absolute epsilon and break the
        # shortest-path-DAG edge test on fast fabrics.
        self._adj: Dict[int, List[Tuple[int, float]]] = {}
        self._radj: Dict[int, List[Tuple[int, float]]] = {}
        for (i, j), bw in conn.items():
            w = self.max_bw / max(bw, 1e-30)
            self._adj.setdefault(i, []).append((j, w))
            self._radj.setdefault(j, []).append((i, w))

    # ---- constructors (reference network.cc topology generators) ----
    @classmethod
    def from_torus(cls, shape: Sequence[int],
                   bw: float = 1.0) -> "GraphTopology":
        t = TorusTopology(tuple(shape))
        conn: Dict[Tuple[int, int], float] = {}
        for d in range(t.num_devices):
            c = t.coord(d)
            for k, s in enumerate(shape):
                for step in ((1, -1) if s >= 3 else (1,) if c[k] + 1 < s
                             else ()):
                    nc = list(c)
                    nc[k] = (nc[k] + step) % s
                    conn[(d, t.device(nc))] = bw
                    conn[(t.device(nc), d)] = bw
        return cls(t.num_devices, conn)

    @classmethod
    def big_switch(cls, n: int, bw: float = 1.0) -> "GraphTopology":
        """Full crossbar: every pair directly connected (the reference's
        ``FlatDegConstraintNetworkTopologyGenerator`` limit case)."""
        conn = {(i, j): bw for i in range(n) for j in range(n) if i != j}
        return cls(n, conn)

    @classmethod
    def degraded(cls, base: "GraphTopology",
                 slow_links: Sequence[Tuple[int, int]],
                 factor: float) -> "GraphTopology":
        """Copy of ``base`` with the listed (src, dst) links running at
        ``bw / factor`` (fault/brownout modeling)."""
        conn = dict(base.conn)
        for (i, j) in slow_links:
            if (i, j) in conn:
                conn[(i, j)] = conn[(i, j)] / factor
        return cls(base.num_devices, conn)

    @classmethod
    def multi_slice_torus(cls, shape: Sequence[int], n_slices: int,
                          ici_bw: float, dcn_bw: float,
                          hosts_per_slice: int = 1) -> "GraphTopology":
        """``n_slices`` tori joined by DCN: each slice exposes
        ``hosts_per_slice`` gateway devices (block-contiguous hosts'
        first chips) with all-to-all DCN links between slices — the
        fabric of a real multi-slice pod."""
        one = cls.from_torus(shape, ici_bw)
        per = one.num_devices
        conn: Dict[Tuple[int, int], float] = {}
        for s in range(n_slices):
            off = s * per
            for (i, j), bw in one.conn.items():
                conn[(off + i, off + j)] = bw
        chips_per_host = max(1, per // max(1, hosts_per_slice))
        gateways = [list(range(s * per, (s + 1) * per, chips_per_host))
                    for s in range(n_slices)]
        for a in range(n_slices):
            for b in range(n_slices):
                if a == b:
                    continue
                for ga, gb in zip(gateways[a], gateways[b]):
                    conn[(ga, gb)] = dcn_bw
        return cls(per * n_slices, conn)

    # ---- routing (WeightedShortestPathRoutingStrategy analog) ----
    def routes(self, src: int, dst: int, k: int = 4) -> List[List[Link]]:
        """Up to ``k`` equal-cost weighted-shortest paths src -> dst.

        All shortest paths live on the Dijkstra shortest-path DAG
        (edges u->v with dist[v] == dist[u] + w); a depth-first walk in
        sorted-neighbor order enumerates them deterministically. The
        reference's WeightedShortestPathRoutingStrategy returns one
        path chosen by a random tie-break (network.cc:89 —
        ``unif(gen) < 0.5``), spreading flows across equal-cost paths
        statistically; here :meth:`route` hash-selects per (src, dst)
        flow, the deterministic form of the same ECMP spreading."""
        if src == dst:
            return [[]]
        hit = self._routes_cache.get((src, dst, k))
        if hit is not None:
            return hit
        dist = self._dist_from(src)
        if dst not in dist:
            raise ValueError(f"no route {src} -> {dst} in topology")
        rdist = self._dist_from(dst, rev=True)
        total = dist[dst]
        # relative tolerance: weights are dimensionless (max_bw/bw >= 1)
        # but long routes accumulate fp error proportional to length
        tol = _EPS * max(1.0, total)
        # one candidate per equal-cost FIRST HOP (sorted, deterministic):
        # distinct egress links by construction, so per-flow selection
        # genuinely spreads source traffic (a k-truncated DFS kept only
        # paths differing near dst — every candidate shared hop 1)
        inf = float("inf")
        firsts = [v for v, w in sorted(self._adj.get(src, ()))
                  if w + rdist.get(v, inf) <= total + tol]
        if not firsts:
            # fp-pathological fabric: fall back to the single best hop
            firsts = [min(self._adj.get(src, ()),
                          key=lambda vw: (vw[1] + rdist.get(vw[0], inf),
                                          vw[0]))[0]]
        paths: List[List[int]] = []
        for first in firsts[:max(1, k)]:
            # greedy descent on rdist: from any node on a shortest path
            # the neighbor minimizing (w + rdist) continues one, so the
            # walk reaches dst in <= num_devices hops; a step cap guards
            # degenerate fp cases (such a path is simply dropped)
            path = [src, first]
            u = first
            for _ in range(self.num_devices):
                if u == dst:
                    break
                u = min(self._adj.get(u, ()),
                        key=lambda vw: (vw[1] + rdist.get(vw[0], inf),
                                        vw[0]))[0]
                path.append(u)
            if path[-1] == dst:
                paths.append(path)
        if not paths:
            raise ValueError(f"no route {src} -> {dst} in topology")
        out = [[(p[i], 0, p[i + 1]) for i in range(len(p) - 1)]
               for p in paths]
        self._routes_cache[(src, dst, k)] = out
        return out

    def _dist_from(self, node: int, rev: bool = False) -> Dict[int, float]:
        """Cached full Dijkstra distance map from ``node`` (forward or
        reverse graph) — ring_links issues a route per device pair, so
        per-node caching turns 2P sweeps into at most 2V."""
        cache = self._rdist_cache if rev else self._dist_cache
        hit = cache.get(node)
        if hit is not None:
            return hit
        import heapq
        adj = self._radj if rev else self._adj
        dist = {node: 0.0}
        pq = [(0.0, node)]
        while pq:
            d, u = heapq.heappop(pq)
            if d > dist.get(u, float("inf")):
                continue
            for v, w in adj.get(u, ()):
                nd = d + w
                if nd < dist.get(v, float("inf")) - _EPS:
                    dist[v] = nd
                    heapq.heappush(pq, (nd, v))
        cache[node] = dist
        return dist

    def route(self, src: int, dst: int) -> List[Link]:
        """One weighted-shortest path; equal-cost alternatives are
        hash-selected per flow (deterministic ECMP — see
        :meth:`routes`)."""
        if src == dst:
            return []
        cands = self.routes(src, dst)   # cached per (src, dst, k)
        # deterministic per-flow spreading: distinct (src, dst) pairs
        # land on different equal-cost paths; repeated queries agree
        idx = (src * 2654435761 + dst * 40503) % len(cands)
        return cands[idx]

    def hop_distance(self, a: int, b: int) -> int:
        """Minimum hop count over the ENUMERATED equal-cost candidates
        (:meth:`routes`, one greedy path per equal-cost first hop,
        k <= 4): deterministic and independent of the per-flow hash
        (ADVICE r4). Not guaranteed to be the global minimum-hop
        equal-weight path — ties inside the greedy descent break by
        node id, which is fine for the latency estimates this feeds."""
        if a == b:
            return 0
        return min(len(p) for p in self.routes(a, b))

    def ring_links(self, devices: Sequence[int]) -> List[List[Link]]:
        n = len(devices)
        return [self.route(devices[i], devices[(i + 1) % n])
                for i in range(n)]

    def link_index(self) -> Dict[Link, int]:
        return {(i, 0, j): k
                for k, (i, j) in enumerate(sorted(self.conn.keys()))}

    def link_factor(self, link: Link) -> float:
        """Duration multiplier for traffic on this link relative to the
        fastest link in the fabric (DCN/degraded links serialize
        longer)."""
        bw = self.conn.get((link[0], link[2]))
        return self.max_bw / bw if bw else 1.0


# ----------------------------------------------------------------------
# machine description files (--machine-model-file)
# ----------------------------------------------------------------------

def _parse_ini(text: str) -> Dict[str, str]:
    """``key = value`` lines, ``#`` comments — the reference's
    ``machine_config_example`` format."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        m = re.match(r"([A-Za-z0-9_]+)\s*=\s*(.+)", line)
        if m:
            out[m.group(1)] = m.group(2).strip()
    return out


def load_machine_file(path: str):
    """Parse a machine description into a ``MachineSpec``.

    Two formats:
      - JSON (TPU-native): ``{"generation": "v5e", "ici_shape": [4, 8],
        "num_hosts": 4, "num_slices": 1, "dcn_bandwidth_gbps": 25, ...}``
      - reference-style INI (``machine_config_example``): ``num_nodes``,
        ``num_gpus_per_socket`` x ``num_sockets_per_node`` -> devices,
        ``nvlink_bandwidth`` -> ICI GB/s, ``nic_bandwidth`` -> DCN,
        latencies in ms.
    """
    from .machine import MachineSpec

    with open(path) as f:
        text = f.read()
    try:
        cfg = json.loads(text)
        is_json = True
    except json.JSONDecodeError:
        cfg = _parse_ini(text)
        is_json = False

    if is_json:
        spec = MachineSpec(
            num_devices=int(cfg.get("num_devices") or
                            _prod(cfg.get("ici_shape", [1])) *
                            int(cfg.get("num_slices", 1))),
            generation=cfg.get("generation", "v5e"),
            ici_shape=tuple(cfg["ici_shape"]) if "ici_shape" in cfg
            else None,
            num_slices=int(cfg.get("num_slices", 1)),
            dcn_bandwidth_gbps=float(cfg.get("dcn_bandwidth_gbps", 25.0)),
            ici_latency_us=float(cfg.get("ici_latency_us", 1.0)),
            dcn_latency_us=float(cfg.get("dcn_latency_us", 10.0)),
        )
        spec.num_hosts = int(cfg.get("num_hosts", spec.num_slices))
        if "ici_bandwidth_gbps" in cfg:
            spec.ici_bandwidth_override = \
                float(cfg["ici_bandwidth_gbps"]) * 1e9
        if "peak_tflops" in cfg:
            spec.peak_flops_override = float(cfg["peak_tflops"]) * 1e12
        if "topology" in cfg:
            spec.topology_override = topology_from_json(cfg["topology"],
                                                        spec)
        return spec

    # reference INI: nodes x sockets x gpus-per-socket accelerators;
    # nvlink ≙ intra-node fabric (ICI), nic ≙ inter-node (DCN)
    nodes = int(cfg.get("num_nodes", 1))
    sockets = int(cfg.get("num_sockets_per_node", 1))
    per_socket = int(cfg.get("num_gpus_per_socket", 1))
    per_node = sockets * per_socket
    spec = MachineSpec(
        num_devices=nodes * per_node,
        num_slices=nodes if nodes > 1 else 1,
        dcn_bandwidth_gbps=float(cfg.get("nic_bandwidth", 25.0)),
        # reference latencies are in ms
        ici_latency_us=float(cfg.get("nvlink_latency", 0.001)) * 1e3,
        dcn_latency_us=float(cfg.get("nic_latency", 0.01)) * 1e3,
    )
    spec.num_hosts = nodes
    spec.ici_shape = (per_node,)
    if "nvlink_bandwidth" in cfg:
        spec.ici_bandwidth_override = float(cfg["nvlink_bandwidth"]) * 1e9
    return spec


def topology_from_json(doc: Dict, spec) -> GraphTopology:
    """Build a ``GraphTopology`` from a machine-file ``topology`` block.

    Kinds (reference topology generators, ``network.cc``):
      - ``{"kind": "torus", "shape": [4, 8]}``
      - ``{"kind": "big_switch", "n": 32}``
      - ``{"kind": "multi_slice_torus", "shape": [4, 8], "n_slices": 2,
         "hosts_per_slice": 8}``
      - ``{"kind": "degraded", "base": {...}, "slow_links": [[0, 1]],
         "factor": 4}``
      - ``{"kind": "matrix", "n": 4,
         "links": [[src, dst, bandwidth_gbps], ...]}``
    """
    kind = doc.get("kind", "torus")
    ici = spec.ici_bandwidth
    if kind == "torus":
        return GraphTopology.from_torus(doc["shape"], ici)
    if kind == "big_switch":
        return GraphTopology.big_switch(int(doc["n"]), ici)
    if kind == "multi_slice_torus":
        return GraphTopology.multi_slice_torus(
            doc["shape"], int(doc["n_slices"]), ici_bw=ici,
            dcn_bw=spec.dcn_bandwidth,
            hosts_per_slice=int(doc.get("hosts_per_slice", 1)))
    if kind == "degraded":
        base = topology_from_json(doc["base"], spec)
        return GraphTopology.degraded(
            base, [tuple(l) for l in doc["slow_links"]],
            float(doc["factor"]))
    if kind == "matrix":
        conn = {(int(s), int(d)): float(bw) * 1e9
                for s, d, bw in doc["links"]}
        return GraphTopology(int(doc["n"]), conn)
    raise ValueError(f"unknown topology kind {kind!r}")


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= int(x)
    return n
