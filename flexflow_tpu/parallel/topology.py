"""Physical interconnect topology: ICI torus + DCN, with routing.

TPU-native analog of the reference's network/machine-model layer
(``src/runtime/network.cc``, ``include/flexflow/simulator.h:381-499``:
``NetworkedMachineModel``, ``ShortestPathNetworkRoutingStrategy``,
topology generators; file loading in ``src/runtime/machine_model.cc`` via
``--machine-model-file``, format ``machine_config_example``). The
reference models sockets/PCIe/NVLink/NIC graphs with shortest-path
routing; a TPU pod is regular, so the model is exact rather than
generated: chips sit on an N-D torus (e.g. 4x8 for v5e-32) joined by
per-dimension ICI links, hosts own contiguous blocks of chips, and
slices are joined by per-host DCN NICs. Routing is dimension-ordered
with shortest wrap direction — the ICI fabric's actual scheme.

``TorusTopology.ring_links``/``route`` let the task-graph simulator
(``search/tasksim.py``) charge traffic to *physical links*, so it can
tell a 4x8 torus from a flat 32-ring: e.g. concurrent row- and
column-rings do not contend on the torus but alias onto the same links
in a flat model.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

Link = Tuple[int, int, int]  # (device, dim, direction ±1) — outgoing port


@dataclasses.dataclass
class TorusTopology:
    """N-D torus of devices; wrap links exist on dims of size >= 3
    (TPU slices expose wraparound only for full rings)."""
    shape: Tuple[int, ...]

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def coord(self, dev: int) -> Tuple[int, ...]:
        c = []
        for s in reversed(self.shape):
            c.append(dev % s)
            dev //= s
        return tuple(reversed(c))

    def device(self, coord: Sequence[int]) -> int:
        d = 0
        for x, s in zip(coord, self.shape):
            d = d * s + (x % s)
        return d

    def _wrap(self, dim: int) -> bool:
        return self.shape[dim] >= 3

    def hop_distance(self, a: int, b: int) -> int:
        """Total hops of the dimension-ordered route."""
        ca, cb = self.coord(a), self.coord(b)
        hops = 0
        for k, s in enumerate(self.shape):
            d = abs(ca[k] - cb[k])
            hops += min(d, s - d) if self._wrap(k) else d
        return hops

    def route(self, src: int, dst: int) -> List[Link]:
        """Dimension-ordered shortest-wrap route as outgoing links.

        Analog of ``ShortestPathNetworkRoutingStrategy::get_routes``
        (``simulator.h:399``) specialized to the torus, where
        dimension-ordered IS shortest-path."""
        links: List[Link] = []
        cur = list(self.coord(src))
        tgt = self.coord(dst)
        for k, s in enumerate(self.shape):
            while cur[k] != tgt[k]:
                fwd = (tgt[k] - cur[k]) % s
                back = (cur[k] - tgt[k]) % s
                step = 1 if (fwd <= back or not self._wrap(k)) else -1
                if not self._wrap(k) and tgt[k] < cur[k]:
                    step = -1
                links.append((self.device(cur), k, step))
                cur[k] = (cur[k] + step) % s
        return links

    def ring_links(self, devices: Sequence[int]) -> List[List[Link]]:
        """Per-step physical links of a ring collective over ``devices``
        (each participant sends to its successor every step)."""
        n = len(devices)
        return [self.route(devices[i], devices[(i + 1) % n])
                for i in range(n)]

    def link_index(self) -> Dict[Link, int]:
        """Dense numbering of every outgoing port (device, dim, dir)."""
        idx: Dict[Link, int] = {}
        for d in range(self.num_devices):
            for k in range(len(self.shape)):
                for s in (1, -1):
                    idx[(d, k, s)] = len(idx)
        return idx


# ----------------------------------------------------------------------
# machine description files (--machine-model-file)
# ----------------------------------------------------------------------

def _parse_ini(text: str) -> Dict[str, str]:
    """``key = value`` lines, ``#`` comments — the reference's
    ``machine_config_example`` format."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        m = re.match(r"([A-Za-z0-9_]+)\s*=\s*(.+)", line)
        if m:
            out[m.group(1)] = m.group(2).strip()
    return out


def load_machine_file(path: str):
    """Parse a machine description into a ``MachineSpec``.

    Two formats:
      - JSON (TPU-native): ``{"generation": "v5e", "ici_shape": [4, 8],
        "num_hosts": 4, "num_slices": 1, "dcn_bandwidth_gbps": 25, ...}``
      - reference-style INI (``machine_config_example``): ``num_nodes``,
        ``num_gpus_per_socket`` x ``num_sockets_per_node`` -> devices,
        ``nvlink_bandwidth`` -> ICI GB/s, ``nic_bandwidth`` -> DCN,
        latencies in ms.
    """
    from .machine import MachineSpec

    with open(path) as f:
        text = f.read()
    try:
        cfg = json.loads(text)
        is_json = True
    except json.JSONDecodeError:
        cfg = _parse_ini(text)
        is_json = False

    if is_json:
        spec = MachineSpec(
            num_devices=int(cfg.get("num_devices") or
                            _prod(cfg.get("ici_shape", [1])) *
                            int(cfg.get("num_slices", 1))),
            generation=cfg.get("generation", "v5e"),
            ici_shape=tuple(cfg["ici_shape"]) if "ici_shape" in cfg
            else None,
            num_slices=int(cfg.get("num_slices", 1)),
            dcn_bandwidth_gbps=float(cfg.get("dcn_bandwidth_gbps", 25.0)),
            ici_latency_us=float(cfg.get("ici_latency_us", 1.0)),
            dcn_latency_us=float(cfg.get("dcn_latency_us", 10.0)),
        )
        spec.num_hosts = int(cfg.get("num_hosts", spec.num_slices))
        if "ici_bandwidth_gbps" in cfg:
            spec.ici_bandwidth_override = \
                float(cfg["ici_bandwidth_gbps"]) * 1e9
        if "peak_tflops" in cfg:
            spec.peak_flops_override = float(cfg["peak_tflops"]) * 1e12
        return spec

    # reference INI: nodes x sockets x gpus-per-socket accelerators;
    # nvlink ≙ intra-node fabric (ICI), nic ≙ inter-node (DCN)
    nodes = int(cfg.get("num_nodes", 1))
    sockets = int(cfg.get("num_sockets_per_node", 1))
    per_socket = int(cfg.get("num_gpus_per_socket", 1))
    per_node = sockets * per_socket
    spec = MachineSpec(
        num_devices=nodes * per_node,
        num_slices=nodes if nodes > 1 else 1,
        dcn_bandwidth_gbps=float(cfg.get("nic_bandwidth", 25.0)),
        # reference latencies are in ms
        ici_latency_us=float(cfg.get("nvlink_latency", 0.001)) * 1e3,
        dcn_latency_us=float(cfg.get("nic_latency", 0.01)) * 1e3,
    )
    spec.num_hosts = nodes
    spec.ici_shape = (per_node,)
    if "nvlink_bandwidth" in cfg:
        spec.ici_bandwidth_override = float(cfg["nvlink_bandwidth"]) * 1e9
    return spec


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= int(x)
    return n
