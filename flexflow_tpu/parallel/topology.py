"""Physical interconnect topology: ICI torus + DCN, with routing.

TPU-native analog of the reference's network/machine-model layer
(``src/runtime/network.cc``, ``include/flexflow/simulator.h:381-499``:
``NetworkedMachineModel``, ``ShortestPathNetworkRoutingStrategy``,
topology generators; file loading in ``src/runtime/machine_model.cc`` via
``--machine-model-file``, format ``machine_config_example``). The
reference models sockets/PCIe/NVLink/NIC graphs with shortest-path
routing; a TPU pod is regular, so the model is exact rather than
generated: chips sit on an N-D torus (e.g. 4x8 for v5e-32) joined by
per-dimension ICI links, hosts own contiguous blocks of chips, and
slices are joined by per-host DCN NICs. Routing is dimension-ordered
with shortest wrap direction — the ICI fabric's actual scheme.

``TorusTopology.ring_links``/``route`` let the task-graph simulator
(``search/tasksim.py``) charge traffic to *physical links*, so it can
tell a 4x8 torus from a flat 32-ring: e.g. concurrent row- and
column-rings do not contend on the torus but alias onto the same links
in a flat model.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

# path-length comparison tolerance; safe because Dijkstra weights are
# normalized to max_bw/bw (dimensionless, >= 1 per hop)
_EPS = 1e-9

Link = Tuple[int, int, int]  # (device, dim, direction ±1) — outgoing port

# flat_ring_links cache bound: device tuples repeat thousands of times
# per search, but a long-lived topology (MachineSpec memo) must not
# accumulate routes without limit across searches
_RING_ROUTE_CACHE_CAP = 4096

# shared Dijkstra cache bound: distance maps are keyed by the topology's
# LINK TABLE fingerprint (+ node, direction), so rebuilt-but-identical
# fabrics (MachineSpec memo invalidation, per-test topologies) reuse one
# another's sweeps while a degraded() copy — different link table,
# different fingerprint — can never alias a healthy fabric's distances
_DIST_CACHE_CAP = 4096
_SHARED_DIST_CACHE: Dict[Tuple, Dict[int, float]] = {}
_ROUTES_CACHE_CAP = 8192


# ----------------------------------------------------------------------
# hardware tiers (arXiv 2110.10548: hierarchical placement + reduction)
# ----------------------------------------------------------------------

#: canonical tier names, innermost (fastest) first
TIER_ORDER = ("ici", "host", "dcn")
#: tier name -> rank (innermost = 0); THE ordering map every consumer
#: shares (placement paths, axis allocation, calibration tier keys)
TIER_RANK = {t: i for i, t in enumerate(TIER_ORDER)}


@dataclasses.dataclass(frozen=True)
class Tier:
    """One bandwidth/latency level of the machine hierarchy.

    ``span`` is the number of devices reachable without leaving the
    tier's domain (chips per host for ``ici``, devices per slice for
    ``host``, the whole machine for ``dcn``) — the quantity placement
    search compares collective degrees against."""
    name: str            # "ici" | "host" | "dcn"
    bandwidth: float     # bytes/s per link, one direction
    latency_s: float     # per-hop latency in seconds
    span: int            # devices reachable inside one tier domain

    def rank(self) -> int:
        return TIER_ORDER.index(self.name) \
            if self.name in TIER_ORDER else len(TIER_ORDER)


class TierGraph:
    """First-class description of the machine's bandwidth tiers —
    ICI-within-host / ICI-or-NIC-across-hosts / DCN-across-slices —
    queryable by the placement search, cost model, plan verifier and
    executor lowering (arXiv 2110.10548 models exactly this hierarchy).

    Tiers are ordered innermost (fastest, smallest span) first. A
    machine may collapse to a single tier (one host, one slice): every
    consumer must then degenerate to flat-mesh behavior.
    """

    def __init__(self, tiers: Sequence[Tier]):
        if not tiers:
            raise ValueError("TierGraph needs at least one tier")
        self.tiers: Tuple[Tier, ...] = tuple(
            sorted(tiers, key=lambda t: (t.span, t.rank())))
        names = [t.name for t in self.tiers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tier names: {names}")

    def __repr__(self) -> str:
        return "TierGraph(" + ", ".join(
            f"{t.name}: span={t.span} bw={t.bandwidth / 1e9:.3g}GB/s "
            f"lat={t.latency_s * 1e6:.3g}us" for t in self.tiers) + ")"

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(t.name for t in self.tiers)

    @property
    def multi_tier(self) -> bool:
        return len(self.tiers) > 1

    def tier(self, name: str) -> Tier:
        for t in self.tiers:
            if t.name == name:
                return t
        raise ValueError(f"unknown tier {name!r} "
                         f"(tiers: {list(self.names)})")

    def innermost(self) -> Tier:
        return self.tiers[0]

    def outermost(self) -> Tier:
        return self.tiers[-1]

    def tier_for_span(self, span: int) -> Tier:
        """The innermost tier whose domain covers ``span`` devices — the
        tier a collective of that reach must traverse."""
        for t in self.tiers:
            if span <= t.span:
                return t
        return self.tiers[-1]

    @classmethod
    def from_machine_spec(cls, spec) -> "TierGraph":
        """Derive the tier ladder from a ``MachineSpec``:

          - ``ici``  — chips of one host (always present);
          - ``host`` — crossing hosts inside a slice (present when a
            slice spans several hosts; ICI bandwidth on TPU pods, the
            host-fabric override — e.g. a reference INI's NIC — when
            ``host_bandwidth_override`` is set);
          - ``dcn``  — crossing slices over per-host NICs (present when
            ``num_slices > 1``).
        """
        n = max(1, spec.num_devices)
        per_slice = max(1, spec.devices_per_slice)
        hosts_per_slice = max(1, spec.num_hosts // max(1, spec.num_slices))
        chips_per_host = max(1, per_slice // hosts_per_slice)
        ici_bw = spec.ici_bandwidth
        ici_lat = spec.ici_latency_us * 1e-6
        tiers = [Tier("ici", ici_bw, ici_lat, chips_per_host)]
        if per_slice > chips_per_host:
            host_bw = getattr(spec, "host_bandwidth_override", None)
            host_lat = getattr(spec, "host_latency_override_us", None)
            tiers.append(Tier(
                "host",
                float(host_bw) if host_bw is not None else ici_bw,
                float(host_lat) * 1e-6 if host_lat is not None
                else ici_lat, per_slice))
        if spec.num_slices > 1 and n > per_slice:
            tiers.append(Tier("dcn", spec.dcn_bandwidth,
                              spec.dcn_latency_us * 1e-6, n))
        return cls(tiers)


# ----------------------------------------------------------------------
# chaos-drill link degradation (resilience/faults.py ``degrade_link``)
# ----------------------------------------------------------------------

#: memoized accessor into the fault registry — pricing paths call this
#: 1e4-1e6 times per search, so the import resolves once
_ld_fn = None


def link_degradation_factor(name: str) -> float:
    """Active chaos-drill slowdown factor of one tier name (1.0 =
    healthy fabric). Registered by ``degrade_link@N:tier:factor``
    clauses (resilience/faults.py); every analytic tier-priced leg
    divides its bandwidth by this so predictions — and therefore the
    re-plan search — see the degraded link the moment the drill fires."""
    global _ld_fn
    if _ld_fn is None:
        try:
            from ..resilience.faults import link_degradation
            _ld_fn = link_degradation
        except Exception:  # noqa: BLE001 — no drill machinery
            _ld_fn = lambda t: 1.0  # noqa: E731
    return _ld_fn(name)


def effective_tier_bandwidth(tier: Tier) -> float:
    """``tier.bandwidth`` after any active chaos-drill degradation."""
    f = link_degradation_factor(tier.name)
    return tier.bandwidth / f if f > 1.0 else tier.bandwidth


def flat_ring_links(topo, devices: Tuple[int, ...]):
    """Flattened ring-collective routes over ``devices``, cached on the
    topology: ``(offsets, links, factors-or-None)`` where ``links`` is
    the concatenated per-participant hop list and ``offsets[i]`` its
    start. Only builder-independent data (raw link tuples, bandwidth
    factors) is cached here — processor-id mapping is per consumer
    (search/tasksim.py), so one shared topology can never serve another
    builder's ids. The cache is bounded at ``_RING_ROUTE_CACHE_CAP``
    entries (cleared wholesale when full; hot tuples repopulate).

    A module function rather than a method so any duck-typed topology
    (``MachineSpec.topology_override`` accepts arbitrary objects with
    ``ring_links``/``link_index``) gets the same caching."""
    cache = topo.__dict__.get("_ring_route_cache")
    if cache is None:
        cache = {}
        topo.__dict__["_ring_route_cache"] = cache
    hit = cache.get(devices)
    if hit is None:
        routes = topo.ring_links(list(devices))
        factor = getattr(topo, "link_factor", None)
        off = [0]
        links: List[Link] = []
        fac: Optional[List[float]] = [] if factor else None
        for hops in routes:
            for link in hops:
                links.append(link)
                if fac is not None:
                    fac.append(float(factor(link)))
            off.append(len(links))
        if len(cache) >= _RING_ROUTE_CACHE_CAP:
            cache.clear()
        hit = (tuple(off), tuple(links),
               tuple(fac) if fac is not None else None)
        cache[devices] = hit
    return hit


@dataclasses.dataclass
class TorusTopology:
    """N-D torus of devices; wrap links exist on dims of size >= 3
    (TPU slices expose wraparound only for full rings)."""
    shape: Tuple[int, ...]

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def coord(self, dev: int) -> Tuple[int, ...]:
        c = []
        for s in reversed(self.shape):
            c.append(dev % s)
            dev //= s
        return tuple(reversed(c))

    def device(self, coord: Sequence[int]) -> int:
        d = 0
        for x, s in zip(coord, self.shape):
            d = d * s + (x % s)
        return d

    def _wrap(self, dim: int) -> bool:
        return self.shape[dim] >= 3

    def hop_distance(self, a: int, b: int) -> int:
        """Total hops of the dimension-ordered route."""
        ca, cb = self.coord(a), self.coord(b)
        hops = 0
        for k, s in enumerate(self.shape):
            d = abs(ca[k] - cb[k])
            hops += min(d, s - d) if self._wrap(k) else d
        return hops

    def route(self, src: int, dst: int) -> List[Link]:
        """Dimension-ordered shortest-wrap route as outgoing links.

        Analog of ``ShortestPathNetworkRoutingStrategy::get_routes``
        (``simulator.h:399``) specialized to the torus, where
        dimension-ordered IS shortest-path."""
        links: List[Link] = []
        cur = list(self.coord(src))
        tgt = self.coord(dst)
        for k, s in enumerate(self.shape):
            while cur[k] != tgt[k]:
                fwd = (tgt[k] - cur[k]) % s
                back = (cur[k] - tgt[k]) % s
                step = 1 if (fwd <= back or not self._wrap(k)) else -1
                if not self._wrap(k) and tgt[k] < cur[k]:
                    step = -1
                links.append((self.device(cur), k, step))
                cur[k] = (cur[k] + step) % s
        return links

    def ring_links(self, devices: Sequence[int]) -> List[List[Link]]:
        """Per-step physical links of a ring collective over ``devices``
        (each participant sends to its successor every step)."""
        n = len(devices)
        return [self.route(devices[i], devices[(i + 1) % n])
                for i in range(n)]

    def link_index(self) -> Dict[Link, int]:
        """Dense numbering of every outgoing port (device, dim, dir)."""
        idx: Dict[Link, int] = {}
        for d in range(self.num_devices):
            for k in range(len(self.shape)):
                for s in (1, -1):
                    idx[(d, k, s)] = len(idx)
        return idx


class GraphTopology:
    """Arbitrary weighted interconnect: a directed connection matrix over
    devices, with weighted shortest-path routing.

    Analog of the reference's ``NetworkedMachineModel`` + connection-
    matrix generators + ``WeightedShortestPathRoutingStrategy``
    (``src/runtime/network.cc:1-586``, ``include/flexflow/
    simulator.h:381-515``). Where the torus model is exact for one
    healthy slice, this expresses what it cannot: big-switch fabrics,
    degraded links, heterogeneous multi-slice pods (ICI inside each
    slice, DCN between them).

    ``conn[(i, j)]`` is the link bandwidth in bytes/s (absent = no
    link). The task simulator charges each link on a route a duration
    scaled by ``link_factor`` — the ratio of the fastest link's
    bandwidth to this link's — so a DCN hop or a degraded link
    serializes traffic proportionally longer. The ``Link`` key is
    ``(src, 0, dst)``: same 3-tuple arity as the torus's
    ``(device, dim, dir)`` ports, so ``link_index``/``ring_links``
    consumers work unchanged.
    """

    def __init__(self, num_devices: int,
                 conn: Dict[Tuple[int, int], float]):
        self.num_devices = num_devices
        self.conn = dict(conn)
        self.max_bw = max(conn.values()) if conn else 1.0
        self._routes_cache: Dict[Tuple[int, int, int], List[List[Link]]] = {}
        self._dist_cache: Dict[int, Dict[int, float]] = {}
        self._rdist_cache: Dict[int, Dict[int, float]] = {}
        # link-table fingerprint: keys the SHARED Dijkstra cache, so
        # identical fabrics (rebuilt per search/test) reuse sweeps while
        # degraded() copies — different table, different key — never
        # alias (the per-instance dicts above stay as the L1 memo).
        # The FULL tuple is the key, not its hash: a 64-bit hash
        # collision between distinct fabrics would silently serve wrong
        # distances; equality comparison rules that out
        self._conn_key = (num_devices,
                          tuple(sorted(self.conn.items())))
        # Dijkstra weight: dimensionless time factor max_bw/bw (>= 1 per
        # hop, the same normalization as link_factor). Raw per-byte
        # weights (1/bw ~ 1e-11 for real ICI bandwidths) would sit at
        # the same scale as any absolute epsilon and break the
        # shortest-path-DAG edge test on fast fabrics.
        self._adj: Dict[int, List[Tuple[int, float]]] = {}
        self._radj: Dict[int, List[Tuple[int, float]]] = {}
        for (i, j), bw in conn.items():
            w = self.max_bw / max(bw, 1e-30)
            self._adj.setdefault(i, []).append((j, w))
            self._radj.setdefault(j, []).append((i, w))

    # ---- constructors (reference network.cc topology generators) ----
    @classmethod
    def from_torus(cls, shape: Sequence[int],
                   bw: float = 1.0) -> "GraphTopology":
        t = TorusTopology(tuple(shape))
        conn: Dict[Tuple[int, int], float] = {}
        for d in range(t.num_devices):
            c = t.coord(d)
            for k, s in enumerate(shape):
                for step in ((1, -1) if s >= 3 else (1,) if c[k] + 1 < s
                             else ()):
                    nc = list(c)
                    nc[k] = (nc[k] + step) % s
                    conn[(d, t.device(nc))] = bw
                    conn[(t.device(nc), d)] = bw
        return cls(t.num_devices, conn)

    @classmethod
    def big_switch(cls, n: int, bw: float = 1.0) -> "GraphTopology":
        """Full crossbar: every pair directly connected (the reference's
        ``FlatDegConstraintNetworkTopologyGenerator`` limit case)."""
        conn = {(i, j): bw for i in range(n) for j in range(n) if i != j}
        return cls(n, conn)

    @classmethod
    def degraded(cls, base: "GraphTopology",
                 slow_links: Sequence[Tuple[int, int]],
                 factor: float) -> "GraphTopology":
        """Copy of ``base`` with the listed (src, dst) links running at
        ``bw / factor`` (fault/brownout modeling)."""
        conn = dict(base.conn)
        for (i, j) in slow_links:
            if (i, j) in conn:
                conn[(i, j)] = conn[(i, j)] / factor
        return cls(base.num_devices, conn)

    @classmethod
    def multi_slice_torus(cls, shape: Sequence[int], n_slices: int,
                          ici_bw: float, dcn_bw: float,
                          hosts_per_slice: int = 1) -> "GraphTopology":
        """``n_slices`` tori joined by DCN: each slice exposes
        ``hosts_per_slice`` gateway devices (block-contiguous hosts'
        first chips) with all-to-all DCN links between slices — the
        fabric of a real multi-slice pod."""
        one = cls.from_torus(shape, ici_bw)
        per = one.num_devices
        conn: Dict[Tuple[int, int], float] = {}
        for s in range(n_slices):
            off = s * per
            for (i, j), bw in one.conn.items():
                conn[(off + i, off + j)] = bw
        chips_per_host = max(1, per // max(1, hosts_per_slice))
        gateways = [list(range(s * per, (s + 1) * per, chips_per_host))
                    for s in range(n_slices)]
        for a in range(n_slices):
            for b in range(n_slices):
                if a == b:
                    continue
                for ga, gb in zip(gateways[a], gateways[b]):
                    conn[(ga, gb)] = dcn_bw
        return cls(per * n_slices, conn)

    # ---- routing (WeightedShortestPathRoutingStrategy analog) ----
    def routes(self, src: int, dst: int, k: int = 4) -> List[List[Link]]:
        """Up to ``k`` equal-cost weighted-shortest paths src -> dst.

        All shortest paths live on the Dijkstra shortest-path DAG
        (edges u->v with dist[v] == dist[u] + w); a depth-first walk in
        sorted-neighbor order enumerates them deterministically. The
        reference's WeightedShortestPathRoutingStrategy returns one
        path chosen by a random tie-break (network.cc:89 —
        ``unif(gen) < 0.5``), spreading flows across equal-cost paths
        statistically; here :meth:`route` hash-selects per (src, dst)
        flow, the deterministic form of the same ECMP spreading."""
        if src == dst:
            return [[]]
        hit = self._routes_cache.get((src, dst, k))
        if hit is not None:
            return hit
        dist = self._dist_from(src)
        if dst not in dist:
            raise ValueError(f"no route {src} -> {dst} in topology")
        rdist = self._dist_from(dst, rev=True)
        total = dist[dst]
        # relative tolerance: weights are dimensionless (max_bw/bw >= 1)
        # but long routes accumulate fp error proportional to length
        tol = _EPS * max(1.0, total)
        # one candidate per equal-cost FIRST HOP (sorted, deterministic):
        # distinct egress links by construction, so per-flow selection
        # genuinely spreads source traffic (a k-truncated DFS kept only
        # paths differing near dst — every candidate shared hop 1)
        inf = float("inf")
        firsts = [v for v, w in sorted(self._adj.get(src, ()))
                  if w + rdist.get(v, inf) <= total + tol]
        if not firsts:
            # fp-pathological fabric: fall back to the single best hop
            firsts = [min(self._adj.get(src, ()),
                          key=lambda vw: (vw[1] + rdist.get(vw[0], inf),
                                          vw[0]))[0]]
        paths: List[List[int]] = []
        for first in firsts[:max(1, k)]:
            # greedy descent on rdist: from any node on a shortest path
            # the neighbor minimizing (w + rdist) continues one, so the
            # walk reaches dst in <= num_devices hops; a step cap guards
            # degenerate fp cases (such a path is simply dropped)
            path = [src, first]
            u = first
            for _ in range(self.num_devices):
                if u == dst:
                    break
                u = min(self._adj.get(u, ()),
                        key=lambda vw: (vw[1] + rdist.get(vw[0], inf),
                                        vw[0]))[0]
                path.append(u)
            if path[-1] == dst:
                paths.append(path)
        if not paths:
            raise ValueError(f"no route {src} -> {dst} in topology")
        out = [[(p[i], 0, p[i + 1]) for i in range(len(p) - 1)]
               for p in paths]
        if len(self._routes_cache) >= _ROUTES_CACHE_CAP:
            self._routes_cache.clear()     # hot pairs repopulate
        self._routes_cache[(src, dst, k)] = out
        return out

    def _dist_from(self, node: int, rev: bool = False) -> Dict[int, float]:
        """Memoized full Dijkstra distance map from ``node`` (forward or
        reverse graph) — ring_links issues a route per device pair, so
        per-node caching turns 2P sweeps into at most 2V. Two-level:
        per-instance dict first, then the module-level bounded cache
        keyed on the link-table fingerprint (``_conn_key``), so a fresh
        but identical topology object reuses earlier sweeps while a
        ``degraded()`` copy's different table can never alias."""
        cache = self._rdist_cache if rev else self._dist_cache
        hit = cache.get(node)
        if hit is not None:
            return hit
        skey = (self._conn_key, node, rev)
        shared = _SHARED_DIST_CACHE.get(skey)
        if shared is not None:
            cache[node] = shared
            return shared
        import heapq
        adj = self._radj if rev else self._adj
        dist = {node: 0.0}
        pq = [(0.0, node)]
        while pq:
            d, u = heapq.heappop(pq)
            if d > dist.get(u, float("inf")):
                continue
            for v, w in adj.get(u, ()):
                nd = d + w
                if nd < dist.get(v, float("inf")) - _EPS:
                    dist[v] = nd
                    heapq.heappush(pq, (nd, v))
        cache[node] = dist
        if len(_SHARED_DIST_CACHE) >= _DIST_CACHE_CAP:
            _SHARED_DIST_CACHE.clear()     # hot fabrics repopulate
        _SHARED_DIST_CACHE[skey] = dist
        return dist

    def route(self, src: int, dst: int) -> List[Link]:
        """One weighted-shortest path; equal-cost alternatives are
        hash-selected per flow (deterministic ECMP — see
        :meth:`routes`)."""
        if src == dst:
            return []
        cands = self.routes(src, dst)   # cached per (src, dst, k)
        # deterministic per-flow spreading: distinct (src, dst) pairs
        # land on different equal-cost paths; repeated queries agree
        idx = (src * 2654435761 + dst * 40503) % len(cands)
        return cands[idx]

    def hop_distance(self, a: int, b: int) -> int:
        """Minimum hop count over the ENUMERATED equal-cost candidates
        (:meth:`routes`, one greedy path per equal-cost first hop,
        k <= 4): deterministic and independent of the per-flow hash
        (ADVICE r4). Not guaranteed to be the global minimum-hop
        equal-weight path — ties inside the greedy descent break by
        node id, which is fine for the latency estimates this feeds."""
        if a == b:
            return 0
        return min(len(p) for p in self.routes(a, b))

    def ring_links(self, devices: Sequence[int]) -> List[List[Link]]:
        n = len(devices)
        return [self.route(devices[i], devices[(i + 1) % n])
                for i in range(n)]

    def link_index(self) -> Dict[Link, int]:
        return {(i, 0, j): k
                for k, (i, j) in enumerate(sorted(self.conn.keys()))}

    def link_factor(self, link: Link) -> float:
        """Duration multiplier for traffic on this link relative to the
        fastest link in the fabric (DCN/degraded links serialize
        longer)."""
        bw = self.conn.get((link[0], link[2]))
        return self.max_bw / bw if bw else 1.0


# ----------------------------------------------------------------------
# machine description files (--machine-model-file)
# ----------------------------------------------------------------------

def _parse_ini(text: str) -> Dict[str, str]:
    """``key = value`` lines, ``#`` comments — the reference's
    ``machine_config_example`` format. Lines that look like
    assignments but don't parse raise a typed ``ValueError`` naming
    the offending line instead of being silently dropped."""
    out: Dict[str, str] = {}
    for ln, line in enumerate(text.splitlines(), start=1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        m = re.match(r"([A-Za-z0-9_]+)\s*=\s*(.+)", line)
        if not m:
            raise ValueError(
                f"machine file line {ln}: {line!r} is not a "
                f"'key = value' entry")
        out[m.group(1)] = m.group(2).strip()
    return out


def _cfg_get(cfg: Dict, key: str, conv, default=None, path: str = ""):
    """Typed machine-file field access: a malformed value raises
    ``ValueError`` naming the offending key (never a bare
    ``KeyError``/``TypeError`` from deep inside the parser)."""
    if key not in cfg or cfg[key] is None:
        return default
    try:
        return conv(cfg[key])
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"machine file {path or '<config>'}: invalid value "
            f"{cfg[key]!r} for key '{key}': {e}") from e


def _shape_conv(v) -> Tuple[int, ...]:
    """ici_shape in JSON list form or INI text form ('4x8', '4 8',
    '4,8')."""
    if isinstance(v, str):
        parts = [p for p in re.split(r"[x,\s]+", v.strip()) if p]
        return tuple(int(p) for p in parts)
    return tuple(int(x) for x in v)


#: keys marking a TPU-native description (JSON or INI); their absence
#: from an INI file selects the reference machine_config_example schema
_TPU_KEYS = ("generation", "ici_shape", "num_slices", "num_devices",
             "dcn_bandwidth_gbps", "ici_bandwidth_gbps")


def load_machine_file(path: str):
    """Parse a machine description into a ``MachineSpec``.

    Formats:
      - JSON (TPU-native): ``{"generation": "v5e", "ici_shape": [4, 8],
        "num_hosts": 4, "num_slices": 1, "dcn_bandwidth_gbps": 25, ...}``
      - INI with the same TPU-native keys (``ici_shape = 4x8``) — e.g.
        ``machine_configs/v5e-2slice.ini``;
      - reference-style INI (``machine_config_example``): ``num_nodes``,
        ``num_gpus_per_socket`` x ``num_sockets_per_node`` -> devices,
        ``nvlink_bandwidth`` -> ICI GB/s, ``nic_bandwidth`` -> DCN,
        latencies in ms.

    Malformed entries raise ``ValueError`` naming the offending key.
    """
    from .machine import MachineSpec

    with open(path) as f:
        text = f.read()
    try:
        cfg = json.loads(text)
        is_json = True
    except json.JSONDecodeError:
        cfg = _parse_ini(text)
        is_json = False

    if is_json or any(k in cfg for k in _TPU_KEYS):
        ici_shape = _cfg_get(cfg, "ici_shape", _shape_conv, None, path)
        num_slices = _cfg_get(cfg, "num_slices", int, 1, path)
        num_devices = _cfg_get(cfg, "num_devices", int, None, path)
        if num_devices is None:
            num_devices = _prod(ici_shape or [1]) * num_slices
        spec = MachineSpec(
            num_devices=num_devices,
            generation=_cfg_get(cfg, "generation", str, "v5e", path),
            ici_shape=ici_shape,
            num_slices=num_slices,
            dcn_bandwidth_gbps=_cfg_get(cfg, "dcn_bandwidth_gbps",
                                        float, 25.0, path),
            ici_latency_us=_cfg_get(cfg, "ici_latency_us", float, 1.0,
                                    path),
            dcn_latency_us=_cfg_get(cfg, "dcn_latency_us", float, 10.0,
                                    path),
        )
        spec.num_hosts = _cfg_get(cfg, "num_hosts", int,
                                  spec.num_slices, path)
        ici_bw = _cfg_get(cfg, "ici_bandwidth_gbps", float, None, path)
        if ici_bw is not None:
            spec.ici_bandwidth_override = ici_bw * 1e9
        host_bw = _cfg_get(cfg, "host_bandwidth_gbps", float, None, path)
        if host_bw is not None:
            spec.host_bandwidth_override = host_bw * 1e9
        host_lat = _cfg_get(cfg, "host_latency_us", float, None, path)
        if host_lat is not None:
            spec.host_latency_override_us = host_lat
        tflops = _cfg_get(cfg, "peak_tflops", float, None, path)
        if tflops is not None:
            spec.peak_flops_override = tflops * 1e12
        from .machine import TPU_GENERATIONS
        if spec.generation not in TPU_GENERATIONS:
            raise ValueError(
                f"machine file {path}: invalid value "
                f"{spec.generation!r} for key 'generation'")
        if "topology" in cfg:
            if not isinstance(cfg["topology"], dict):
                raise ValueError(
                    f"machine file {path}: invalid value for key "
                    f"'topology': expected an object, got "
                    f"{type(cfg['topology']).__name__}")
            spec.topology_override = topology_from_json(cfg["topology"],
                                                        spec)
        return spec

    # reference INI: nodes x sockets x gpus-per-socket accelerators;
    # nvlink ≙ intra-node fabric (ICI), nic ≙ inter-node (DCN)
    nodes = _cfg_get(cfg, "num_nodes", int, 1, path)
    sockets = _cfg_get(cfg, "num_sockets_per_node", int, 1, path)
    per_socket = _cfg_get(cfg, "num_gpus_per_socket", int, 1, path)
    per_node = sockets * per_socket
    spec = MachineSpec(
        num_devices=nodes * per_node,
        num_slices=nodes if nodes > 1 else 1,
        dcn_bandwidth_gbps=_cfg_get(cfg, "nic_bandwidth", float, 25.0,
                                    path),
        # reference latencies are in ms
        ici_latency_us=_cfg_get(cfg, "nvlink_latency", float, 0.001,
                                path) * 1e3,
        dcn_latency_us=_cfg_get(cfg, "nic_latency", float, 0.01,
                                path) * 1e3,
    )
    spec.num_hosts = nodes
    spec.ici_shape = (per_node,)
    nvlink = _cfg_get(cfg, "nvlink_bandwidth", float, None, path)
    if nvlink is not None:
        spec.ici_bandwidth_override = nvlink * 1e9
    return spec


def topology_from_json(doc: Dict, spec) -> GraphTopology:
    """Build a ``GraphTopology`` from a machine-file ``topology`` block.

    Kinds (reference topology generators, ``network.cc``):
      - ``{"kind": "torus", "shape": [4, 8]}``
      - ``{"kind": "big_switch", "n": 32}``
      - ``{"kind": "multi_slice_torus", "shape": [4, 8], "n_slices": 2,
         "hosts_per_slice": 8}``
      - ``{"kind": "degraded", "base": {...}, "slow_links": [[0, 1]],
         "factor": 4}``
      - ``{"kind": "matrix", "n": 4,
         "links": [[src, dst, bandwidth_gbps], ...]}``
    """
    kind = doc.get("kind", "torus")
    ici = spec.ici_bandwidth
    if kind == "torus":
        return GraphTopology.from_torus(doc["shape"], ici)
    if kind == "big_switch":
        return GraphTopology.big_switch(int(doc["n"]), ici)
    if kind == "multi_slice_torus":
        return GraphTopology.multi_slice_torus(
            doc["shape"], int(doc["n_slices"]), ici_bw=ici,
            dcn_bw=spec.dcn_bandwidth,
            hosts_per_slice=int(doc.get("hosts_per_slice", 1)))
    if kind == "degraded":
        base = topology_from_json(doc["base"], spec)
        return GraphTopology.degraded(
            base, [tuple(l) for l in doc["slow_links"]],
            float(doc["factor"]))
    if kind == "matrix":
        conn = {(int(s), int(d)): float(bw) * 1e9
                for s, d, bw in doc["links"]}
        return GraphTopology(int(doc["n"]), conn)
    raise ValueError(f"unknown topology kind {kind!r}")


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= int(x)
    return n
