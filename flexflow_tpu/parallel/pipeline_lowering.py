"""Pipeline lowering: find a repeated-block region in a layer graph and
lower it onto the GPipe engine, through the PRODUCT path (FFModel.compile
→ Executor), not a hand-built stage_fn.

The reference reserves ``OP_PIPELINE`` (``include/flexflow/ffconst.h:159``)
and task ids but ships no implementation; here pipelining is a first-class
strategy dimension: ``FFConfig.pipeline_stages = k`` (or a searched
candidate) partitions the *maximal repeated-block run* of the graph —
transformer blocks, residual MLP stacks — into k structurally identical
stages, stacks their parameters on a leading stage dim sharded over the
``pp`` mesh axis, and executes the region with the ``lax.scan`` +
``ppermute`` schedule from ``parallel/pipeline.py``. Layers before/after
the region (embedding, LM head, loss) run as ordinary sharded ops.

Constraints (checked by ``find_pipeline_region``): the region must be a
chain of ``n_stages`` structurally identical single-input/single-output
chunks with shape-preserving boundaries, no stateful ops (BN running
stats), and no tensor from outside the region consumed inside it (other
than the boundary activation). Dropout inside the region draws its rng
from (step, stage, scan-step), so masks differ across microbatches.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.layer import Layer
from ..ffconst import OperatorType

__all__ = ["PipelineRegion", "assign_tp_roles", "find_pipeline_region",
           "find_ragged_pipeline_region", "layer_signature",
           "region_entry_transition", "region_exit_transition"]


def layer_signature(layer: Layer) -> Tuple:
    """Structural identity of a layer for repeated-block detection:
    op type + params + input/output shapes/dtypes (not names/guids)."""
    from ..core.layer import _hashable
    return (layer.op_type, _hashable(layer.params),
            tuple(t.shape for t in layer.inputs),
            tuple(t.dtype for t in layer.inputs),
            tuple(t.shape for t in layer.outputs))


@dataclasses.dataclass
class PipelineRegion:
    """A lowered pipeline region inside a layer program."""
    start: int                  # first region layer index in the program
    end: int                    # exclusive
    n_stages: int
    n_microbatches: int
    entry_guid: int             # activation entering stage 0
    exit_guid: int              # activation leaving stage n_stages-1
    template: List[Layer]       # chunk 0's layers (the chunk program)
    template_entry_guid: int
    # for global chunk c (= stage + k*n_stages under the interleaved
    # schedule; == stage when n_chunks == 1), layer j corresponds to
    # template[j]; stage_layer_names[c][j] is its original layer name,
    # used to initialize per-chunk weights before stacking
    stage_layer_names: List[List[str]]
    # interleaved (circular) schedule: chunks per stage. 1 = plain GPipe;
    # v > 1 splits the region into v*S chunks, device s owning chunks
    # {s + k*S} — the template then describes ONE CHUNK, not one stage.
    n_chunks: int = 1
    # mesh binding, filled in by parallel.presets.pipeline_strategy
    pp_axis: Optional[str] = None
    dp_axes: Tuple[str, ...] = ()
    # tensor parallelism INSIDE each stage (Megatron-style, composed with
    # dp x pp — the reference composes per-op machine views the same way,
    # substitution.cc:1898): template layer name -> "attn" | "col" | "row".
    # "attn": heads sharded over tp_axis, one psum after the out-proj;
    # "col"/"row": paired Linears (col shards the output dim, row shards
    # the input dim, one psum after row). None when tp is off.
    tp_axis: Optional[str] = None
    tp_roles: Dict[str, str] = dataclasses.field(default_factory=dict)
    # ---- ragged schedule (gpipe_ragged) ----
    # per-stage block counts (sum = number of region blocks); None =
    # uniform schedule. With counts set, the template describes ONE
    # BLOCK and stage s applies counts[s] of them per step (padded to
    # max(counts) and masked).
    counts: Optional[Tuple[int, ...]] = None
    # layers absorbed INTO stage 0 / stage S-1 (embedding prologue /
    # LM-head epilogue) — they execute inside the pipelined shard_map
    # instead of running replicated outside the region
    prologue: List[Layer] = dataclasses.field(default_factory=list)
    epilogue: List[Layer] = dataclasses.field(default_factory=list)
    # graph-input tensors the prologue consumes (microbatched raw feed)
    prologue_inputs: List[Any] = dataclasses.field(default_factory=list)
    # tensor guid the epilogue produces (the region's overall output;
    # == exit_guid when there is no epilogue)
    epilogue_exit_guid: Optional[int] = None

    @property
    def is_ragged(self) -> bool:
        return self.counts is not None

    @property
    def region_out_guid(self) -> int:
        """guid of the tensor the pipelined apply produces overall."""
        return self.epilogue_exit_guid if self.epilogue \
            else self.exit_guid

    @property
    def template_exit_guid(self) -> int:
        return self.template[-1].outputs[0].guid

    @property
    def layers_per_stage(self) -> int:
        return len(self.template)

    def param_name(self, template_layer: Layer) -> str:
        """Key of the stacked parameter subtree in the params pytree."""
        return f"pp::{template_layer.name}"


def _single_crossing(layers: Sequence[Layer], cut: int,
                     region_end: int) -> Optional[int]:
    """If exactly one tensor produced by layers[:cut] (within the region
    under test) is consumed by layers[cut:region_end], return its guid."""
    produced = {t.guid for l in layers[:cut] for t in l.outputs}
    crossing = set()
    for l in layers[cut:region_end]:
        for t in l.inputs:
            if t.guid in produced:
                crossing.add(t.guid)
            elif t.owner_layer is not None and \
                    t.owner_layer not in layers[cut:region_end]:
                # produced outside the candidate window entirely
                return None
    if len(crossing) != 1:
        return None
    return next(iter(crossing))


def _chunks_isomorphic(a: Sequence[Layer], b: Sequence[Layer],
                       a_entry: int, b_entry: int) -> bool:
    """Do chunks a and b compute the same function of their entry tensor?
    Layer-wise signature equality + input-wiring isomorphism."""
    guid_map = {a_entry: b_entry}
    for la, lb in zip(a, b):
        if layer_signature(la) != layer_signature(lb):
            return False
        if len(la.inputs) != len(lb.inputs) or \
                len(la.outputs) != len(lb.outputs):
            return False
        for ta, tb in zip(la.inputs, lb.inputs):
            if guid_map.get(ta.guid) != tb.guid:
                return False
        for ta, tb in zip(la.outputs, lb.outputs):
            guid_map[ta.guid] = tb.guid
    return True


def _has_state(layer: Layer) -> bool:
    from ..ops import get_op_def
    op = get_op_def(layer.op_type)
    state_spec = getattr(op, "state_spec", None)
    if state_spec is None:
        return False
    ss = state_spec(layer.params, [t.shape for t in layer.inputs],
                    [t.dtype for t in layer.inputs])
    return bool(ss)


def find_pipeline_region(layers: Sequence[Layer], n_stages: int,
                         n_microbatches: int = 0, n_chunks: int = 1
                         ) -> Optional[PipelineRegion]:
    """Find the maximal run of identical single-input/single-output chunks
    divisible into ``n_stages`` stages (x ``n_chunks`` chunks per stage
    for the interleaved schedule). Returns None when the graph has no
    such region (the caller falls back to non-pipelined execution)."""
    layers = list(layers)
    n_parts = n_stages * max(n_chunks, 1)   # total chunk count to divide by
    best = find_repeated_run(layers, n_parts)
    if best is None:
        return None
    total, start, unit = best
    reps = total // unit
    per_chunk = (reps // n_parts) * unit
    end = start + total
    region = layers[start:end]
    # chunk boundaries must each cross exactly one tensor
    boundaries = chunk_boundaries(layers, start, per_chunk, n_parts)
    if boundaries is None:
        return None
    entry = boundaries[0]
    exit_guid = region[-1].outputs[0].guid
    # chunk shape preservation: entry and exit tensors of each chunk match
    by_guid = {t.guid: t for l in layers for t in l.outputs}
    for l in layers:
        for t in l.inputs:
            by_guid.setdefault(t.guid, t)
    shapes = {tuple(by_guid[g].shape) for g in boundaries + [exit_guid]
              if g in by_guid}
    if len(shapes) != 1:
        return None
    # chunks must be isomorphic to chunk 0 and stateless
    template = region[:per_chunk]
    if any(_has_state(l) for l in template):
        return None
    for c in range(1, n_parts):
        chunk = region[c * per_chunk:(c + 1) * per_chunk]
        if not _chunks_isomorphic(template, chunk, boundaries[0],
                                  boundaries[c]):
            return None
    if n_microbatches <= 0:
        n_microbatches = 2 * n_stages
    elif max(n_chunks, 1) > 1 and n_microbatches % n_stages:
        # the circular schedule's round-robin needs M % S == 0; a
        # user-chosen M that violates it must fail loudly here, not at
        # the executor's batch-divisibility assert with a rounded M
        raise ValueError(
            f"interleaved schedule (n_chunks={n_chunks}) requires "
            f"n_microbatches % n_stages == 0, got M={n_microbatches} "
            f"S={n_stages}")
    return PipelineRegion(
        start=start, end=end, n_stages=n_stages,
        n_microbatches=n_microbatches, n_chunks=max(n_chunks, 1),
        entry_guid=entry,
        exit_guid=exit_guid, template=list(template),
        template_entry_guid=boundaries[0],
        stage_layer_names=[
            [l.name for l in region[c * per_chunk:(c + 1) * per_chunk]]
            for c in range(n_parts)])


def _absorbable_prologue(layers: Sequence[Layer], start: int, end: int,
                         entry_guid: int, entry_batch: int):
    """Can ``layers[:start]`` move inside stage 0? Yes iff every
    pre-layer input is a graph input whose leading dim IS the batch dim
    (``entry_batch`` — so microbatch slicing is meaningful) or
    pre-produced, nothing pre-produced is consumed at/after ``end``
    except via the region, the single region crossing is
    ``entry_guid``, and nothing is stateful. Returns
    ``(prologue_layers, raw_input_tensors)`` or ``(None, None)``."""
    pre = list(layers[:start])
    if not pre:
        return None, None
    produced = {t.guid for l in pre for t in l.outputs}
    raw_inputs = {}
    for l in pre:
        if _has_state(l):
            return None, None
        for t in l.inputs:
            if t.guid in produced:
                continue
            if t.owner_layer is not None:
                return None, None       # fed by a non-pre layer
            if not t.shape or t.get_tensor() is not None:
                return None, None       # const / shapeless: not feedable
            if t.shape[0] != entry_batch:
                # non-batch-led input (shared mask, (T,) positions):
                # microbatch slicing would silently hand each microbatch
                # 1/M of it — not absorbable
                return None, None
            raw_inputs[t.guid] = t
    # pre outputs consumed outside the region (post layers)?
    for l in layers[end:]:
        for t in l.inputs:
            if t.guid in produced:
                return None, None
    # region must consume exactly the entry from pre
    crossing = {t.guid for l in layers[start:end] for t in l.inputs
                if t.guid in produced}
    if crossing != {entry_guid}:
        return None, None
    # every pre output must be consumed by pre or the region: an
    # unconsumed pre tensor may be a graph OUTPUT (hidden-state export),
    # and absorbing its producer would strand it at trace time
    consumed = {t.guid for l in layers for t in l.inputs}
    for g in produced:
        if g not in consumed:
            return None, None
    return pre, list(raw_inputs.values())


def _absorbable_epilogue(layers: Sequence[Layer], end: int,
                         exit_guid: int, final_output_guid: int):
    """Maximal prefix of ``layers[end:]`` forming a chain off the region
    exit: each layer consumes only ``exit_guid`` or earlier epilogue
    outputs, is stateless, and produces one output. The final softmax is
    left OUTSIDE when it produces the graph output (so the executor's
    CE-on-logits fusion still sees the pre-softmax logits). Returns
    ``(epilogue_layers, epilogue_exit_guid)`` (possibly ``([], None)``)."""
    post = list(layers[end:])
    avail = {exit_guid}
    chain: List[Layer] = []
    out_guid = None
    for l in post:
        if _has_state(l) or len(l.outputs) != 1:
            break
        if not all(t.guid in avail for t in l.inputs):
            break
        g = l.outputs[0].guid
        if l.op_type == OperatorType.OP_SOFTMAX \
                and g == final_output_guid:
            break               # keep the CE-fusion producer outside
        chain.append(l)
        avail.add(g)
        out_guid = g
    if not chain:
        return [], None
    # the chain must hand exactly ONE tensor to whatever follows
    chain_guids = {l.outputs[0].guid for l in chain}
    consumed_later = set()
    for l in post[len(chain):]:
        for t in l.inputs:
            if t.guid in chain_guids:
                consumed_later.add(t.guid)
    if len(consumed_later) > 1:
        return [], None
    if consumed_later:
        out_guid = next(iter(consumed_later))
        # drop trailing chain layers past the handed-off tensor
        keep: List[Layer] = []
        for l in chain:
            keep.append(l)
            if l.outputs[0].guid == out_guid:
                break
        chain = keep
    # nothing after the absorbed chain may read a tensor the epilogue
    # swallowed: the executor exports ONLY out_guid from the region, so
    # any later read of exit_guid or an interior chain output would
    # KeyError at trace time — bail instead of absorbing
    internal = ({exit_guid} | {l.outputs[0].guid for l in chain}) \
        - {out_guid}
    for l in post[len(chain):]:
        for t in l.inputs:
            if t.guid in internal:
                return [], None
    # and every swallowed tensor must be consumed INSIDE the chain: an
    # unconsumed interior tensor may be a graph output (e.g. a
    # hidden-states export in a multi-output program) that tracing
    # would then fail to find in env
    chain_consumed = {t.guid for l in chain for t in l.inputs}
    for g in internal:
        if g not in chain_consumed:
            return [], None
    return chain, out_guid


def find_ragged_pipeline_region(layers: Sequence[Layer], n_stages: int,
                                n_microbatches: int = 0
                                ) -> Optional[PipelineRegion]:
    """Ragged variant of ``find_pipeline_region``: per-stage block
    counts may differ (no ``reps % n_stages`` requirement) and the
    layers before/after the repeated run are absorbed into stage 0 /
    stage S-1 when structurally possible (embedding and LM head
    pipelined end-to-end). Plain GPipe schedule only (no interleaving,
    no in-stage tp in v1)."""
    layers = list(layers)
    run = find_repeated_run(layers, 1)
    if run is None:
        return None
    total, start, unit = run
    reps = total // unit
    if reps < n_stages:
        return None
    end = start + total
    region = layers[start:end]
    boundaries = chunk_boundaries(layers, start, unit, reps)
    if boundaries is None:
        return None
    entry = boundaries[0]
    exit_guid = region[-1].outputs[0].guid
    by_guid = {t.guid: t for l in layers for t in l.outputs}
    for l in layers:
        for t in l.inputs:
            by_guid.setdefault(t.guid, t)
    shapes = {tuple(by_guid[g].shape) for g in boundaries + [exit_guid]
              if g in by_guid}
    if len(shapes) != 1:
        return None
    template = region[:unit]
    if any(_has_state(l) for l in template):
        return None
    for c in range(1, reps):
        chunk = region[c * unit:(c + 1) * unit]
        if not _chunks_isomorphic(template, chunk, boundaries[0],
                                  boundaries[c]):
            return None
    # ragged counts: extras go to interior stages (stage 0 carries the
    # prologue, stage S-1 the epilogue)
    base, extra = divmod(reps, n_stages)
    counts = [base] * n_stages
    order = list(range(1, n_stages - 1)) + [0, n_stages - 1] \
        if n_stages > 2 else list(range(n_stages))
    for i in range(extra):
        counts[order[i % len(order)]] += 1
    final_out = layers[-1].outputs[0].guid if layers else -1
    entry_batch = next(iter(shapes))[0] if shapes else 0
    prologue, pro_inputs = _absorbable_prologue(layers, start, end, entry,
                                                entry_batch)
    epilogue, epi_out = _absorbable_epilogue(layers, end, exit_guid,
                                             final_out)
    if n_microbatches <= 0:
        n_microbatches = 2 * n_stages
    return PipelineRegion(
        start=start, end=end, n_stages=n_stages,
        n_microbatches=n_microbatches, n_chunks=1,
        entry_guid=entry, exit_guid=exit_guid,
        template=list(template), template_entry_guid=boundaries[0],
        stage_layer_names=[
            [l.name for l in region[c * unit:(c + 1) * unit]]
            for c in range(reps)],
        counts=tuple(counts),
        prologue=list(prologue or []),
        epilogue=list(epilogue or []),
        prologue_inputs=list(pro_inputs or []),
        epilogue_exit_guid=epi_out)


def assign_tp_roles(template: Sequence[Layer], tp: int
                    ) -> Dict[str, str]:
    """Megatron-style tensor-parallel roles for a stage template:

    - every causal/bidirectional OP_MULTIHEAD_ATTENTION whose head count
      divides by ``tp`` -> "attn" (wq/wk/wv column-split over heads,
      wo row-split, one psum after the output projection);
    - every Linear pair d1 -> d2 where d2 consumes ONLY d1's output,
      d1's output feeds ONLY d2, d2 has no activation, and the shared
      hidden dim (d1's out_dim = d2's contraction dim) divides by
      ``tp`` -> d1 "col", d2 "row" (one psum after d2).

    Returns {} when the template has no tp-able structure (the caller
    treats tp > 1 as an error then). Layers without a role run fully
    replicated over the tp axis — correct for elementwise/norm layers
    whose activations are replicated between the psum points.
    """
    roles: Dict[str, str] = {}
    consumers: Dict[int, List[Layer]] = {}
    for l in template:
        for t in l.inputs:
            consumers.setdefault(t.guid, []).append(l)
    from ..ffconst import ActiMode
    for l in template:
        if l.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
            kvh = l.params.get("num_kv_heads", 0) \
                or l.params["num_heads"]
            if l.params["num_heads"] % tp == 0 and kvh % tp == 0:
                roles[l.name] = "attn"
        elif l.op_type == OperatorType.OP_LINEAR \
                and l.name not in roles:
            out = l.outputs[0]
            cons = consumers.get(out.guid, [])
            if len(cons) == 1 \
                    and cons[0].op_type == OperatorType.OP_LINEAR \
                    and cons[0].name not in roles:
                d2 = cons[0]
                d2_act = d2.params.get("activation", ActiMode.AC_MODE_NONE)
                if (d2.inputs[0].guid == out.guid
                        and d2_act == ActiMode.AC_MODE_NONE
                        and l.params["out_dim"] % tp == 0):
                    roles[l.name] = "col"
                    roles[d2.name] = "row"
    return roles


def find_repeated_run(layers: Sequence[Layer], n_parts: int = 1
                      ) -> Optional[Tuple[int, int, int]]:
    """The maximal verified run of identical consecutive chunks whose
    repeat count is divisible by ``n_parts``. Returns
    ``(total_len, start, unit)`` or None. Shared by the pipeline region
    finder and the block-rematerialization pass."""
    layers = list(layers)
    n = len(layers)
    sigs = [layer_signature(l) for l in layers]
    best: Optional[Tuple[int, int, int]] = None  # (total_len, start, unit)
    for unit in range(1, n // max(n_parts, 2) + 1):
        for start in range(n - unit * 2 + 1):
            # count consecutive repeats of layers[start:start+unit]
            reps = 1
            while True:
                nxt = start + reps * unit
                if nxt + unit > n:
                    break
                if sigs[nxt:nxt + unit] != sigs[start:start + unit]:
                    break
                reps += 1
            reps -= reps % n_parts           # whole chunks only
            if reps >= max(n_parts, 2) and reps * unit > (best or (0,))[0]:
                # verify structure before accepting
                if _verify_run(layers, start, unit, reps):
                    best = (reps * unit, start, unit)
    return best


def chunk_boundaries(layers: Sequence[Layer], start: int, unit: int,
                     reps: int) -> Optional[List[int]]:
    """Entry-tensor guid of each of the ``reps`` unit chunks of the run,
    or None if any boundary crosses more than one tensor. Shared by the
    pipeline region finder and the block-rematerialization pass."""
    layers = list(layers)
    total = reps * unit
    region = layers[start:start + total]
    e0 = _single_crossing(layers[:start] + region, start, start + total)
    if e0 is None:
        return None
    out = [e0]
    for b in range(1, reps):
        g = _single_crossing(region, b * unit, total)
        if g is None:
            return None
        out.append(g)
    return out


def _verify_run(layers: Sequence[Layer], start: int, unit: int,
                reps: int) -> bool:
    """Cheap pre-check that consecutive unit chunks are chainable: each
    chunk's inputs come from itself or the previous chunk's outputs (or
    the tensor entering the first chunk)."""
    region = layers[start:start + unit * reps]
    internal = {t.guid for l in region for t in l.outputs}
    external = set()
    for l in region:
        for t in l.inputs:
            if t.guid not in internal:
                external.add(t.guid)
    return len(external) == 1


# ---------------------------------------------------------------------------
# region-boundary layout transitions (parallel/reshard.py integration)
# ---------------------------------------------------------------------------

def region_entry_transition(x, strategy, entry_t):
    """Explicitly lower the region-entry layout transition.

    The microbatch reshape (``[B,...] -> [M, B/M, ...]``) interleaves
    rows across shards, so a sharded entry activation cannot reach the
    GPipe engine's ``P(None, dp, ...)`` spec by any local reshape —
    GSPMD resolves it with an 'involuntary full rematerialization'
    whose reshape/concat rewrite miscompiles on CPU (NaN in the banked
    composition test). Instead the planner gathers the activation to
    replicated with EXPLICIT collectives (scored steps under a
    shard_map whose in/out specs pin both layouts); the engine's
    ``in_specs`` then slice it locally — the one transition GSPMD
    always gets right. ``FF_NAIVE_RESHARD=1`` restores the bare
    (pre-planner) path."""
    from jax.sharding import PartitionSpec as P
    from .reshard import (naive_reshard, norm_spec, planner_for,
                          tensor_spec)
    if naive_reshard():
        return x
    src = tensor_spec(strategy, entry_t) if entry_t is not None else None
    if src is None or not any(norm_spec(src, len(x.shape))):
        return x
    return planner_for(strategy).apply(x, src, P())


def region_exit_transition(ys, strategy, xs_spec):
    """Explicitly gather the region output (sharded per the engine's
    ``out_specs``) back to replicated before the inverse microbatch
    reshape — the mirror of :func:`region_entry_transition`; the post-
    region layers re-apply their own strategy constraints."""
    from jax.sharding import PartitionSpec as P
    from .reshard import naive_reshard, norm_spec, planner_for
    if naive_reshard():
        return ys
    if not any(norm_spec(xs_spec, ys.ndim)):
        return ys
    return planner_for(strategy).apply(ys, xs_spec, P())
