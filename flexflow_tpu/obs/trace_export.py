"""Chrome trace-event JSON export of the recorded spans.

The output loads in ``chrome://tracing``, Perfetto (ui.perfetto.dev),
and TensorBoard's trace viewer — the same viewers that read the XPlane
traces ``utils/profiling.profile_region`` produces via ``jax.profiler``,
so a host-side span trace and a device-side XLA trace of the same run
can be inspected side by side (they cannot be merged into one file —
XPlane is a different container — but the shared wall-clock makes the
phases line up).

Format: the "JSON Array Format" of the Trace Event spec — one complete
('X') event per span, one instant ('i') event per point event,
process/thread-name metadata ('M') events so multi-rank merges are
readable in Perfetto, and each counter exported as a Chrome 'C' counter
event (its cumulative value, sampled at the trace end) in addition to
the ``otherData`` summary.

Multi-rank: :func:`dump_rank_trace` writes one RAW ring dump per rank
(``.ffcache/trace_rank<r>_epoch<e>.json``) with this rank's clock
anchor from the coordinator's KV handshake
(``resilience.coord.Coordinator.clock_sync``); ``tools/fftrace.py``
merges the dumps into one aligned Chrome trace with world epochs as
lanes.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from . import events as _events


def _meta(pid: int, name: str, value: str, tid: int = 0,
          sort_index: Optional[int] = None) -> List[Dict[str, Any]]:
    out = [{"name": name, "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": value}}]
    if sort_index is not None:
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"sort_index": sort_index}})
    return out


def to_chrome_trace(evts: Optional[Sequence[Dict[str, Any]]] = None,
                    counters: Optional[Dict[str, float]] = None,
                    pid: Optional[int] = None,
                    process_name: Optional[str] = None,
                    sort_index: Optional[int] = None,
                    base: Optional[float] = None) -> Dict[str, Any]:
    """Convert recorded events (default: the live ring) to a Chrome
    trace-event document. Timestamps are rebased to ``base`` (default:
    the earliest event, so the viewer opens at t=0). ``pid`` /
    ``process_name`` / ``sort_index`` label the process lane — the
    multi-rank merger passes the rank/epoch here."""
    if evts is None:
        evts = _events.events()
    if counters is None:
        counters = _events.counters()
    if base is None:
        base = min((e["ts"] for e in evts), default=0.0)
    if pid is None:
        pid = os.getpid()
    out: List[Dict[str, Any]] = []
    out.extend(_meta(pid, "process_name",
                     process_name or f"flexflow pid {pid}",
                     sort_index=sort_index))
    named_tids = set()
    end_us = 0.0
    for e in evts:
        rec: Dict[str, Any] = {
            "name": e["name"],
            "ph": "X" if e["kind"] == "span" else "i",
            "ts": round((e["ts"] - base) * 1e6, 3),
            "pid": pid,
            "tid": e["tid"],
        }
        if e["kind"] == "span":
            rec["dur"] = round(e["dur"] * 1e6, 3)
        else:
            rec["s"] = "t"          # instant scoped to its thread
        if e.get("attrs"):
            rec["args"] = e["attrs"]
        if e["tid"] not in named_tids:
            named_tids.add(e["tid"])
            out.extend(_meta(pid, "thread_name", f"host-{e['tid']}",
                             tid=e["tid"]))
        end_us = max(end_us, rec["ts"] + rec.get("dur", 0.0))
        out.append(rec)
    # counters as Chrome 'C' events: one cumulative sample at the trace
    # end per counter, so merged multi-rank traces show them as tracks
    # in Perfetto instead of burying them in otherData
    for cname in sorted(counters):
        out.append({"name": cname, "ph": "C", "ts": round(end_us, 3),
                    "pid": pid, "args": {"value": counters[cname]}})
    out.extend(_flow_events(evts, pid, base))
    return {"traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"counters": dict(counters),
                          "dropped_events": _events.dropped()}}


def _flow_events(evts: Sequence[Dict[str, Any]], pid: int,
                 base: float) -> List[Dict[str, Any]]:
    """Chrome flow events ('s'/'t'/'f') linking spans that share a
    ``trace`` attribute — a serving request's lifecycle spans land on
    different scheduler threads (HTTP handler, queue worker, decode
    loop), and the flow arrows stitch them into one visible path in
    Perfetto.  Only groups with >= 2 spans get arrows; flow ids reuse
    the trace id string (Chrome accepts string ids)."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for e in evts:
        attrs = e.get("attrs")
        if e["kind"] == "span" and attrs and attrs.get("trace"):
            groups.setdefault(str(attrs["trace"]), []).append(e)
    out: List[Dict[str, Any]] = []
    for tid_key in sorted(groups):
        chain = sorted(groups[tid_key], key=lambda e: e["ts"])
        if len(chain) < 2:
            continue
        for i, e in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            rec: Dict[str, Any] = {
                "name": "request", "cat": "request", "ph": ph,
                "id": tid_key, "pid": pid, "tid": e["tid"],
                "ts": round((e["ts"] - base) * 1e6, 3),
            }
            if ph == "f":
                rec["bp"] = "e"     # bind to the enclosing slice
            out.append(rec)
    return out


def export_chrome_trace(path: str,
                        evts: Optional[Sequence[Dict[str, Any]]] = None
                        ) -> str:
    """Write the Chrome trace JSON to ``path``; returns the path."""
    doc = to_chrome_trace(evts)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# ----------------------------------------------------------------------
# per-rank raw dumps (fftrace merge input)
# ----------------------------------------------------------------------

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

RANK_DUMP_SCHEMA = 1


def rank_trace_path(rank: int, epoch: int,
                    cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or _DEFAULT_DIR,
                        f"trace_rank{rank}_epoch{epoch}.json")


def dump_rank_trace(path: Optional[str] = None,
                    cache_dir: Optional[str] = None) -> Optional[str]:
    """Dump this rank's raw ring (events + counters + drop count) with
    its identity (rank, world epoch) and clock anchor, for the
    ``tools/fftrace.py`` cross-rank merge. The anchor is the
    ``(perf_counter, wall)`` pair sampled at the coordinator's
    epoch-scoped KV barrier release (``Coordinator.clock_sync``) — the
    same physical instant on every rank, which is what lets the merger
    place each rank's monotonic span clocks on one timeline without
    trusting cross-host wall clocks. Returns the path (None on
    failure; dumping telemetry must never kill the training run)."""
    try:
        from ..resilience import status
        world = status.snapshot()
        rank = int(world.get("world_rank") or 0)
        epoch = int(world.get("world_epoch") or 0)
        snap = _events.snapshot()
        doc: Dict[str, Any] = {
            "schema": RANK_DUMP_SCHEMA,
            "rank": rank,
            "world_epoch": epoch,
            "world_size": int(world.get("world_size") or 1),
            "pid": os.getpid(),
            "events": snap["events"],
            "counters": snap["counters"],
            "dropped": snap["dropped"],
        }
        try:
            from ..resilience import coord
            c = coord.get()
            anchor = getattr(c, "clock_anchor", None) \
                if c is not None else None
            if anchor:
                doc["clock"] = dict(anchor)
        except Exception:  # noqa: BLE001 — anchor is best-effort
            pass
        if path is None:
            path = rank_trace_path(rank, epoch, cache_dir)
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        _events.counter("trace.rank_dumps")
        return path
    except Exception:  # noqa: BLE001
        return None


# ----------------------------------------------------------------------
# serving-process raw dumps (fftrace merge input, role="serving")
# ----------------------------------------------------------------------


def serving_trace_path(pid: Optional[int] = None,
                       cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or _DEFAULT_DIR,
                        f"trace_serving_{pid or os.getpid()}.json")


def dump_serving_trace(path: Optional[str] = None,
                       cache_dir: Optional[str] = None) -> Optional[str]:
    """Dump a serving process's raw ring for the ``tools/fftrace.py``
    merge — same schema as the rank dumps but tagged ``role="serving"``
    (no world rank/epoch: serving processes sit outside the training
    world), so one merged Chrome trace can show a request's lifecycle
    spans next to the training lanes.  Returns the path (None on
    failure; dumping telemetry must never kill the server)."""
    try:
        snap = _events.snapshot()
        doc: Dict[str, Any] = {
            "schema": RANK_DUMP_SCHEMA,
            "role": "serving",
            "rank": 0,
            "world_epoch": 0,
            "world_size": 1,
            "pid": os.getpid(),
            "events": snap["events"],
            "counters": snap["counters"],
            "dropped": snap["dropped"],
        }
        if path is None:
            path = serving_trace_path(cache_dir=cache_dir)
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        _events.counter("trace.serving_dumps")
        return path
    except Exception:  # noqa: BLE001
        return None
