"""Chrome trace-event JSON export of the recorded spans.

The output loads in ``chrome://tracing``, Perfetto (ui.perfetto.dev),
and TensorBoard's trace viewer — the same viewers that read the XPlane
traces ``utils/profiling.profile_region`` produces via ``jax.profiler``,
so a host-side span trace and a device-side XLA trace of the same run
can be inspected side by side (they cannot be merged into one file —
XPlane is a different container — but the shared wall-clock makes the
phases line up).

Format: the "JSON Array Format" of the Trace Event spec — one complete
('X') event per span with microsecond timestamps, one instant ('i')
event per point event, counters summarized in ``otherData``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from . import events as _events


def to_chrome_trace(evts: Optional[Sequence[Dict[str, Any]]] = None,
                    counters: Optional[Dict[str, float]] = None
                    ) -> Dict[str, Any]:
    """Convert recorded events (default: the live ring) to a Chrome
    trace-event document. Timestamps are rebased to the earliest event
    so the viewer opens at t=0."""
    if evts is None:
        evts = _events.events()
    if counters is None:
        counters = _events.counters()
    base = min((e["ts"] for e in evts), default=0.0)
    pid = os.getpid()
    out: List[Dict[str, Any]] = []
    for e in evts:
        rec: Dict[str, Any] = {
            "name": e["name"],
            "ph": "X" if e["kind"] == "span" else "i",
            "ts": round((e["ts"] - base) * 1e6, 3),
            "pid": pid,
            "tid": e["tid"],
        }
        if e["kind"] == "span":
            rec["dur"] = round(e["dur"] * 1e6, 3)
        else:
            rec["s"] = "t"          # instant scoped to its thread
        if e.get("attrs"):
            rec["args"] = e["attrs"]
        out.append(rec)
    return {"traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"counters": dict(counters),
                          "dropped_events": _events.dropped()}}


def export_chrome_trace(path: str,
                        evts: Optional[Sequence[Dict[str, Any]]] = None
                        ) -> str:
    """Write the Chrome trace JSON to ``path``; returns the path."""
    doc = to_chrome_trace(evts)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path
