"""Process-wide metrics registry with Prometheus text exposition.

Counters, gauges, and histograms — the serving/executor metrics the
``/metrics`` endpoint scrapes (Prometheus text format 0.0.4, the same
surface Triton's metrics endpoint speaks). Unlike ``obs.events`` this is
always on: the metrics are plain in-process numbers whose update cost is
a dict write under a lock, and serving wants them regardless of whether
span tracing is enabled.

Labels are supported as keyword arguments::

    REGISTRY.counter("ff_requests_total", "Requests").inc(model="bert")
    REGISTRY.histogram("ff_request_latency_seconds",
                       "Latency").observe(0.012, model="bert")

Point-in-time values (queue depths, instance counts) are gauges SET at
scrape time by the ``/metrics`` route handler
(``serving.http_server.render_prometheus``) rather than on every update.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

#: default histogram buckets (seconds) — tuned for request latencies
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: µs–ms-tuned buckets (seconds) for per-token decode-step and prefill
#: latencies: the request-latency defaults start at 1 ms, so µs-scale
#: decode steps all collapse into the first bucket.  Every registration
#: site of ``ff_decode_step_seconds`` / ``ff_prefill_seconds`` must pass
#: this same set (the registry rejects mismatched explicit buckets).
DECODE_STEP_BUCKETS = (1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
                       1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                       0.01, 0.025, 0.05, 0.1, 0.25)


def _labelkey(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()
                ) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(v: str) -> str:
    """Escape a label VALUE per the exposition format: backslash first
    (escaping the escapes we are about to add), then quote, then
    newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """Escape HELP text: only backslash and newline (quotes are legal
    verbatim in HELP lines, unlike in label values)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    # total over floats: a NaN/Inf landing in a metric must render as
    # Prometheus spells them, not raise and kill every future scrape
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(self, name: str, help_: str, kind: str, lock):
        self.name = name
        self.help = help_
        self.kind = kind
        self._lock = lock


class Counter(_Metric):
    def __init__(self, name, help_, lock):
        super().__init__(name, help_, "counter", lock)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labelkey(labels), 0.0)

    def _render(self) -> List[str]:
        # lock held per metric: a scrape racing a first-seen-label inc
        # must not observe the dict mid-insert
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}"
                for k, v in items]


class Gauge(_Metric):
    def __init__(self, name, help_, lock):
        super().__init__(name, help_, "gauge", lock)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_labelkey(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_all(self, rows) -> None:
        """Atomically REPLACE every label row with ``rows`` (iterable of
        ``(labels_dict, value)``) — for gauges re-sampled from live
        state at scrape time (per-model queue depth): rows for unloaded
        models disappear, and a concurrent scrape sees the old or the
        new complete set, never a half-cleared one."""
        new = {_labelkey(lb): float(v) for lb, v in rows}
        with self._lock:
            self._values = new

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labelkey(labels), 0.0)

    def _render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}"
                for k, v in items]


class Histogram(_Metric):
    def __init__(self, name, help_, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, "histogram", lock)
        self.buckets = tuple(sorted(buckets))
        # labelkey -> [per-bucket counts..., +Inf count, sum]
        self._values: Dict[LabelKey, List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _labelkey(labels)
        with self._lock:
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = [0.0] * (len(self.buckets) + 2)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    row[i] += 1
            row[-2] += 1            # +Inf / total count
            row[-1] += value        # sum

    def count(self, **labels) -> float:
        with self._lock:
            row = self._values.get(_labelkey(labels))
            return row[-2] if row else 0.0

    def sum(self, **labels) -> float:
        with self._lock:
            row = self._values.get(_labelkey(labels))
            return row[-1] if row else 0.0

    def _render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            # rows snapshot: an observe() racing the scrape must not
            # mutate a row (or insert a label key) mid-iteration
            items = [(k, list(row))
                     for k, row in sorted(self._values.items())]
        for k, row in items:
            for i, b in enumerate(self.buckets):
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels(k, [('le', _fmt_value(b))])}"
                           f" {_fmt_value(row[i])}")
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(k, [('le', '+Inf')])}"
                       f" {_fmt_value(row[-2])}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} "
                       f"{_fmt_value(row[-1])}")
            out.append(f"{self.name}_count{_fmt_labels(k)} "
                       f"{_fmt_value(row[-2])}")
        return out


class MetricsRegistry:
    """Named metrics, rendered in creation order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, name: str, kind: str, factory) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, "counter",
                         lambda: Counter(name, help_, self._lock))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, "gauge",
                         lambda: Gauge(name, help_, self._lock))

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """``buckets=None`` means "don't care" (DEFAULT_BUCKETS on first
        creation); explicitly passed buckets must MATCH an existing
        registration — silently dropping a mismatched bucket set would
        land observations on wrong boundaries."""
        m = self._get(name, "histogram",
                      lambda: Histogram(name, help_, self._lock,
                                        buckets or DEFAULT_BUCKETS))
        if buckets is not None and tuple(sorted(buckets)) != m.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}, requested {tuple(sorted(buckets))}")
        return m

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._render())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric (tests)."""
        with self._lock:
            self._metrics.clear()


#: process-wide default registry (what ``/metrics`` serves)
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
