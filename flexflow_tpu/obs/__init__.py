"""Unified telemetry: span tracing, metrics, and strategy audit records.

Three pieces, wired through all three execution layers (search,
executor, serving):

  - :mod:`.events` — thread-safe ring-buffered span/counter recorder,
    near-zero-cost when disabled, enabled via ``FF_TRACE=1`` or
    ``FFConfig.trace``;
  - :mod:`.trace_export` — Chrome trace-event JSON export of the
    recorded spans (Perfetto / TensorBoard-viewable, composable with
    the ``jax.profiler`` regions in ``utils/profiling.py``);
  - :mod:`.metrics_registry` — counters/gauges/histograms with
    Prometheus text exposition (served at ``GET /metrics`` by both
    HTTP front-ends);
  - :mod:`.audit` — per-op predicted-cost breakdowns of each search
    adoption (searched vs DP baseline), persisted to
    ``.ffcache/strategy_audit_<hash>.json``.

See docs/observability.md.
"""
from . import events
from .audit import load_strategy_audit, workload_key
from .events import counter, instant, span
from .metrics_registry import REGISTRY, MetricsRegistry, get_registry
from .trace_export import export_chrome_trace, to_chrome_trace

__all__ = ["events", "span", "counter", "instant", "REGISTRY",
           "MetricsRegistry", "get_registry", "to_chrome_trace",
           "export_chrome_trace", "workload_key", "load_strategy_audit"]
