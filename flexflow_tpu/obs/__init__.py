"""Unified telemetry: tracing, metrics, audit records, attribution.

Pieces, wired through all three execution layers (search, executor,
serving) plus the resilience runtime:

  - :mod:`.events` — thread-safe ring-buffered span/counter recorder,
    near-zero-cost when disabled, enabled via ``FF_TRACE=1`` or
    ``FFConfig.trace``;
  - :mod:`.trace_export` — Chrome trace-event JSON export of the
    recorded spans (Perfetto / TensorBoard-viewable) plus the per-rank
    ring dumps ``tools/fftrace.py`` merges across a multi-process
    world;
  - :mod:`.metrics_registry` — counters/gauges/histograms with
    Prometheus text exposition (served at ``GET /metrics`` by both
    HTTP front-ends);
  - :mod:`.audit` — per-op predicted-cost breakdowns of each search
    adoption (searched vs DP baseline), persisted to
    ``.ffcache/strategy_audit_<hash>.json``;
  - :mod:`.attribution` — step-time attribution: measured per-op /
    per-collective costs of the compiled plan, written as the
    ``measured`` side of the audit record (``FF_ATTRIB=1``);
  - :mod:`.drift` — predicted-vs-measured drift detection, attributed
    to the calibration rows that produced the predictions (stale rows
    are re-measured on the next calibration load); the serving variant
    (:func:`drift.serving_drift_report`) closes the same loop for a
    live serving session's per-bucket decode profile;
  - :mod:`.request_trace` — per-request serving lifecycle traces
    (admission → queue → batch → prefill → decode → response), id
    propagated via the ``x-ff-trace-id`` header and linked in the
    Chrome export as flow events;
  - :mod:`.sketch` — mergeable streaming quantile sketches
    (DDSketch-style, relative-error-bounded) backing the serving
    latency quantiles on ``/healthz`` and ``/v2/metrics``;
  - :mod:`.flight` — bounded flight-recorder dumps at failure sites
    (RankFailure, NaN rollback, unhandled crash).

See docs/observability.md.
"""
from . import events
from .audit import load_strategy_audit, workload_key
from .events import counter, instant, span
from .metrics_registry import REGISTRY, MetricsRegistry, get_registry
from .request_trace import TRACE_HEADER, RequestTrace
from .sketch import QuantileSketch
from .trace_export import (dump_rank_trace, dump_serving_trace,
                           export_chrome_trace, to_chrome_trace)

__all__ = ["events", "span", "counter", "instant", "REGISTRY",
           "MetricsRegistry", "get_registry", "to_chrome_trace",
           "export_chrome_trace", "dump_rank_trace",
           "dump_serving_trace", "workload_key",
           "load_strategy_audit", "QuantileSketch", "RequestTrace",
           "TRACE_HEADER"]
