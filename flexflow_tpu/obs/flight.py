"""Flight recorder: a bounded black-box dump at the moment of failure.

When a rank dies — a peer :class:`RankFailure`, a NaN rollback, an
unhandled crash — the evidence is usually gone with the process: the
span ring lived in memory, the counters were never scraped, and the
``WorldSupervisor`` only sees an exit code and a stderr tail. The
flight recorder dumps a BOUNDED record at the failure site:

  - the newest ``max_events`` spans/instants from the ring (tracing
    off = empty list; the record is still written — counters and world
    facts don't need tracing);
  - the span counters and the ring's drop count;
  - the resilience status block (world epoch/rank/size, restart and
    rank-failure tallies — the same facts ``/healthz`` serves);
  - the reason and, when available, the triggering exception.

One file per (rank, world-epoch):
``<repo>/.ffcache/flight_rank<r>_epoch<e>.json`` — a later failure in
the same incarnation overwrites (the newest failure is the one being
debugged), so the cache can never grow unboundedly. The path is
mirrored into ``resilience.status`` (``last_flight_record``) so
``/healthz`` references it, and the ``WorldSupervisor`` attaches the
per-epoch flight files to its per-rank report.

Triggers wired in this PR: ``resilience/coord.py`` (RankFailure
detection), ``resilience/supervisor.py`` (NaN rollback + every restart
recovery), and an optional ``sys.excepthook`` chain for unhandled
crashes (:func:`install_excepthook`, installed by the Supervisor and
the coordinator).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from . import events as obs_events
from .metrics_registry import REGISTRY

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

SCHEMA_VERSION = 1
DEFAULT_MAX_EVENTS = 256

_hook_lock = threading.Lock()
_hook_installed = False


def flight_path(rank: int, epoch: int,
                cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or _DEFAULT_DIR,
                        f"flight_rank{rank}_epoch{epoch}.json")


def flight_record(reason: str, exc: Optional[BaseException] = None,
                  max_events: int = DEFAULT_MAX_EVENTS
                  ) -> Dict[str, Any]:
    """Assemble the bounded record (no I/O)."""
    from ..resilience import status
    snap = obs_events.snapshot(max_events=max_events)
    world = status.snapshot()
    doc: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "reason": reason,
        "pid": os.getpid(),
        "rank": int(world.get("world_rank") or 0),
        "world_epoch": int(world.get("world_epoch") or 0),
        "world_size": int(world.get("world_size") or 1),
        "written_unix_s": time.time(),
        "perf_counter_s": time.perf_counter(),
        "events": snap["events"],
        "counters": snap["counters"],
        "dropped_events": snap["dropped"],
        "world": world,
    }
    if exc is not None:
        doc["exception"] = f"{type(exc).__name__}: {exc}"
    coord = _clock_anchor()
    if coord is not None:
        doc["clock"] = coord
    return doc


def _clock_anchor() -> Optional[Dict[str, Any]]:
    """The coordinator's KV-handshake clock anchor, when one ran —
    lets fftrace place this record's spans on the merged timeline."""
    try:
        from ..resilience import coord
        c = coord.get()
        return getattr(c, "clock_anchor", None) if c is not None else None
    except Exception:  # noqa: BLE001
        return None


def dump_flight_record(reason: str,
                       exc: Optional[BaseException] = None,
                       cache_dir: Optional[str] = None,
                       max_events: int = DEFAULT_MAX_EVENTS,
                       rank: Optional[Any] = None,
                       epoch: Optional[int] = None,
                       extra: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
    """Write the flight record; returns its path (None on any failure —
    a recorder that throws at the failure site would mask the real
    error). Best-effort and re-entrant. ``rank``/``epoch`` override the
    identity (the launcher-side WorldSupervisor records as
    ``rank="launcher"`` so it can never collide with a worker rank's
    file); ``extra`` fields merge into the record."""
    try:
        doc = flight_record(reason, exc=exc, max_events=max_events)
        if rank is not None:
            doc["rank"] = rank
        if epoch is not None:
            doc["world_epoch"] = int(epoch)
        if extra:
            doc.update(extra)
        path = flight_path(doc["rank"], doc["world_epoch"], cache_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        from ..resilience import status
        status.set_value("last_flight_record", path)
        REGISTRY.counter("ff_flight_records_total",
                         "Flight records dumped at failure sites"
                         ).inc(reason=reason)
        obs_events.counter("flight.records")
        return path
    except Exception:  # noqa: BLE001 — never mask the failing path
        return None


def install_excepthook() -> None:
    """Chain a ``sys.excepthook`` that dumps a flight record for
    unhandled crashes before delegating to the previous hook.
    Idempotent; KeyboardInterrupt/SystemExit are not failures."""
    global _hook_installed
    with _hook_lock:
        if _hook_installed:
            return
        prev = sys.excepthook

        def hook(etype, value, tb):
            if not issubclass(etype, (KeyboardInterrupt, SystemExit)):
                dump_flight_record("crash", exc=value)
            prev(etype, value, tb)

        sys.excepthook = hook
        _hook_installed = True
