"""Mergeable streaming quantile sketch (DDSketch-style).

Replaces the bounded latency reservoir in ``serving.scheduler``: a
relative-error quantile estimator over log-spaced buckets, so serving
can report p50/p90/p99/p99.9 per (model, bucket) with *bounded* memory
no matter how many requests flow through — the reservoir's fixed window
forgets history and its percentile error is unbounded under skew.

The sketch guarantees: for any value ``v`` inserted, ``quantile(q)``
returns an estimate within a factor of ``(1 + alpha) / (1 - alpha)`` of
the true q-quantile (relative error ``alpha``, default 1%).  Sketches
with the same ``alpha`` merge exactly (bucket-wise count addition), so
per-bucket sketches can be combined into a per-model aggregate and
scheduler snapshots can be unioned across instances.

Not internally locked — callers (``SchedulerMetrics``) already hold a
lock around every mutation; locking again here would double the cost of
the hot path.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["QuantileSketch"]


class QuantileSketch:
    """Log-bucketed relative-error quantile estimator.

    ``alpha`` is the relative accuracy: quantile estimates are within
    ``alpha`` (to first order) of the true value.  ``max_bins`` bounds
    memory: when exceeded, the *lowest* buckets collapse together (the
    tail — p99 and up — is what serving cares about, so accuracy is
    sacrificed at the floor, never the ceiling).  Values at or below
    ``min_value`` land in a dedicated zero bucket.
    """

    __slots__ = ("alpha", "gamma", "_log_gamma", "max_bins", "min_value",
                 "_bins", "_zero", "count", "total", "min", "max")

    def __init__(self, alpha: float = 0.01, max_bins: int = 2048,
                 min_value: float = 1e-9):
        if not (0.0 < alpha < 1.0):
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        if max_bins < 2:
            raise ValueError(f"max_bins must be >= 2, got {max_bins}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_bins = int(max_bins)
        self.min_value = float(min_value)
        self._bins: Dict[int, int] = {}
        self._zero = 0
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- ingest ---------------------------------------------------------

    def add(self, value: float) -> None:
        v = float(value)
        if v != v:              # NaN: drop rather than poison the sketch
            return
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= self.min_value:
            self._zero += 1
            return
        idx = int(math.ceil(math.log(v) / self._log_gamma))
        self._bins[idx] = self._bins.get(idx, 0) + 1
        if len(self._bins) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        # fold the lowest bucket into its neighbour until under budget —
        # low quantiles blur, the tail stays at full resolution
        keys = sorted(self._bins)
        while len(keys) > self.max_bins:
            lo = keys.pop(0)
            self._bins[keys[0]] = self._bins.get(keys[0], 0) \
                + self._bins.pop(lo)

    # -- query ----------------------------------------------------------

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0, 1]); NaN when empty."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return math.nan
        rank = q * (self.count - 1)
        seen = self._zero
        if rank < seen or not self._bins:
            return 0.0 if self._zero else self.min
        for idx in sorted(self._bins):
            seen += self._bins[idx]
            if rank < seen:
                # midpoint of the bucket's value range in log space
                est = 2.0 * self.gamma ** idx / (self.gamma + 1.0)
                # clamp to observed extremes: bucket midpoints can land
                # just outside [min, max] at the edges
                return min(max(est, self.min), self.max)
        return self.max

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else math.nan

    # -- merge / serialize ----------------------------------------------

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (exact for equal ``alpha``)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} and "
                f"{other.alpha}: bucket boundaries differ")
        for idx, n in other._bins.items():
            self._bins[idx] = self._bins.get(idx, 0) + n
        self._zero += other._zero
        self.count += other.count
        self.total += other.total
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        if len(self._bins) > self.max_bins:
            self._collapse()
        return self

    def copy(self) -> "QuantileSketch":
        s = QuantileSketch(self.alpha, self.max_bins, self.min_value)
        s._bins = dict(self._bins)
        s._zero = self._zero
        s.count = self.count
        s.total = self.total
        s.min = self.min
        s.max = self.max
        return s

    def to_dict(self) -> Dict:
        return {
            "alpha": self.alpha,
            "max_bins": self.max_bins,
            "min_value": self.min_value,
            "zero": self._zero,
            "count": self.count,
            "total": self.total,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "bins": sorted(self._bins.items()),
        }

    @classmethod
    def from_dict(cls, doc: Dict) -> "QuantileSketch":
        s = cls(doc["alpha"], doc["max_bins"], doc["min_value"])
        s._bins = {int(i): int(n) for i, n in doc["bins"]}
        s._zero = int(doc["zero"])
        s.count = int(doc["count"])
        s.total = float(doc["total"])
        s.min = math.inf if doc["min"] is None else float(doc["min"])
        s.max = -math.inf if doc["max"] is None else float(doc["max"])
        return s

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (f"QuantileSketch(alpha={self.alpha}, count={self.count}, "
                f"bins={len(self._bins)})")
