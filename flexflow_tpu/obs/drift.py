"""Cost-model drift detection: predicted vs measured, row by row.

Input: a strategy audit record that carries BOTH a predicted
``adopted`` side (the additive evaluator's per-op breakdown, written at
search time) and a ``measured`` side (the attribution harness',
obs/attribution.py) keyed 1:1 by op name. For every entry and every
component (compute / xfer / sync) the detector computes the
measured/predicted ratio and flags the out-of-band ones — ratio outside
``[1/band, band]`` with at least one side above the noise floor.

Each flagged ratio is **attributed to the calibration row that produced
the prediction**: the evaluator's breakdown path runs with the cost
model's provenance tap installed (``OpCostModel.provenance``), so every
predicted entry carries the ``(backend, dtype, shape-class, axis-size,
tier)`` table keys its pricing consulted. The drift report names them,
``ff_costmodel_drift_total{table}`` counts them, and the keys are
**marked stale** in the calibration sidecar
(``CalibrationTable.mark_stale``) — the next calibration load treats
exactly those rows as misses and re-measures only them, leaving every
healthy row warm. Predictions that never touched a measured table
(analytic roofline, uncalibrated runs) are reported under
``table="analytic"`` and mark nothing.

Knobs: ``FF_DRIFT_BAND`` (default 4.0 — the CPU sim's dispatch jitter
makes tighter bands noisy) and ``FF_DRIFT_MIN_S`` (default 1e-4 s —
entries cheaper than one host dispatch on both sides carry no signal).

Reports land in ``<repo>/.ffcache/drift_report_<workload>.json`` next
to the audit record they were derived from.

The serving variant — :func:`detect_serving_drift` /
:func:`serving_drift_report` — runs the same band logic over a live
``ServingPlanSession``: measured per-bucket prefill / decode-step
latency (the model's always-on decode sink) against the ``serving``
audit block's predicted entries, keyed 1:1 by batch bucket. Each
out-of-band bucket is attributed to the calibration rows its
search-time pricing consulted (the bucket's ``calib`` provenance list)
and those rows are marked stale the same way. Its noise floor is
``FF_SERVING_DRIFT_MIN_S`` (default 1e-6 — whole-bucket latencies, not
single ops).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from . import events as obs_events
from .metrics_registry import REGISTRY

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

SCHEMA_VERSION = 1
DEFAULT_BAND = 4.0
DEFAULT_MIN_SECONDS = 1e-4
#: serving entries are whole prefill/decode-step latencies, not single
#: ops — even a tiny bucket's decode step is micro-seconds, so the
#: serving floor sits far below the per-op one
DEFAULT_SERVING_MIN_SECONDS = 1e-6

#: audit-entry components diffed independently; the provenance ``term``
#: of each calibration row selects which component it explains
_COMPONENTS = ("compute", "xfer", "sync")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def _component(entry: Dict[str, Any], comp: str) -> float:
    if comp == "compute":
        return float(entry.get("fwd_s", 0.0)) \
            + float(entry.get("bwd_s", 0.0))
    return float(entry.get(f"{comp}_s", 0.0))


def drift_report_path(key: str, cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or _DEFAULT_DIR,
                        f"drift_report_{key}.json")


def detect_drift(doc: Dict[str, Any], band: Optional[float] = None,
                 min_s: Optional[float] = None) -> Dict[str, Any]:
    """Diff the ``adopted`` (predicted) and ``measured`` sides of one
    audit record. Pure — no files, no counters; see
    :func:`detect_and_write` for the persisted + metered entry point."""
    band = band if band is not None \
        else _env_float("FF_DRIFT_BAND", DEFAULT_BAND)
    band = max(1.0 + 1e-9, float(band))
    min_s = min_s if min_s is not None \
        else _env_float("FF_DRIFT_MIN_S", DEFAULT_MIN_SECONDS)
    predicted = (doc.get("adopted") or {}).get("per_op") or []
    measured = {e.get("name"): e
                for e in (doc.get("measured") or {}).get("per_op") or []}
    out: List[Dict[str, Any]] = []
    n_compared = 0
    for pe in predicted:
        me = measured.get(pe.get("name"))
        if me is None or not me.get("measured"):
            continue
        prov = pe.get("calib") or []
        for comp in _COMPONENTS:
            if comp == "sync" and not me.get("sync_measured", True):
                # the harness found no mesh-axis group realizing the
                # dp degree, so measured sync is 0 by omission, not by
                # observation — diffing it would stale-mark healthy rows
                continue
            p = _component(pe, comp)
            m = _component(me, comp)
            if p < min_s and m < min_s:
                continue
            n_compared += 1
            ratio = m / max(p, 1e-12)
            if 1.0 / band <= ratio <= band:
                continue
            rows = [r for r in prov if r.get("term") == comp]
            keys = sorted({r["key"] for r in rows if r.get("key")})
            tables = sorted({r.get("table") or "analytic"
                             for r in rows}) or ["analytic"]
            out.append({
                "name": pe.get("name"),
                "op_type": pe.get("op_type"),
                "component": comp,
                "predicted_s": p,
                "measured_s": m,
                "ratio": ratio,
                "tables": tables,
                "calibration_keys": keys,
            })
    # overlap prediction coverage (ISSUE 13): diff the overlap-aware
    # evaluator's predicted EXPOSED comm against the attribution
    # harness's measured exposed-comm entry. Measured is a lower-bound
    # estimator (see attribution._attach_measured_overlap), so only a
    # measured value ABOVE the band flags — a clamped-to-zero measured
    # side must not stale-mark a healthy prediction.
    pred_ov = doc.get("overlap") or {}
    meas_ov = (doc.get("measured") or {}).get("overlap") or {}
    if pred_ov.get("enabled") and "exposed_comm_s" in meas_ov:
        p = float(pred_ov.get("predicted_exposed_s", 0.0) or 0.0)
        m = float(meas_ov.get("exposed_comm_s", 0.0) or 0.0)
        if p >= min_s or m >= min_s:
            n_compared += 1
            ratio = m / max(p, 1e-12)
            if ratio > band:
                out.append({
                    "name": "__overlap__",
                    "op_type": "OVERLAP",
                    "component": "exposed-comm",
                    "predicted_s": p,
                    "measured_s": m,
                    "ratio": ratio,
                    "tables": ["overlap"],
                    "calibration_keys": [],
                })
    stale = sorted({k for e in out for k in e["calibration_keys"]})
    return {
        "schema": SCHEMA_VERSION,
        "workload_key": doc.get("workload_key"),
        "band": band,
        "min_s": min_s,
        "measured_mode": (doc.get("measured") or {}).get("mode"),
        "n_compared": n_compared,
        "n_out_of_band": len(out),
        "out_of_band": out,
        "stale_keys": stale,
    }


def _meter_mark_write(report: Dict[str, Any],
                      cache_dir: Optional[str],
                      mark_stale: bool) -> Optional[str]:
    """Shared back half of both drift entry points: bump the per-table
    drift counters, mark the attributed calibration rows stale, and
    persist the report JSON. Returns the report path (None when the
    write failed)."""
    for e in report["out_of_band"]:
        for table in e["tables"]:
            REGISTRY.counter(
                "ff_costmodel_drift_total",
                "Out-of-band predicted-vs-measured cost entries, by "
                "the calibration table that produced the prediction"
            ).inc(table=table)
        obs_events.counter("drift.out_of_band")
    report["stale_marked"] = 0
    if mark_stale and report["stale_keys"]:
        try:
            from ..search.calibration import CalibrationTable
            report["stale_marked"] = CalibrationTable(
                cache_dir).mark_stale(report["stale_keys"])
            if report["stale_marked"]:
                REGISTRY.counter(
                    "ff_calibration_rows_staled_total",
                    "Calibration rows marked for re-measurement by the "
                    "drift detector").inc(report["stale_marked"])
        except Exception:  # noqa: BLE001 — marking is best-effort
            pass
    report["generated_unix_s"] = time.time()
    key = report.get("workload_key") or "unknown"
    path = drift_report_path(key, cache_dir)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — reporting must never raise
        return None
    return path


def detect_and_write(doc: Dict[str, Any],
                     cache_dir: Optional[str] = None,
                     band: Optional[float] = None,
                     min_s: Optional[float] = None,
                     mark_stale: bool = True) -> Optional[str]:
    """Run the detector, bump ``ff_costmodel_drift_total{table}``, mark
    the attributed calibration rows stale, and persist the drift report
    JSON. Returns the report path (None when the write failed)."""
    t0 = time.perf_counter()
    report = detect_drift(doc, band=band, min_s=min_s)
    path = _meter_mark_write(report, cache_dir, mark_stale)
    if path is None:
        return None
    obs_events.record_span("obs.drift", t0, time.perf_counter() - t0,
                           out_of_band=report["n_out_of_band"],
                           stale=report["stale_marked"])
    return path


#: serving-audit components diffed independently; both are whole-bucket
#: latencies priced by the same calibration rows, so every out-of-band
#: entry attributes the bucket's full ``calib`` row set
_SERVING_COMPONENTS = ("prefill_s", "decode_step_s")


def detect_serving_drift(doc: Dict[str, Any],
                         measured: Dict[str, Dict[str, Any]],
                         band: Optional[float] = None,
                         min_s: Optional[float] = None
                         ) -> Dict[str, Any]:
    """Diff a ``serving`` audit block's predicted per-bucket
    prefill/decode-step latencies against the live session's measured
    profile (:meth:`ServingPlanSession.measured_profile`), keyed 1:1 by
    batch bucket. Each out-of-band ratio is attributed to the exact
    calibration rows the bucket's search-time pricing consulted (the
    bucket's ``calib`` provenance list, recorded by the serving
    evaluator's tap). Pure — no files, no counters; see
    :func:`serving_drift_report` for the persisted + metered entry
    point. Buckets never served (absent from ``measured``) are skipped:
    no observation, no signal."""
    band = band if band is not None \
        else _env_float("FF_DRIFT_BAND", DEFAULT_BAND)
    band = max(1.0 + 1e-9, float(band))
    min_s = min_s if min_s is not None \
        else _env_float("FF_SERVING_DRIFT_MIN_S",
                        DEFAULT_SERVING_MIN_SECONDS)
    buckets = (doc.get("serving") or {}).get("buckets") or {}
    out: List[Dict[str, Any]] = []
    n_compared = 0
    for bkey in sorted(buckets, key=lambda k: int(k)):
        pb = buckets[bkey]
        mb = measured.get(str(bkey))
        if not mb:
            continue
        prov = pb.get("calib") or []
        keys = sorted({r["key"] for r in prov if r.get("key")})
        tables = sorted({r.get("table") or "analytic"
                         for r in prov}) or ["analytic"]
        for comp in _SERVING_COMPONENTS:
            p = float(pb.get(comp) or 0.0)
            m = float(mb.get(comp) or 0.0)
            if p < min_s and m < min_s:
                continue
            n_compared += 1
            ratio = m / max(p, 1e-12)
            if 1.0 / band <= ratio <= band:
                continue
            out.append({
                "name": f"bucket[{bkey}]",
                "bucket": int(bkey),
                "component": comp,
                "predicted_s": p,
                "measured_s": m,
                "ratio": ratio,
                "n_samples": int(mb.get("n", 0) or 0),
                "tables": tables,
                "calibration_keys": keys,
            })
    stale = sorted({k for e in out for k in e["calibration_keys"]})
    return {
        "schema": SCHEMA_VERSION,
        "kind": "serving",
        "workload_key": doc.get("workload_key"),
        "band": band,
        "min_s": min_s,
        "n_compared": n_compared,
        "n_out_of_band": len(out),
        "out_of_band": out,
        "stale_keys": stale,
    }


def serving_drift_report(session,
                         audit_path: Optional[str] = None,
                         cache_dir: Optional[str] = None,
                         band: Optional[float] = None,
                         min_s: Optional[float] = None,
                         mark_stale: bool = True) -> Optional[str]:
    """Close the serving re-plan loop for one live
    ``ServingPlanSession``: read its strategy-audit record (the
    ``serving`` block written at plan-search time), annotate it with the
    measured per-bucket profile (``serving_measured``, keyed 1:1 to the
    predicted entries), run :func:`detect_serving_drift`, bump the drift
    counters, mark the attributed calibration rows stale, and persist
    the report next to the audit. Returns the report path — None when
    there is no audit record, nothing was measured yet, or the write
    failed."""
    t0 = time.perf_counter()
    if audit_path is None:
        audit_path = getattr(getattr(session, "ff", None),
                             "_strategy_audit_path", None)
    if not audit_path or not os.path.exists(audit_path):
        return None
    try:
        with open(audit_path) as f:
            doc = json.load(f)
    except Exception:  # noqa: BLE001 — reporting must never raise
        return None
    measured = session.measured_profile()
    if not measured:
        return None
    try:
        from .audit import annotate_strategy_audit
        annotate_strategy_audit(audit_path,
                                {"serving_measured": {"buckets": measured}})
    except Exception:  # noqa: BLE001 — annotation is best-effort
        pass
    report = detect_serving_drift(doc, measured, band=band, min_s=min_s)
    path = _meter_mark_write(report, cache_dir, mark_stale)
    if path is None:
        return None
    obs_events.record_span("obs.serving_drift", t0,
                           time.perf_counter() - t0,
                           out_of_band=report["n_out_of_band"],
                           stale=report["stale_marked"])
    return path


def load_drift_report(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
