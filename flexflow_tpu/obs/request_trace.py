"""Per-request lifecycle tracing for the serving path.

Every serving request gets a trace id — propagated from the
``x-ff-trace-id`` HTTP header when the client supplies one, generated
otherwise — and each lifecycle stage (admission, queue wait, batch
assembly, prefill, per-segment decode, response) is emitted as a span in
the ``obs.events`` ring carrying a ``trace=<id>`` attribute.  Spans from
different scheduler threads thus link into one logical request in the
Chrome trace (``obs.trace_export`` emits flow events between them), and
the response span records the request's terminal outcome:

    ok | expired | deadline-rejected | breaker | rejected |
    invalid | failed

Cost discipline matches ``obs.events``: when tracing is disabled
(``FF_TRACE`` unset) ``start()``/``from_headers()`` return ``None`` and
every call site is a single ``is None`` check — no ids are generated,
no spans recorded.

The *ambient* trace (``activate``/``current``) is a thread-local: the
HTTP front activates the request's trace for the duration of the route
handler so deep layers (``model._generate_kv``'s prefill/decode spans,
``serving.session``'s segmented decode) can tag their spans with the
trace id without threading a handle through every signature.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, Optional

from . import events as obs_events

__all__ = ["TRACE_HEADER", "RequestTrace", "start", "from_headers",
           "new_trace_id", "activate", "current", "current_id"]

#: request/response header carrying the trace id (lowercase: both HTTP
#: fronts normalize header names before routing)
TRACE_HEADER = "x-ff-trace-id"

#: terminal outcomes a request.response span may carry
OUTCOMES = ("ok", "expired", "deadline-rejected", "breaker", "rejected",
            "invalid", "failed")

_local = threading.local()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class RequestTrace:
    """Handle for one request's linked span chain.

    ``stage()`` records an intermediate lifecycle span; ``finish()``
    records the terminal ``request.response`` span exactly once — the
    first caller's outcome wins, so the scheduler's precise verdict
    (set before the waiter wakes) beats the HTTP layer's coarse
    status-code mapping.
    """

    __slots__ = ("trace_id", "model", "t0", "_finished")

    def __init__(self, trace_id: str, model: str = ""):
        self.trace_id = trace_id
        self.model = model
        self.t0 = time.perf_counter()
        # one-shot latch; written without a lock: finishers are ordered
        # by the request event (scheduler sets outcome before event.set,
        # the HTTP thread finishes after event.wait returns) and a
        # double-record on a true race is a duplicate span, not
        # corruption  # ffcheck: ok(guarded-field)
        self._finished = False

    def stage(self, name: str, t0: float, dur: Optional[float] = None,
              **attrs) -> None:
        """Record lifecycle span ``request.<name>`` for this trace."""
        if dur is None:
            dur = time.perf_counter() - t0
        obs_events.record_span("request." + name, t0, dur,
                               trace=self.trace_id, model=self.model,
                               **attrs)

    def finish(self, outcome: str, t0: Optional[float] = None,
               **attrs) -> None:
        """Record the terminal response span (idempotent)."""
        if self._finished:
            return
        self._finished = True
        start_ = self.t0 if t0 is None else t0
        obs_events.record_span("request.response", start_,
                               time.perf_counter() - start_,
                               trace=self.trace_id, model=self.model,
                               outcome=outcome, **attrs)

    def __repr__(self) -> str:
        return f"RequestTrace({self.trace_id!r}, model={self.model!r})"


def start(model: str = "",
          trace_id: Optional[str] = None) -> Optional[RequestTrace]:
    """New trace handle, or ``None`` when tracing is disabled."""
    if not obs_events.enabled():
        return None
    return RequestTrace(trace_id or new_trace_id(), model)


def from_headers(headers: Optional[Dict[str, str]],
                 model: str = "") -> Optional[RequestTrace]:
    """Trace handle honoring a client-supplied ``x-ff-trace-id``.

    ``headers`` keys must already be lowercased (both HTTP fronts
    normalize before routing).  A blank/absent header generates an id.
    """
    if not obs_events.enabled():
        return None
    tid = (headers or {}).get(TRACE_HEADER, "").strip()
    # bound the id: a hostile client must not bloat every span's attrs
    if tid and len(tid) > 64:
        tid = tid[:64]
    return RequestTrace(tid or new_trace_id(), model)


class activate:
    """Context manager installing ``trace`` as the thread's ambient
    trace for the duration (``trace=None`` is a no-op, so call sites
    don't branch on the disabled path)."""

    __slots__ = ("_trace", "_prev")

    def __init__(self, trace: Optional[RequestTrace]):
        self._trace = trace
        self._prev = None

    def __enter__(self):
        if self._trace is not None:
            self._prev = getattr(_local, "trace", None)
            _local.trace = self._trace
        return self._trace

    def __exit__(self, *exc):
        if self._trace is not None:
            _local.trace = self._prev
        return False


def current() -> Optional[RequestTrace]:
    """The thread's ambient trace (``None`` outside ``activate``)."""
    return getattr(_local, "trace", None)


def current_id() -> Optional[str]:
    """Ambient trace id, for tagging spans recorded by deep layers."""
    t = getattr(_local, "trace", None)
    return t.trace_id if t is not None else None
