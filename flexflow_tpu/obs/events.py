"""Span/counter event recorder — the host-side half of the telemetry
layer (the device-side half is ``jax.profiler`` via
``utils/profiling.profile_region``; the two compose — a ``span`` brackets
host phases like "unity.dp", the XLA trace shows what the devices did
inside it).

Design constraints (ISSUE 2 tentpole):

  - **near-zero cost when disabled**: every public entry point is one
    module-global flag check; ``span`` is a ``__slots__`` class-based
    context manager (no generator machinery), so a disabled span costs
    two attribute reads and a branch — hot loops like
    ``OpCostModel.op_cost`` (1e4–1e6 calls per search) can call
    ``counter()`` unconditionally;
  - **thread-safe**: search, executor, and serving record concurrently
    (one lock around the ring + counters; the enabled check is a benign
    race — an event straddling enable/disable may be dropped, never
    corrupted);
  - **bounded**: completed spans land in a ring buffer of ``capacity``
    events — the newest N survive, wraparound drops the oldest (a
    long-running server cannot grow without bound).

Enabling: ``FF_TRACE=1`` in the environment (read at import), or
``FFConfig.trace = "true"`` (applied by ``FFModel.compile`` via
:func:`configure`), or :func:`enable` directly.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 65536

_lock = threading.Lock()
_enabled = False
_capacity = DEFAULT_CAPACITY
_ring: List[Dict[str, Any]] = []
_head = 0                         # index of the OLDEST event once full
_dropped = 0                      # events overwritten by wraparound
_counters: Dict[str, float] = {}


def _env_on(val: Optional[str]) -> bool:
    return (val or "").lower() in ("1", "true", "yes", "on")


def enabled() -> bool:
    """Fast global check — the only cost telemetry pays when off."""
    # benign race by design (module docstring): a single-flag read with
    # no invariant tied to other state; locking here would put a lock
    # on every op_cost call
    return _enabled  # ffcheck: ok(guarded-field)


def enable(capacity: Optional[int] = None) -> None:
    global _enabled, _capacity
    with _lock:
        if capacity is not None and capacity > 0 \
                and capacity != _capacity:
            _capacity = capacity
            _reset_locked()
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def _reset_locked() -> None:
    global _head, _dropped
    _ring.clear()
    _head = 0
    _dropped = 0
    _counters.clear()


def clear() -> None:
    """Drop every recorded event and counter (capacity/enabled kept)."""
    with _lock:
        _reset_locked()


def configure(cfg) -> None:
    """Apply an ``FFConfig``: ``trace`` "true"/"false" forces the
    PROCESS-WIDE recorder state — there is one recorder per process, so
    compiling a model with ``trace="false"`` switches tracing off for
    everything else in the process too (that is what ``--no-trace``
    means; use the default "auto" to leave other models' tracing alone);
    "auto" (the default) leaves the FF_TRACE / explicit-enable decision
    untouched — except that a non-empty ``trace_export_file`` implies
    tracing (requesting an export of an empty trace is never what the
    caller meant; the ``--trace-export`` flag applies the same rule),
    and so does an enabled attribution harness (``FF_ATTRIB`` /
    ``FFConfig.attribution``): the measured side it produces lands in
    the strategy audit record, which only exists when tracing is on."""
    mode = str(getattr(cfg, "trace", "auto") or "auto").lower()
    if mode in ("false", "off", "0", "no"):
        disable()
        return
    attrib = False
    if getattr(cfg, "attribution", None) is not None:
        from . import attribution as _attrib
        attrib = _attrib.attribution_enabled(cfg)
    if _env_on(mode) or mode == "true" \
            or getattr(cfg, "trace_export_file", "") or attrib:
        enable()


def counter(name: str, n: float = 1) -> None:
    """Increment a named counter (no-op when disabled)."""
    # benign race: disabled fast path (see enabled())
    if not _enabled:  # ffcheck: ok(guarded-field)
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counters() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


_drop_counter = None


def _count_drop() -> None:
    """Mirror ring-wraparound drops into the always-on Prometheus
    registry (``ff_trace_events_dropped_total``): overflow used to be
    silent — invisible unless someone compared ``dropped()`` by hand.
    Only runs when an event is actually overwritten, so the disabled
    path and the non-full ring pay nothing."""
    global _drop_counter
    if _drop_counter is None:
        from .metrics_registry import REGISTRY
        _drop_counter = REGISTRY.counter(
            "ff_trace_events_dropped_total",
            "Trace events lost to ring-buffer wraparound")
    _drop_counter.inc()


def _record(ev: Dict[str, Any]) -> None:
    global _head, _dropped
    with _lock:
        if len(_ring) < _capacity:
            _ring.append(ev)
        else:
            _ring[_head] = ev
            _head = (_head + 1) % _capacity
            _dropped += 1
            _count_drop()


def record_span(name: str, t0: float, dur: float, **attrs) -> None:
    """Record one completed span explicitly (``t0`` from
    ``time.perf_counter()``). Used where a ``with`` block would force
    reindenting a long phase — e.g. ``FFModel.compile``."""
    # benign race: disabled fast path (see enabled())
    if not _enabled:  # ffcheck: ok(guarded-field)
        return
    _record({"name": name, "kind": "span", "ts": t0, "dur": dur,
             "tid": threading.get_ident(),
             "attrs": attrs or None})


def instant(name: str, **attrs) -> None:
    """Record a point-in-time event (e.g. a recompile trigger)."""
    # benign race: disabled fast path (see enabled())
    if not _enabled:  # ffcheck: ok(guarded-field)
        return
    _record({"name": name, "kind": "instant",
             "ts": time.perf_counter(), "dur": 0.0,
             "tid": threading.get_ident(),
             "attrs": attrs or None})


class span:
    """``with span("unity.dp", depth=2): ...`` — records one completed
    span on exit. Nesting is recovered from timing containment (the
    Chrome trace viewer does this natively for same-thread 'X' events).
    Disabled cost: one flag check on enter and one on exit."""

    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "span":
        # benign race: disabled fast path (see enabled())
        self._t0 = time.perf_counter() if _enabled else None  # ffcheck: ok(guarded-field)
        return self

    def set(self, **attrs) -> "span":
        """Attach attributes discovered mid-span (e.g. the batch size a
        request was assembled into, known only after the body ran)."""
        self.attrs.update(attrs)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t0 = self._t0
        # benign race: a span straddling enable/disable may be dropped,
        # never corrupted (module docstring)
        if t0 is not None and _enabled:  # ffcheck: ok(guarded-field)
            record_span(self.name, t0, time.perf_counter() - t0,
                        **self.attrs)
        return False


def events() -> List[Dict[str, Any]]:
    """Snapshot of recorded events, oldest first."""
    with _lock:
        return _ring[_head:] + _ring[:_head]


def dropped() -> int:
    """Events lost to ring wraparound since the last clear()."""
    with _lock:
        return _dropped


def snapshot(max_events: Optional[int] = None) -> Dict[str, Any]:
    """One consistent view of the recorder — events (newest
    ``max_events`` when bounded), counters, and the drop count — for
    the per-rank trace dumps and the flight recorder."""
    with _lock:
        evts = _ring[_head:] + _ring[:_head]
        ctrs = dict(_counters)
        drops = _dropped
    if max_events is not None and max_events >= 0:
        # NOT evts[-max_events:]: a 0 bound means "no spans", while
        # [-0:] would return the ENTIRE ring
        evts = evts[-max_events:] if max_events else []
    return {"events": evts, "counters": ctrs, "dropped": drops}


# FF_TRACE honored at import so serving entry points (which never see an
# FFConfig) are covered too
if _env_on(os.environ.get("FF_TRACE")):
    _enabled = True
