"""Strategy audit records: per-decision cost breakdowns of a search.

A fidelity number like ``virtual_fidelity_spearman`` (ROADMAP: 0.64–0.71
after PR 1) is a single scalar over many (workload, ranker) rows — when
it regresses there is nothing to diff. The audit record persists, per
search, the **per-op predicted cost breakdown of the adopted strategy
AND of the DP baseline** (both priced by the additive evaluator, whose
per-op terms sum exactly to its graph total), so a regression can be
chased decision-by-decision: which op's predicted compute/xfer/sync
moved, and on which side of the searched-vs-DP comparison.

Records land in ``<repo>/.ffcache/strategy_audit_<hash>.json`` next to
the op-cost and calibration caches; ``<hash>`` is a structural workload
key (op types, names, shapes), so re-searching the same model
overwrites its record and different models never collide. The measured
DP-floor guard appends its timings to the same record when it runs —
predicted and measured sides of one adoption in one file.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, Optional, Sequence

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".ffcache")

SCHEMA_VERSION = 1


def workload_key(layers: Sequence, n_devices: int = 0) -> str:
    """Structural hash of the layer graph (op types, names, shapes) +
    device count: stable across processes, distinct across models."""
    h = hashlib.sha1()
    h.update(str(n_devices).encode())
    for l in layers:
        h.update(str((getattr(l.op_type, "name", l.op_type), l.name,
                      tuple(tuple(t.shape) for t in l.inputs),
                      tuple(tuple(t.shape) for t in l.outputs))
                     ).encode())
    return h.hexdigest()[:12]


def audit_path(key: str, cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or _DEFAULT_DIR,
                        f"strategy_audit_{key}.json")


def side_record(entries: Sequence[Dict[str, Any]], total_s: float
                ) -> Dict[str, Any]:
    """One side (adopted / dp_baseline) of the audit: per-op entries +
    the evaluator total they sum to."""
    return {
        "total_s": total_s,
        "compute_s": sum(e.get("fwd_s", 0.0) + e.get("bwd_s", 0.0)
                         for e in entries),
        "xfer_s": sum(e.get("xfer_s", 0.0) for e in entries),
        "sync_s": sum(e.get("sync_s", 0.0) for e in entries),
        "per_op": list(entries),
    }


def write_strategy_audit(record: Dict[str, Any], key: str,
                         cache_dir: Optional[str] = None
                         ) -> Optional[str]:
    """Persist one audit record (atomic rename; best-effort — an audit
    write must never kill a compile). Returns the path, or None."""
    path = audit_path(key, cache_dir)
    doc = dict(record, schema=SCHEMA_VERSION, workload_key=key,
               written_unix_s=time.time())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — audit is best-effort telemetry
        return None


def annotate_strategy_audit(path: str, extra: Dict[str, Any]) -> None:
    """Merge extra fields (e.g. the floor guard's measured timings) into
    an existing record; best-effort."""
    try:
        with open(path) as f:
            doc = json.load(f)
        doc.update(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001
        pass


def load_strategy_audit(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
