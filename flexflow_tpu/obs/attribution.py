"""Step-time attribution: measured per-op costs for the audit record.

The strategy audit record (:mod:`.audit`) carries two PREDICTED sides —
``adopted`` and ``dp_baseline`` — priced by the additive evaluator whose
entries sum exactly to its graph total. Nothing in the runtime ever
closed the loop: calibration rows go stale silently, and every fidelity
question ("is the cost model still right on THIS machine?") needs a
hand-run A/B. This module is the closing half (the simulator-calibration
loop of arXiv 2110.10548, which A/Bs predicted reduction trees against
measured collectives): profile a few steady-state steps of the compiled
plan and write a ``measured`` side into the same record, keyed 1:1 to
the predicted entries, so :mod:`.drift` can diff them row by row.

Two measurement modes:

  - **spans** (the CPU-sim fallback, and the default everywhere the
    XPlane toolchain is absent): the executor's program is re-run as
    instrumented sub-steps — one jitted ``fwd+bwd`` per op (with the
    strategy's sharding constraints applied, so collectives execute),
    one timed gradient-sync collective per weighted op, one timed
    optimizer update — each bracketed by a host timer with a device
    sync. The per-entry times cover the instrumented step end to end,
    so their sum tracks the instrumented step's wall time by
    construction (pinned by test). A separate timing of the REAL
    compiled step is recorded as ``jit_step_wall_s`` — the fused
    executable is faster than the sub-step decomposition (XLA fuses
    across ops; each sub-step pays its own dispatch), and both numbers
    matter: per-op ratios for drift, the fused wall for throughput.
  - **xplane** (real accelerators): run the steps under
    ``jax.profiler.trace`` and parse the XPlane protobuf when the
    profiler toolchain is importable; falls back to **spans** otherwise.
  - **coarse** (pipelined regions): the per-op decomposition cannot
    thread a GPipe region's stacked params, so only the compiled-step
    wall is measured and the per-op entries are marked unmeasured.

Enabling: ``FF_ATTRIB=1`` or ``FFConfig.attribution = "true"``
(``--attribution``); either implies tracing (the audit record only
exists when tracing is on). The harness runs ONCE, after ``fit``
completes — it adds zero work to the training step itself. Profiling
runs on deep copies of params/optimizer state with a synthetic batch,
so the trained model is never mutated.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from . import audit as obs_audit
from . import events as obs_events

#: entries below this predicted+measured floor are dispatch noise on the
#: CPU sim; drift skips them (see obs/drift.py)
DEFAULT_STEPS = 3


def attribution_enabled(cfg=None) -> bool:
    """Resolve the opt-in: config "true"/"false" wins; "auto" (and no
    config at all) honors the FF_ATTRIB env var."""
    mode = str(getattr(cfg, "attribution", "auto") or "auto").lower()
    if mode in ("true", "on", "1", "yes"):
        return True
    if mode in ("false", "off", "0", "no"):
        return False
    return os.environ.get("FF_ATTRIB", "").lower() \
        in ("1", "true", "yes", "on")


def attribution_steps(cfg=None) -> int:
    try:
        return max(1, int(os.environ["FF_ATTRIB_STEPS"]))
    except (KeyError, ValueError):
        pass
    return max(1, int(getattr(cfg, "attribution_steps", DEFAULT_STEPS)
                      or DEFAULT_STEPS))


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------

def _sync(x) -> float:
    """Device→host fetch as the sync barrier (block_until_ready does not
    block on tunneled backends — same convention as calibration.py)."""
    import numpy as np
    return float(np.asarray(x).ravel()[0])


def _bytes_of_spec(w) -> int:
    import numpy as np
    from ..dtypes import itemsize
    return int(np.prod(w.shape)) * itemsize(w.dtype)


def _weight_degree(strategy, lname: str, wname: str,
                   axis_sizes: Dict[str, int]) -> int:
    """Shard degree of one weight under the strategy (product of mesh
    axis sizes its PartitionSpec consumes)."""
    try:
        sh = strategy.weight_sharding(lname, wname)
    except Exception:  # noqa: BLE001 — missing specs mean replicated
        return 1
    spec = getattr(sh, "spec", None)
    if spec is None:
        return 1
    deg = 1
    for part in spec:
        if part is None:
            continue
        names = part if isinstance(part, tuple) else (part,)
        for a in names:
            deg *= axis_sizes.get(a, 1)
    return max(1, deg)


def _degradation(tiers) -> float:
    """Max active chaos-drill link slowdown over a sync group's tiers
    (resilience/faults.py ``degrade_link``): the virtual mesh cannot
    physically slow the modeled link, so the measured sync wall time is
    scaled instead — the drift detector then sees exactly what a real
    degraded fabric would show it."""
    try:
        from ..parallel.topology import link_degradation_factor
        return max([link_degradation_factor(t) for t in tiers] or [1.0])
    except Exception:  # noqa: BLE001 — no drill machinery = healthy
        return 1.0


def _axes_for_degree(axis_sizes: Dict[str, int], deg: int
                     ) -> Optional[Tuple[str, ...]]:
    """A contiguous mesh-axis run whose sizes multiply to ``deg`` —
    the group the measured grad-sync proxy collective runs over.
    Suffix runs are tried first (grad sync lives on the leftover inner
    axes under the tier-aware allocator)."""
    names = list(axis_sizes)
    starts = list(range(len(names) - 1, -1, -1))
    for i in starts:
        p = 1
        for j in range(i, len(names)):
            p *= axis_sizes[names[j]]
            if p == deg:
                return tuple(names[i:j + 1])
            if p > deg:
                break
    return None


# ----------------------------------------------------------------------
# instrumented sub-step measurement (the spans mode)
# ----------------------------------------------------------------------

class _SubStepHarness:
    """Per-op jitted callables over the executor's program, threaded
    through a shared env exactly like ``GraphProgram.emit`` — but one
    XLA executable per op, so each op's forward+backward (collectives
    included, via the strategy's sharding constraints) is individually
    timeable with a host clock."""

    def __init__(self, ff):
        import jax
        self.ff = ff
        self.ex = ff.executor
        self.program = self.ex.program
        self.strategy = ff.strategy
        self.dmesh = ff.dmesh
        self.rngs = self.ex._rngs_for_step(0)
        self._fns: Dict[str, Any] = {}
        self._fwd_fns: Dict[str, Any] = {}
        self._sync_fns: Dict[Tuple, Any] = {}
        self._jax = jax

    def _ctx(self):
        from ..ops import EmitCtx
        return EmitCtx(training=True, rngs=self.rngs,
                       state=self.ff.state or {}, config=self.ff.config)

    def _constrain(self, layer, i, o):
        from ..parallel import reshard as reshard_mod
        if self.strategy is None or not hasattr(o, "ndim"):
            return o
        sh = self.strategy.output_sharding(layer.name, i)
        if sh is None:
            return o
        return reshard_mod.constrain_output(o, sh, self.strategy, layer)

    def _emit(self, layer, ins, w):
        from ..ops import get_op_def
        op = get_op_def(layer.op_type)
        outs = op.emit(layer.params, list(ins), w, self._ctx(), layer.name)
        return [self._constrain(layer, i, o) for i, o in enumerate(outs)]

    def fwd_fn(self, layer):
        """jitted ``(ins, w) -> (outs, probe_scalar)``."""
        fn = self._fwd_fns.get(layer.name)
        if fn is None:
            import jax.numpy as jnp

            def fwd(ins, w):
                outs = self._emit(layer, ins, w)
                probe = sum((jnp.sum(o.astype(jnp.float32))
                             for o in outs if hasattr(o, "astype")),
                            jnp.float32(0.0))
                return outs, probe

            fn = self._fwd_fns[layer.name] = self._jax.jit(fwd)
        return fn

    def fwdbwd_fn(self, layer, float_idx: List[int], has_w: bool):
        """jitted ``(ins, w) -> (outs, gradsum)``: forward plus the
        gradients w.r.t. float inputs and weights — the per-op analog of
        ``OpCostModel.measure``'s fwd+bwd body, at GLOBAL shapes with
        the strategy's shardings (so tp/dp collectives execute)."""
        if not float_idx and not has_w:
            return self.fwd_fn(layer)
        fn = self._fns.get(layer.name)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp

            def fwdbwd(ins, w):
                def loss(w_, fins):
                    full = list(ins)
                    for i, a in zip(float_idx, fins):
                        full[i] = a
                    outs = self._emit(layer, full, w_)
                    s = sum((jnp.sum(o.astype(jnp.float32))
                             for o in outs if hasattr(o, "astype")),
                            jnp.float32(0.0))
                    return s, outs
                (_, outs), g = jax.value_and_grad(
                    loss, argnums=(0, 1), has_aux=True)(
                        w, [ins[i] for i in float_idx])
                gsum = jax.tree_util.tree_reduce(
                    lambda acc, x: acc + jnp.sum(x.astype(jnp.float32)),
                    g, jnp.float32(0.0))
                return outs, gsum

            fn = self._fns[layer.name] = self._jax.jit(fwdbwd)
        return fn

    def sync_fn(self, dp_deg: int, n_elems: int):
        """jitted grad-sync proxy: one all-reduce of ``n_elems`` f32
        over a mesh-axis group of degree ``dp_deg`` — what XLA lowers
        the weight-gradient sync of one op to (the combiner-coalesced
        step pays it fewer times; per-op timing is the attribution
        grain, matching the predicted entries)."""
        key = (dp_deg, n_elems)
        fn = self._sync_fns.get(key)
        if fn is not None:
            return fn
        axes = _axes_for_degree(dict(self.dmesh.axis_sizes), dp_deg)
        if axes is None:
            self._sync_fns[key] = None
            return None
        jax = self._jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ..utils.jax_compat import shard_map
        mesh = self.dmesh.mesh
        all_axes = tuple(mesh.axis_names)

        def body(x):
            return jnp.sum(jax.lax.psum(x, axes))[None]

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P(),
                              out_specs=P(all_axes)))
        x = jnp.ones((max(8, n_elems),), jnp.float32)
        # the tier names this sync group spans: measured wall times are
        # scaled by any active degrade_link drill on them at ACCRUAL
        # time (the drill may fire mid-run, after this fn is built) —
        # the CPU-sim mesh has no physical link to slow
        try:
            tiers = frozenset(dict(self.dmesh.axis_tiers).get(a, "ici")
                              for a in axes)
        except Exception:  # noqa: BLE001 — untrier'd mesh
            tiers = frozenset()
        fn = self._sync_fns[key] = (f, x, tiers)
        return fn


def _measure_spans(ff, steps: int, predicted: List[Dict[str, Any]]
                   ) -> Dict[str, Any]:
    """The instrumented sub-step measurement. Returns the measured side
    (``mode="spans"``)."""
    import jax.numpy as jnp
    import numpy as np
    from ..ffconst import PARALLEL_OPS
    from ..search.optimizer import _synth_batch
    from ..search.calibration import shape_class

    h = _SubStepHarness(ff)
    program = h.program
    batch = _synth_batch(ff)
    pred_set = {e["name"] for e in predicted}
    n_dev = ff.dmesh.num_devices
    axis_sizes = dict(ff.dmesh.axis_sizes)

    # ---- per-layer plan: callables, weights, sync payloads ----
    # EVERY program layer runs (downstream ops read their outputs from
    # the shared env — input/no-op passthroughs included); only the
    # layers present in the predicted breakdown get entries, the rest
    # fold into unattributed_s
    plan = []
    for layer in program.layers:
        w = ff.params.get(layer.name, {}) if ff.params else {}
        sync_spec = None
        if layer.weights:
            wbytes = sum(_bytes_of_spec(s) for s in layer.weights)
            wdeg = max((_weight_degree(ff.strategy, layer.name, s.name,
                                       axis_sizes)
                        for s in layer.weights), default=1)
            dp_deg = max(1, n_dev // max(wdeg, 1))
            if dp_deg > 1 and wbytes > 0:
                # bucket payloads by shape class so the jit count stays
                # bounded on deep towers of same-sized layers
                n_elems = max(8, shape_class(wbytes // max(wdeg, 1)) // 4)
                sync_spec = (dp_deg, n_elems)
        plan.append({"layer": layer, "w": w, "sync": sync_spec})

    # ---- warmup + fwd/bwd split probe (compiles excluded from steps) --
    env = program.init_env(batch)
    frac = {}
    for item in plan:
        layer = item["layer"]
        ins = [env[t.guid] for t in layer.inputs]
        float_idx = [i for i, a in enumerate(ins)
                     if hasattr(a, "dtype")
                     and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)]
        item["float_idx"] = float_idx
        fb = h.fwdbwd_fn(layer, float_idx, bool(item["w"]))
        item["fn"] = fb
        outs, g = fb(ins, item["w"])      # compile
        _sync(g)
        fwd = h.fwd_fn(layer)
        o2, p = fwd(ins, item["w"])       # compile
        _sync(p)
        t_f, t_fb = [], []
        for _ in range(2):
            t0 = time.perf_counter()
            _, p = fwd(ins, item["w"])
            _sync(p)
            t_f.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            outs, g = fb(ins, item["w"])
            _sync(g)
            t_fb.append(time.perf_counter() - t0)
        tf, tfb = min(t_f), max(min(t_fb), 1e-9)
        frac[layer.name] = min(1.0, max(0.05, tf / tfb))
        for o, t in zip(outs, layer.outputs):
            env[t.guid] = o
        if item["sync"] is not None:
            fx = h.sync_fn(*item["sync"])
            if fx is not None:
                _sync(fx[0](fx[1]))       # compile
            item["sync_fn"] = fx
            # wanted but no mesh-axis group realizes the dp degree:
            # the entry must say so, or a predicted-nonzero vs
            # measured-zero sync would read as (phantom) drift
            item["sync_unmeasured"] = fx is None

    # optimizer update (timed once per step, zero grads — placement and
    # math are what cost, not the values)
    g0 = h._jax.tree.map(jnp.zeros_like, ff.params)
    upd = h._jax.jit(
        lambda p, g, o: ff.optimizer.update(p, g, o, 1))
    p2, o2 = upd(ff.params, g0, ff.opt_state)   # compile; discard
    h._jax.block_until_ready(o2)

    # ---- K measured steps ----
    acc: Dict[str, Dict[str, float]] = {
        item["layer"].name: {"t": 0.0, "sync": 0.0} for item in plan}
    unattributed = 0.0
    update_s = 0.0
    walls = []
    for _ in range(steps):
        env = program.init_env(batch)
        t_step0 = time.perf_counter()
        for item in plan:
            layer = item["layer"]
            ins = [env[t.guid] for t in layer.inputs]
            t0 = time.perf_counter()
            outs, g = item["fn"](ins, item["w"])
            _sync(g)
            dt = time.perf_counter() - t0
            acc[layer.name]["t"] += dt
            for o, t in zip(outs, layer.outputs):
                env[t.guid] = o
            fx = item.get("sync_fn")
            if fx is not None:
                t0 = time.perf_counter()
                _sync(fx[0](fx[1]))
                acc[layer.name]["sync"] += \
                    (time.perf_counter() - t0) * _degradation(fx[2])
        t0 = time.perf_counter()
        p2, o2 = upd(ff.params, g0, ff.opt_state)
        h._jax.block_until_ready(o2)
        update_s += time.perf_counter() - t0
        walls.append(time.perf_counter() - t_step0)

    # ---- aggregate, keyed 1:1 to the predicted entries ----
    by_name = {}
    for item in plan:
        layer = item["layer"]
        t = acc[layer.name]["t"] / steps
        sync = acc[layer.name]["sync"] / steps
        if layer.name not in pred_set:
            unattributed += t + sync
            continue
        is_par = layer.op_type in PARALLEL_OPS
        f = frac.get(layer.name, 0.5)
        by_name[layer.name] = {
            "name": layer.name,
            "op_type": getattr(layer.op_type, "name", str(layer.op_type)),
            "fwd_s": 0.0 if is_par else t * f,
            "bwd_s": 0.0 if is_par else t * (1.0 - f),
            "xfer_s": t if is_par else 0.0,
            "sync_s": sync,
            "total_s": t + sync,
            "measured": True,
            "sync_measured": not item.get("sync_unmeasured", False),
        }
    entries = []
    for e in predicted:
        m = by_name.get(e["name"])
        if m is None:
            m = {"name": e["name"], "op_type": e.get("op_type", ""),
                 "fwd_s": 0.0, "bwd_s": 0.0, "xfer_s": 0.0,
                 "sync_s": 0.0, "total_s": 0.0, "measured": False}
        entries.append(m)
    total = sum(e["total_s"] for e in entries)
    return {
        "mode": "spans",
        "n_steps": steps,
        "step_wall_s": float(np.mean(walls)),
        "update_s": update_s / steps,
        "unattributed_s": unattributed,
        "total_s": total,
        "compute_s": sum(e["fwd_s"] + e["bwd_s"] for e in entries),
        "xfer_s": sum(e["xfer_s"] for e in entries),
        "sync_s": sum(e["sync_s"] for e in entries),
        "per_op": entries,
    }


# ----------------------------------------------------------------------
# compiled-step wall (all modes) + coarse fallback
# ----------------------------------------------------------------------

def _time_compiled_step(ff, steps: int) -> Optional[float]:
    """Mean steady wall of the REAL compiled train step, on deep copies
    (the step donates its inputs; the trained model must not move)."""
    import jax
    import jax.numpy as jnp
    from ..search.optimizer import _synth_batch
    try:
        step = ff.executor.make_train_step()
        cp = jax.tree.map(jnp.array, (ff.params, ff.opt_state, ff.state))
        p, o, s = cp
        batch = _synth_batch(ff)
        p, o, s, bm = step(p, o, s, jnp.int32(0), batch)  # compile+warm
        _sync(bm["loss"])
        ts = []
        for i in range(steps):
            t0 = time.perf_counter()
            p, o, s, bm = step(p, o, s, jnp.int32(i + 1), batch)
            _sync(bm["loss"])
            ts.append(time.perf_counter() - t0)
        return sum(ts) / len(ts)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return None


def _measure_coarse(ff, steps: int, predicted: List[Dict[str, Any]]
                    ) -> Dict[str, Any]:
    wall = _time_compiled_step(ff, steps)
    entries = [{"name": e["name"], "op_type": e.get("op_type", ""),
                "fwd_s": 0.0, "bwd_s": 0.0, "xfer_s": 0.0, "sync_s": 0.0,
                "total_s": 0.0, "measured": False} for e in predicted]
    return {"mode": "coarse", "n_steps": steps,
            "step_wall_s": wall, "total_s": 0.0,
            "compute_s": 0.0, "xfer_s": 0.0, "sync_s": 0.0,
            "per_op": entries}


# ----------------------------------------------------------------------
# XPlane mode (real accelerators; falls back when unparseable)
# ----------------------------------------------------------------------

def _measure_xplane(ff, steps: int, predicted: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Profile K compiled steps under ``jax.profiler.trace`` and parse
    the XPlane output. Returns None whenever the backend is the CPU sim
    (its XPlane has no device lanes worth attributing) or the profiler
    protobuf toolchain is not importable — the caller falls back to the
    instrumented spans mode, which works everywhere."""
    import jax
    if jax.default_backend() == "cpu":
        return None
    try:  # the parse toolchain is optional by design
        from tensorflow.core.profiler.protobuf import (  # noqa: F401
            xplane_pb2)
    except Exception:  # noqa: BLE001
        return None
    import glob
    import tempfile
    import jax.numpy as jnp
    from ..search.optimizer import _synth_batch
    try:
        step = ff.executor.make_train_step()
        cp = jax.tree.map(jnp.array, (ff.params, ff.opt_state, ff.state))
        p, o, s = cp
        batch = _synth_batch(ff)
        p, o, s, bm = step(p, o, s, jnp.int32(0), batch)
        _sync(bm["loss"])
        tmp = tempfile.mkdtemp(prefix="ff_attrib_xplane_")
        with jax.profiler.trace(tmp):
            for i in range(steps):
                p, o, s, bm = step(p, o, s, jnp.int32(i + 1), batch)
                _sync(bm["loss"])
        pbs = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"),
                        recursive=True)
        if not pbs:
            return None
        # per-op lane attribution from XPlane requires the full
        # tensorboard profiler converter; until a real-pod run wires it
        # (ROADMAP: real-pod validation), record the artifact path and
        # let the spans mode supply the per-op side
        side = _measure_spans(ff, steps, predicted)
        side["xplane_path"] = pbs[0]
        return side
    except Exception:  # noqa: BLE001
        return None


# ----------------------------------------------------------------------
# measured exposed-comm entry (overlap prediction coverage)
# ----------------------------------------------------------------------

def _attach_measured_overlap(side: Dict[str, Any]) -> None:
    """Attach the measured ``overlap`` block to the measured side so
    :mod:`.drift` can diff the overlap-aware evaluator's predicted
    exposed comm against reality (ISSUE 13: drift detection covers the
    overlap prediction, not just per-op costs).

    Estimator: ``exposed_comm_s = max(0, fused step wall − measured
    compute − optimizer update)`` — the step time the compute terms
    cannot account for, i.e. communication left on the critical path.
    The spans mode's per-op compute carries its own dispatch overhead,
    so this is a LOWER bound on exposed comm (it can clamp to 0 on the
    CPU sim); the drift band absorbs the bias, and the per-op
    ``sync_s`` entries record the SERIALIZED comm cost next to it.
    Also bumps ``ff_comm_exposed_s_total{side="measured"}``."""
    try:
        wall = side.get("jit_step_wall_s")
        if wall is None:
            return
        compute = float(side.get("compute_s", 0.0) or 0.0)
        update = float(side.get("update_s", 0.0) or 0.0)
        exposed = max(0.0, float(wall) - compute - update)
        side["overlap"] = {
            "exposed_comm_s": exposed,
            "comm_serial_s": float(side.get("sync_s", 0.0) or 0.0)
            + float(side.get("xfer_s", 0.0) or 0.0),
            "estimator": "step_wall_minus_compute",
        }
        from .metrics_registry import REGISTRY
        REGISTRY.counter(
            "ff_comm_exposed_s_total",
            "Communication seconds exposed on the step critical path"
        ).inc(exposed, side="measured")
        # hidden = serialized comm the step wall did not pay — like the
        # predicted side, an ALL-communication quantity (the counter
        # help says so); xfer and sync are not separable in the wall
        hidden = max(0.0, side["overlap"]["comm_serial_s"] - exposed)
        side["overlap"]["hidden_comm_s"] = hidden
        REGISTRY.counter(
            "ff_comm_overlap_hidden_s_total",
            "Communication seconds hidden behind backward compute "
            "(overlap-aware scoring)").inc(hidden, side="measured")
    except Exception:  # noqa: BLE001 — the entry is best-effort
        pass


# ----------------------------------------------------------------------
# entry point
# ----------------------------------------------------------------------

def run_attribution(ff, steps: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
    """Profile the compiled plan and write the ``measured`` side into
    the model's strategy audit record, then run the drift detector over
    the predicted/measured pair. Best-effort: returns the measured side,
    or None when there is no audit record to attribute against (e.g.
    ``--only-data-parallel`` skips the search audit entirely)."""
    import logging
    log = logging.getLogger("flexflow_tpu")
    path = getattr(ff, "_strategy_audit_path", None)
    if not path or not os.path.exists(path):
        log.info("attribution: no strategy audit record for this "
                 "compile (searchless path?) — skipping")
        return None
    if ff.executor is None or ff.params is None:
        return None
    try:
        doc = obs_audit.load_strategy_audit(path)
    except Exception:  # noqa: BLE001
        return None
    predicted = (doc.get("adopted") or {}).get("per_op") or []
    if not predicted:
        return None
    steps = steps if steps is not None else attribution_steps(ff.config)
    t0 = time.perf_counter()
    try:
        side = _measure_xplane(ff, steps, predicted)
        if side is None:
            # pipelined regions and device-subset groups stack member
            # weights under group keys the per-layer decomposition
            # cannot address — coarse (compiled-step-wall-only) mode
            grouped = (ff.executor.pipe is not None
                       or bool(getattr(ff.strategy, "banks", None))
                       or bool(getattr(ff.strategy, "place_groups",
                                       None)))
            if grouped:
                side = _measure_coarse(ff, steps, predicted)
            else:
                side = _measure_spans(ff, steps, predicted)
        side["jit_step_wall_s"] = _time_compiled_step(ff, steps)
    except Exception as e:  # noqa: BLE001 — must never kill training
        log.warning("attribution harness failed: %r", e)
        obs_events.counter("attribution.failures")
        return None
    _attach_measured_overlap(side)
    side["duration_s"] = round(time.perf_counter() - t0, 6)
    side["written_unix_s"] = time.time()
    obs_audit.annotate_strategy_audit(path, {"measured": side})
    obs_events.record_span("obs.attribution", t0,
                           time.perf_counter() - t0, mode=side["mode"],
                           steps=steps)
    obs_events.counter("attribution.runs")
    from .metrics_registry import REGISTRY
    REGISTRY.counter("ff_attribution_runs_total",
                     "Step-time attribution harness runs").inc(
                         mode=side["mode"])
    # drift detection over the freshly measured pair
    try:
        from . import drift as obs_drift
        doc = dict(doc, measured=side)
        report_path = obs_drift.detect_and_write(doc)
        if report_path:
            obs_audit.annotate_strategy_audit(
                path, {"drift_report": report_path})
    except Exception as e:  # noqa: BLE001
        log.warning("drift detection failed: %r", e)
    return side
