"""Resilient training driver: bounded restarts, exact resume, rollback.

``FFModel.fit`` assumes the process, the data pipeline, and the machine
survive the whole run; :class:`Supervisor` drops that assumption. It
drives the same per-step machinery (``ff._run_train_step`` over a
``SingleDataLoader``) inside a recovery loop:

  - **auto-resume**: on start, the newest *valid* checkpoint in the
    directory is restored — model state via the re-placing
    ``restore_model_checkpoint`` path, dataloader position (rng state,
    epoch, batch index) from the checkpoint metadata — so a resumed run
    replays the exact remaining batches;
  - **bounded restarts**: any step failure (a real exception or an
    injected :class:`~flexflow_tpu.resilience.faults.SimulatedCrash`)
    consumes one unit of the restart budget, sleeps an exponential
    backoff with jitter, restores, and continues; budget exhausted →
    the last error propagates;
  - **NaN rollback**: a non-finite loss never reaches a checkpoint —
    the step is detected before the periodic save, the run rolls back
    to the last good checkpoint, and the rollback is counted;
  - **elastic re-plan**: an injected (or detected)
    :class:`~flexflow_tpu.resilience.faults.DeviceLoss` triggers
    :func:`~flexflow_tpu.resilience.elastic.replan_on_device_loss` —
    re-search on the shrunken mesh, reshard the restored state, rebuild
    the loader on the new strategy — and training continues.

Checkpoints are the hardened atomic kind (``runtime/checkpoint.py``);
``async_save=True`` overlaps the file writes with the next train steps.
Everything reports into ``obs``: restart/rollback counters, a
time-since-last-checkpoint gauge, save/restore spans, and the always-on
:mod:`.status` block that ``/healthz`` serves.
"""
from __future__ import annotations

import logging
import os
import random
import time
from typing import Dict, List, Optional

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from ..runtime.metrics_buffer import MetricsBuffer, NonFiniteMetrics
from . import status
from .coord import EXIT_RANK_FAILURE, RankFailure
from .faults import DeviceLoss, SimulatedCrash  # noqa: F401 (re-export)

log = logging.getLogger("flexflow_tpu")


class RestartBudgetExceeded(RuntimeError):
    """The supervisor ran out of restarts; the cause is ``__cause__``."""


# the NaN-rollback trigger now carries the first bad step index found by
# the deferred flush (runtime/metrics_buffer.py); old alias kept
_NonFiniteLoss = NonFiniteMetrics


class Supervisor:
    """Wraps a compiled :class:`FFModel` in a crash/corruption/device-loss
    tolerant train loop. See the module docstring for semantics.

    ``checkpoint_every`` is in optimizer steps; ``max_restarts`` bounds
    recoveries of EVERY kind (crash, NaN rollback, device loss) across
    the whole run."""

    def __init__(self, ff, directory: str, *,
                 checkpoint_every: int = 1, max_to_keep: int = 3,
                 max_restarts: int = 3, backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, backoff_jitter: float = 0.25,
                 async_save: bool = False, elastic: bool = True,
                 verbose: bool = False):
        from ..runtime.checkpoint import CheckpointManager
        self.ff = ff
        self.directory = directory
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.async_save = async_save
        self.elastic = elastic
        self.verbose = verbose
        self.restarts = 0
        self.nan_rollbacks = 0
        self.elastic_replans = 0
        self._mgr = CheckpointManager(directory, max_to_keep=max_to_keep,
                                      async_save=async_save)
        self._since_ckpt = 0
        self._last_save_t: Optional[float] = None
        self._run_args: Optional[tuple] = None
        self._nan_steps: set = set()
        # live deferred-metrics buffer while _run_epoch is driving
        # steps; _save flushes + NaN-screens through it so a poisoned
        # state can never reach a checkpoint (the PR-3 invariant under
        # async dispatch)
        self._buffer: Optional[MetricsBuffer] = None

    # ------------------------------------------------------------------
    def run(self, x=None, y=None, epochs: Optional[int] = None,
            batch_size: Optional[int] = None, shuffle: bool = True,
            callbacks=None) -> List[Dict[str, float]]:
        """Train to completion (the resilient ``fit``); returns the
        per-epoch history. Resumes automatically from the newest valid
        checkpoint in ``directory`` when one exists. ``callbacks`` get
        the same per-epoch ``on_epoch_end(epoch, logs, model)`` contract
        as ``fit`` (a stop request ends the run after the epoch's
        checkpoint)."""
        ff = self.ff
        if ff.executor is None:
            raise ValueError("call compile() first")
        from ..obs import flight
        flight.install_excepthook()  # unhandled crash -> flight record
        epochs = epochs or ff.config.epochs
        self._run_args = (x, y, batch_size, shuffle)
        loader = ff._combined_loader(x, y, batch_size, shuffle=shuffle)
        if not self._try_resume(loader):
            loader.reset()
            loader.epoch = 0
            self._save(loader)  # step-0 restore point: recovery always
            #                     has somewhere to land, even pre-ckpt-1
        history: List[Dict[str, float]] = []
        while loader.epoch < epochs:
            try:
                rep = self._run_epoch(loader)
                epoch_done = loader.epoch
                loader.epoch += 1
                if loader.epoch < epochs:
                    loader.reset()
                # epoch-boundary save so a later resume lands in the
                # right epoch with the fresh shuffle order; history is
                # appended only AFTER it succeeds — a failed save
                # triggers recovery, which replays the tail and must
                # not find the epoch already recorded
                self._save(loader)
                if rep is not None:
                    history.append(rep)
                    if callbacks:
                        # same contract as fit(); runs after the
                        # boundary save so a callback crash never
                        # loses the epoch
                        stop = False
                        for cb in callbacks:
                            cb.on_epoch_end(epoch_done, rep, ff)
                            stop = stop or getattr(cb, "stop_requested",
                                                   False)
                        if stop:
                            break
            except NonFiniteMetrics as e:
                if e.step in self._nan_steps:
                    # the rollback replays the exact same batch into the
                    # exact same params (that is what makes injected-
                    # fault recovery deterministic) — so a GENUINE
                    # divergence recurs identically; fail now instead of
                    # burning the remaining budget on doomed replays
                    raise RestartBudgetExceeded(
                        f"non-finite loss at step {e.step} recurred "
                        f"after rollback (deterministic divergence, not "
                        f"a transient)") from e
                self._nan_steps.add(e.step)
                self.nan_rollbacks += 1
                status.record("nan_rollbacks")
                from ..obs import flight
                flight.dump_flight_record("nan_rollback", exc=e)
                self._recover(loader, reason="nan_loss", err=e)
            except DeviceLoss as e:
                loader = self._recover_device_loss(loader, e)
            except RankFailure:
                # a dead PEER rank: no in-process restore can reform the
                # world (every restore is a collective with the corpse).
                # Propagate so the world supervisor can relaunch/shrink;
                # the restart budget is for THIS process's failures.
                raise
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — that's the job
                self._recover(loader, reason=type(e).__name__, err=e)
        self._mgr.wait(timeout_s=self._mgr.WAIT_TIMEOUT_S)
        ff._current_metrics = history[-1] if history else {}
        if getattr(ff.config, "trace_export_file", ""):
            # same end-of-training export hook as fit()
            from ..obs.trace_export import export_chrome_trace
            if obs_events.enabled():
                export_chrome_trace(ff.config.trace_export_file)
        ff._end_of_training_telemetry()   # attribution + rank dump
        return history

    # ------------------------------------------------------------------
    def _run_epoch(self, loader) -> Optional[Dict[str, float]]:
        from ..runtime.metrics import PerfMetrics
        ff = self.ff
        step_fn = ff.executor.make_train_step()
        pm = PerfMetrics()
        buf = MetricsBuffer.for_config(ff.config, pm=pm)
        self._buffer = buf
        ff._metrics_buffer = buf  # ff.save_checkpoint screens through it
        t0 = time.perf_counter()
        nb = 0
        try:
            while True:
                batch = loader.next_batch()
                if batch is None:
                    break
                bm = ff._run_train_step(step_fn, batch)
                bsz = next(iter(batch.values())).shape[0]
                # deferred accumulation: metrics stay on device; the
                # NaN screen is the fused all_finite flag checked at
                # flush points (every save below, print_freq, epoch
                # end). In sync-every-step mode the push flushes
                # immediately — old-loop semantics, but each metric is
                # converted exactly once (one device_get per step, no
                # float(np.asarray(loss)) + second np.asarray sweep).
                buf.push(ff._step - 1, bm, bsz)
                buf.raise_if_poisoned()
                nb += 1
                # dynamic recompilation hook — same contract as fit()
                # (model.py: reference RecompileState, model.cc:2422)
                rs = getattr(ff, "_recompile_state", None)
                if rs is not None and rs.step(ff):
                    step_fn = ff.executor.make_train_step()
                self._since_ckpt += 1
                if self._since_ckpt >= self.checkpoint_every:
                    # _save flushes + screens the pending window first
                    self._save(loader)
                self._update_ckpt_age_gauge()
                pf = ff.config.print_freq
                if self.verbose and pf > 0 and nb % pf == 0:
                    buf.flush()
                    buf.raise_if_poisoned()
                    rep = pm.report()
                    msg = " ".join(f"{k}={v:.4f}" for k, v in rep.items())
                    print(f"epoch {loader.epoch} iter {nb}/"
                          f"{loader.num_batches} {msg}")
            buf.flush()
            buf.raise_if_poisoned()
        finally:
            self._buffer = None
            ff._metrics_buffer = None
        if nb == 0:
            # resumed from a checkpoint taken at the epoch's last batch
            # (killed before the boundary save overwrote it): nothing
            # left to run — report None so a metric-less {} never lands
            # in history (consumers index history[-1]["loss"])
            return None
        dt = time.perf_counter() - t0
        rep = pm.report()
        rep["epoch_time_s"] = dt
        rep["samples_per_sec"] = pm.train_all / dt if dt > 0 else 0.0
        obs_events.record_span("supervisor.epoch", t0, dt,
                               epoch=loader.epoch, batches=nb)
        REGISTRY.gauge(
            "ff_train_samples_per_sec",
            "Training throughput of the last completed epoch"
        ).set(rep["samples_per_sec"])
        return rep

    # ------------------------------------------------------------------
    def _save(self, loader) -> None:
        from ..runtime.checkpoint import save_model_checkpoint
        if self._buffer is not None:
            # the deferred NaN screen ALWAYS runs immediately before a
            # checkpoint save: flush the in-flight window and raise on
            # the first non-finite step — the rollback happens INSTEAD
            # of the save, so a poisoned state never lands on disk
            self._buffer.flush()
            self._buffer.raise_if_poisoned()
        t0 = time.perf_counter()
        save_model_checkpoint(
            self.ff, self.directory, manager=self._mgr,
            extra_metadata={"loader": loader.state_dict(),
                            "supervisor": {"restarts": self.restarts}},
            blocking=not self.async_save)
        self._since_ckpt = 0
        self._last_save_t = time.monotonic()
        self._update_ckpt_age_gauge()
        obs_events.record_span("supervisor.save", t0,
                               time.perf_counter() - t0,
                               step=self.ff._step,
                               blocking=not self.async_save)

    def _try_resume(self, loader) -> bool:
        if self._mgr.latest_step() is None:
            return False
        try:
            self._restore(loader)
        except FileNotFoundError:
            return False  # every step corrupt: start fresh
        log.info("supervisor: resumed from checkpoint step %d "
                 "(epoch %d, batch %d)", self.ff._step, loader.epoch,
                 loader.idx)
        return True

    def _restore(self, loader) -> None:
        from ..runtime.checkpoint import restore_model_checkpoint
        self._mgr.wait(timeout_s=self._mgr.WAIT_TIMEOUT_S)
        step, meta = restore_model_checkpoint(self.ff, self.directory,
                                              with_meta=True)
        ld = meta.get("loader")
        if ld is not None:
            loader.load_state_dict(ld)
        else:
            loader.reset()
        self._since_ckpt = 0

    # ------------------------------------------------------------------
    def _consume_restart(self, reason: str, err: BaseException) -> None:
        self.restarts += 1
        status.record("restarts")
        REGISTRY.counter("ff_resilience_restarts_total",
                         "Supervisor recoveries, any cause"
                         ).inc(reason=reason)
        obs_events.counter("resilience.restart")
        obs_events.instant("resilience.restart", reason=reason,
                           step=self.ff._step, attempt=self.restarts)
        if self.restarts > self.max_restarts:
            raise RestartBudgetExceeded(
                f"restart budget ({self.max_restarts}) exhausted; "
                f"last failure: {reason}: {err}") from err
        log.warning("supervisor: recovering from %s at step %d "
                    "(restart %d/%d): %s", reason, self.ff._step,
                    self.restarts, self.max_restarts, err)

    def _backoff(self) -> None:
        delay = min(self.backoff_max_s,
                    self.backoff_base_s * (2 ** (self.restarts - 1)))
        delay *= 1.0 + self.backoff_jitter * random.random()
        time.sleep(delay)

    def _recover(self, loader, reason: str, err: BaseException) -> None:
        self._consume_restart(reason, err)
        self._backoff()
        self._restore(loader)

    def _recover_device_loss(self, loader, err: DeviceLoss):
        """Elastic path: re-plan the strategy for the shrunken mesh,
        reshard the restored state onto it, rebuild the loader (its
        shardings reference the dead mesh), and resume in place."""
        if not self.elastic:
            raise err
        self._consume_restart("device_loss", err)
        self._backoff()
        from .elastic import replan_on_device_loss
        self._mgr.wait(timeout_s=self._mgr.WAIT_TIMEOUT_S)
        replan_on_device_loss(self.ff, err.n_lost)
        self.elastic_replans += 1
        x, y, batch_size, shuffle = self._run_args
        new_loader = self.ff._combined_loader(x, y, batch_size,
                                              shuffle=shuffle)
        new_loader.epoch = loader.epoch
        self._restore(new_loader)
        return new_loader

    # ------------------------------------------------------------------
    def _update_ckpt_age_gauge(self) -> None:
        if self._last_save_t is not None:
            REGISTRY.gauge(
                "ff_time_since_last_checkpoint_seconds",
                "Age of the newest completed checkpoint"
            ).set(time.monotonic() - self._last_save_t)


def run_world_member(fn, *args, **kwargs):
    """Run a worker-main under world-supervision exit semantics: a
    :class:`~flexflow_tpu.resilience.coord.RankFailure` (a dead PEER)
    exits with :data:`EXIT_RANK_FAILURE` so the
    :class:`WorldSupervisor` can tell "I detected a corpse" apart from
    "I am the corpse". Every other exception propagates normally."""
    try:
        return fn(*args, **kwargs)
    except RankFailure as e:
        log.error("world member exiting for re-formation: %s", e)
        # os._exit, not sys.exit: the process may hold wedged device
        # state; skip atexit/XLA teardown that could hang the exit
        os._exit(EXIT_RANK_FAILURE)


class WorldFailure(RuntimeError):
    """The world could not be re-formed within the restart/shrink
    policy; per-rank exit details ride in ``.report``."""

    def __init__(self, msg: str, report=None):
        super().__init__(msg)
        self.report = report or []


class WorldSupervisor:
    """Launcher-side supervisor of an N-process jax.distributed world —
    the cross-process half of the resilience story (ISSUE 7; the
    per-process :class:`Supervisor` handles everything that does not
    kill a rank).

    Workers detect a dead peer via ``resilience/coord.py`` (missed
    heartbeats, bounded barriers) and exit ``EXIT_RANK_FAILURE``; dead
    ranks just die (or hang and are killed here). On any failed epoch
    the WorldSupervisor kills the remnants, bumps the **world epoch**,
    and re-forms the world at a fresh coordinator port:

      - while the restart budget lasts: **relaunch** at full size — the
        dead rank comes back and every rank resumes bit-exact from the
        last committed multi-host checkpoint step (quorum restore);
      - budget exhausted (or ``policy="shrink"``): **shrink** — drop to
        the largest batch-divisible world below the current size and
        keep going; the restored state reshards onto the smaller world
        through the reshard planner's ``place_host`` path exactly like
        the in-process elastic re-plan.

    ``worker_cmd`` is either a callable ``(rank, nprocs, port, epoch)
    -> argv list`` or an argv template whose ``{rank}``/``{nprocs}``/
    ``{port}``/``{epoch}`` placeholders are substituted. Workers
    inherit the environment plus the ``FF_*`` world variables
    (coordinator address, process id/count, world epoch,
    ``FF_WORLD_SUPERVISED=1``).

    Every wait is bounded: a world that neither finishes nor fails
    within ``world_timeout_s`` is killed and treated as failed
    (unattributed hang)."""

    def __init__(self, worker_cmd, nprocs: int, *,
                 max_world_restarts: int = 1, policy: str = "auto",
                 min_world: int = 1, batch_size: int = 0,
                 devices_per_rank: int = 1,
                 world_timeout_s: float = 300.0,
                 poll_interval_s: float = 0.1, env=None):
        if policy not in ("auto", "relaunch", "shrink"):
            raise ValueError(f"policy must be 'auto', 'relaunch', or "
                             f"'shrink', got {policy!r}")
        self.worker_cmd = worker_cmd
        self.nprocs = int(nprocs)
        self.max_world_restarts = max_world_restarts
        self.policy = policy
        self.min_world = max(1, min_world)
        self.batch_size = batch_size
        self.devices_per_rank = max(1, devices_per_rank)
        self.world_timeout_s = world_timeout_s
        self.poll_interval_s = poll_interval_s
        self.env = dict(env) if env else None
        self.epoch = int(os.environ.get("FF_WORLD_EPOCH", "0"))
        self.world_restarts = 0
        self.shrinks = 0
        self.report: List[Dict] = []

    # -- helpers -------------------------------------------------------
    def _argv(self, rank: int, port: int) -> List[str]:
        if callable(self.worker_cmd):
            return list(self.worker_cmd(rank, self.nprocs, port,
                                        self.epoch))
        subst = {"{rank}": str(rank), "{nprocs}": str(self.nprocs),
                 "{port}": str(port), "{epoch}": str(self.epoch)}
        out = []
        for a in self.worker_cmd:
            for k, v in subst.items():  # embedded forms too: --rank={rank}
                a = a.replace(k, v)
            out.append(a)
        return out

    @staticmethod
    def _free_port() -> int:
        import socket
        with socket.socket() as s:
            s.bind(("localhost", 0))
            return s.getsockname()[1]

    # -- one epoch -----------------------------------------------------
    def _launch_epoch(self) -> List[Dict]:
        """Spawn the world, wait bounded, reap everything; returns the
        per-rank records (rank, rc, out, err)."""
        import signal
        import subprocess
        import tempfile
        port = self._free_port()
        base_env = dict(os.environ)
        if self.env:
            base_env.update(self.env)
        procs = []
        deadline = time.monotonic() + self.world_timeout_s
        try:
            # spawning INSIDE the try: a Popen failure on a later rank
            # (EMFILE, bad argv) must still reap the ranks already
            # launched — they would otherwise block in rendezvous forever
            for r in range(self.nprocs):
                env = dict(base_env)
                env.update({
                    "FF_COORDINATOR_ADDRESS": f"localhost:{port}",
                    "FF_NUM_PROCESSES": str(self.nprocs),
                    "FF_PROCESS_ID": str(r),
                    "FF_WORLD_EPOCH": str(self.epoch),
                    "FF_WORLD_SUPERVISED": "1",
                })
                # files, not pipes: a chatty worker must never deadlock
                # the launcher on a full pipe while we wait on a sibling
                out_f = tempfile.TemporaryFile(mode="w+")
                err_f = tempfile.TemporaryFile(mode="w+")
                p = subprocess.Popen(self._argv(r, port), env=env,
                                     stdout=out_f, stderr=err_f,
                                     text=True, start_new_session=True)
                procs.append({"rank": r, "proc": p, "out_f": out_f,
                              "err_f": err_f, "rc": None})
            while True:
                alive = 0
                failed = False
                for rec in procs:
                    if rec["rc"] is None:
                        rc = rec["proc"].poll()
                        if rc is None:
                            alive += 1
                        else:
                            rec["rc"] = rc
                            failed = failed or rc != 0
                if alive == 0 or failed or time.monotonic() > deadline:
                    break
                time.sleep(self.poll_interval_s)
        finally:
            for rec in procs:
                if rec["proc"].poll() is None:
                    # SIGKILL the whole group: a SIGSTOP'd (hung-fault)
                    # worker ignores anything milder
                    rec["killed"] = True
                    try:
                        os.killpg(rec["proc"].pid, signal.SIGKILL)
                    except OSError:
                        pass
            out = []
            for rec in procs:
                try:
                    # the group was SIGKILLed above; a reap that still
                    # blocks means the kernel is wedged on the process
                    # (e.g. uninterruptible I/O) - give up loudly
                    rec["proc"].wait(timeout=30.0)
                except subprocess.TimeoutExpired:
                    log.error("supervisor: rank %s unreaped 30s after "
                              "SIGKILL; abandoning the zombie",
                              rec.get("rank"))
                rec["rc"] = rec["proc"].returncode
                rec.setdefault("killed", False)
                for key in ("out_f", "err_f"):
                    f = rec.pop(key)
                    f.seek(0)
                    rec[key[:3]] = f.read()
                    f.close()
                rec.pop("proc")
                out.append(rec)
        return out

    @staticmethod
    def _flight_records(epoch: int) -> List[str]:
        """Flight-recorder dumps the workers of world-epoch ``epoch``
        left behind (obs/flight.py — written at RankFailure/NaN/crash
        sites): attached to the per-epoch report so a failed epoch's
        post-mortem starts from the black boxes, not a stderr tail."""
        import glob
        from ..obs import flight
        try:
            return sorted(glob.glob(flight.flight_path("*", epoch)))
        except Exception:  # noqa: BLE001
            return []

    @staticmethod
    def _suspects(records) -> List[int]:
        """Ranks believed dead/hung on their own: died hard without our
        SIGKILL, or — ONLY when no rank died hard — still running
        (wedged) when a peer exited with the detector code and we
        reaped them. A hard death explains the epoch's failure, and the
        reaped survivors were healthy ranks we killed ourselves;
        counting them too would over-shrink worlds larger than 2."""
        detectors = [r["rank"] for r in records
                     if r["rc"] == EXIT_RANK_FAILURE]
        out = [r["rank"] for r in records
               if r["rc"] not in (0, EXIT_RANK_FAILURE)
               and not r["killed"]]
        if not out and detectors:
            out = [r["rank"] for r in records if r["killed"]]
        return sorted(out)

    def _classify(self, records) -> str:
        detectors = [r["rank"] for r in records
                     if r["rc"] == EXIT_RANK_FAILURE]
        return (f"suspect ranks {self._suspects(records)} (exit codes "
                f"{[r['rc'] for r in records]}), detected by ranks "
                f"{detectors}")

    # -- the loop ------------------------------------------------------
    def run(self) -> List[Dict]:
        """Drive the world to a successful epoch; returns the per-rank
        records (with stdout/stderr) of that epoch. Raises
        :class:`WorldFailure` when the policy is exhausted."""
        from .elastic import shrunken_world_size
        while True:
            log.info("world supervisor: launching epoch %d with %d "
                     "process(es)", self.epoch, self.nprocs)
            records = self._launch_epoch()
            flights = self._flight_records(self.epoch)
            for rec in records:
                rec["flight_records"] = [
                    p for p in flights
                    if f"flight_rank{rec['rank']}_" in
                    os.path.basename(p)]
            self.report.append({"epoch": self.epoch,
                                "nprocs": self.nprocs,
                                "rcs": [r["rc"] for r in records],
                                "flight_records": flights})
            if all(r["rc"] == 0 for r in records):
                status.set_value("world_epoch", self.epoch)
                return records
            why = self._classify(records)
            REGISTRY.counter(
                "ff_world_restarts_total",
                "World re-formations by the world supervisor").inc()
            obs_events.instant("resilience.world_restart",
                               epoch=self.epoch, nprocs=self.nprocs,
                               why=why)
            # launcher-side flight record: a hard-crashed rank leaves
            # nothing (os._exit), and the supervisor reaps survivors
            # before their detection window — the launcher is the one
            # process guaranteed to witness the failed epoch, so it
            # records the black box (rank="launcher" can never collide
            # with a worker rank's file)
            from ..obs import flight
            fpath = flight.dump_flight_record(
                "world_restart", rank="launcher", epoch=self.epoch,
                extra={"why": why,
                       "rcs": {str(r["rank"]): r["rc"]
                               for r in records}})
            if fpath and self.report:
                self.report[-1].setdefault("flight_records",
                                           []).append(fpath)
            self.epoch += 1
            relaunch_ok = (self.policy in ("auto", "relaunch")
                           and self.world_restarts
                           < self.max_world_restarts)
            if relaunch_ok:
                self.world_restarts += 1
                status.record("restarts")
                log.warning("world supervisor: %s — relaunching epoch "
                            "%d at full size %d (restart %d/%d)", why,
                            self.epoch, self.nprocs,
                            self.world_restarts,
                            self.max_world_restarts)
                continue
            n_failed = len(self._suspects(records)) or 1
            new_n = 0
            if self.policy in ("auto", "shrink") \
                    and self.nprocs - n_failed >= self.min_world:
                new_n = shrunken_world_size(
                    self.nprocs - n_failed, self.batch_size,
                    self.devices_per_rank)
            if new_n >= self.min_world and new_n > 0:
                log.warning("world supervisor: %s — shrinking world "
                            "%d -> %d for epoch %d", why, self.nprocs,
                            new_n, self.epoch)
                self.nprocs = new_n
                self.shrinks += 1
                status.record("elastic_replans")
                obs_events.counter("resilience.world_shrink")
                continue
            tails = "; ".join(
                f"rank {r['rank']} rc={r['rc']}: "
                f"{(r['err'] or '')[-500:]}" for r in records
                if r["rc"] != 0)
            raise WorldFailure(
                f"world unrecoverable after {self.world_restarts} "
                f"restart(s) and {self.shrinks} shrink(s): {why}\n"
                f"{tails}", report=self.report)


def run_supervised(ff, directory: str, x=None, y=None,
                   epochs: Optional[int] = None,
                   batch_size: Optional[int] = None,
                   shuffle: bool = True, callbacks=None,
                   **supervisor_kwargs) -> List[Dict[str, float]]:
    """One-call resilient training: ``fit`` semantics under a
    :class:`Supervisor` (auto-resume + bounded restarts + rollback +
    elastic re-plan). ``run()``'s loop options are explicit parameters;
    ``supervisor_kwargs`` configure the :class:`Supervisor` itself."""
    sup = Supervisor(ff, directory, **supervisor_kwargs)
    return sup.run(x=x, y=y, epochs=epochs, batch_size=batch_size,
                   shuffle=shuffle, callbacks=callbacks)
