"""Resilient training driver: bounded restarts, exact resume, rollback.

``FFModel.fit`` assumes the process, the data pipeline, and the machine
survive the whole run; :class:`Supervisor` drops that assumption. It
drives the same per-step machinery (``ff._run_train_step`` over a
``SingleDataLoader``) inside a recovery loop:

  - **auto-resume**: on start, the newest *valid* checkpoint in the
    directory is restored — model state via the re-placing
    ``restore_model_checkpoint`` path, dataloader position (rng state,
    epoch, batch index) from the checkpoint metadata — so a resumed run
    replays the exact remaining batches;
  - **bounded restarts**: any step failure (a real exception or an
    injected :class:`~flexflow_tpu.resilience.faults.SimulatedCrash`)
    consumes one unit of the restart budget, sleeps an exponential
    backoff with jitter, restores, and continues; budget exhausted →
    the last error propagates;
  - **NaN rollback**: a non-finite loss never reaches a checkpoint —
    the step is detected before the periodic save, the run rolls back
    to the last good checkpoint, and the rollback is counted;
  - **elastic re-plan**: an injected (or detected)
    :class:`~flexflow_tpu.resilience.faults.DeviceLoss` triggers
    :func:`~flexflow_tpu.resilience.elastic.replan_on_device_loss` —
    re-search on the shrunken mesh, reshard the restored state, rebuild
    the loader on the new strategy — and training continues.

Checkpoints are the hardened atomic kind (``runtime/checkpoint.py``);
``async_save=True`` overlaps the file writes with the next train steps.
Everything reports into ``obs``: restart/rollback counters, a
time-since-last-checkpoint gauge, save/restore spans, and the always-on
:mod:`.status` block that ``/healthz`` serves.
"""
from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from ..runtime.metrics_buffer import MetricsBuffer, NonFiniteMetrics
from . import status
from .faults import DeviceLoss, SimulatedCrash  # noqa: F401 (re-export)

log = logging.getLogger("flexflow_tpu")


class RestartBudgetExceeded(RuntimeError):
    """The supervisor ran out of restarts; the cause is ``__cause__``."""


# the NaN-rollback trigger now carries the first bad step index found by
# the deferred flush (runtime/metrics_buffer.py); old alias kept
_NonFiniteLoss = NonFiniteMetrics


class Supervisor:
    """Wraps a compiled :class:`FFModel` in a crash/corruption/device-loss
    tolerant train loop. See the module docstring for semantics.

    ``checkpoint_every`` is in optimizer steps; ``max_restarts`` bounds
    recoveries of EVERY kind (crash, NaN rollback, device loss) across
    the whole run."""

    def __init__(self, ff, directory: str, *,
                 checkpoint_every: int = 1, max_to_keep: int = 3,
                 max_restarts: int = 3, backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, backoff_jitter: float = 0.25,
                 async_save: bool = False, elastic: bool = True,
                 verbose: bool = False):
        from ..runtime.checkpoint import CheckpointManager
        self.ff = ff
        self.directory = directory
        self.checkpoint_every = max(1, checkpoint_every)
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.async_save = async_save
        self.elastic = elastic
        self.verbose = verbose
        self.restarts = 0
        self.nan_rollbacks = 0
        self.elastic_replans = 0
        self._mgr = CheckpointManager(directory, max_to_keep=max_to_keep,
                                      async_save=async_save)
        self._since_ckpt = 0
        self._last_save_t: Optional[float] = None
        self._run_args: Optional[tuple] = None
        self._nan_steps: set = set()
        # live deferred-metrics buffer while _run_epoch is driving
        # steps; _save flushes + NaN-screens through it so a poisoned
        # state can never reach a checkpoint (the PR-3 invariant under
        # async dispatch)
        self._buffer: Optional[MetricsBuffer] = None

    # ------------------------------------------------------------------
    def run(self, x=None, y=None, epochs: Optional[int] = None,
            batch_size: Optional[int] = None, shuffle: bool = True,
            callbacks=None) -> List[Dict[str, float]]:
        """Train to completion (the resilient ``fit``); returns the
        per-epoch history. Resumes automatically from the newest valid
        checkpoint in ``directory`` when one exists. ``callbacks`` get
        the same per-epoch ``on_epoch_end(epoch, logs, model)`` contract
        as ``fit`` (a stop request ends the run after the epoch's
        checkpoint)."""
        ff = self.ff
        assert ff.executor is not None, "call compile() first"
        epochs = epochs or ff.config.epochs
        self._run_args = (x, y, batch_size, shuffle)
        loader = ff._combined_loader(x, y, batch_size, shuffle=shuffle)
        if not self._try_resume(loader):
            loader.reset()
            loader.epoch = 0
            self._save(loader)  # step-0 restore point: recovery always
            #                     has somewhere to land, even pre-ckpt-1
        history: List[Dict[str, float]] = []
        while loader.epoch < epochs:
            try:
                rep = self._run_epoch(loader)
                epoch_done = loader.epoch
                loader.epoch += 1
                if loader.epoch < epochs:
                    loader.reset()
                # epoch-boundary save so a later resume lands in the
                # right epoch with the fresh shuffle order; history is
                # appended only AFTER it succeeds — a failed save
                # triggers recovery, which replays the tail and must
                # not find the epoch already recorded
                self._save(loader)
                if rep is not None:
                    history.append(rep)
                    if callbacks:
                        # same contract as fit(); runs after the
                        # boundary save so a callback crash never
                        # loses the epoch
                        stop = False
                        for cb in callbacks:
                            cb.on_epoch_end(epoch_done, rep, ff)
                            stop = stop or getattr(cb, "stop_requested",
                                                   False)
                        if stop:
                            break
            except NonFiniteMetrics as e:
                if e.step in self._nan_steps:
                    # the rollback replays the exact same batch into the
                    # exact same params (that is what makes injected-
                    # fault recovery deterministic) — so a GENUINE
                    # divergence recurs identically; fail now instead of
                    # burning the remaining budget on doomed replays
                    raise RestartBudgetExceeded(
                        f"non-finite loss at step {e.step} recurred "
                        f"after rollback (deterministic divergence, not "
                        f"a transient)") from e
                self._nan_steps.add(e.step)
                self.nan_rollbacks += 1
                status.record("nan_rollbacks")
                self._recover(loader, reason="nan_loss", err=e)
            except DeviceLoss as e:
                loader = self._recover_device_loss(loader, e)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — that's the job
                self._recover(loader, reason=type(e).__name__, err=e)
        self._mgr.wait()
        ff._current_metrics = history[-1] if history else {}
        if getattr(ff.config, "trace_export_file", ""):
            # same end-of-training export hook as fit()
            from ..obs.trace_export import export_chrome_trace
            if obs_events.enabled():
                export_chrome_trace(ff.config.trace_export_file)
        return history

    # ------------------------------------------------------------------
    def _run_epoch(self, loader) -> Optional[Dict[str, float]]:
        from ..runtime.metrics import PerfMetrics
        ff = self.ff
        step_fn = ff.executor.make_train_step()
        pm = PerfMetrics()
        buf = MetricsBuffer.for_config(ff.config, pm=pm)
        self._buffer = buf
        ff._metrics_buffer = buf  # ff.save_checkpoint screens through it
        t0 = time.perf_counter()
        nb = 0
        try:
            while True:
                batch = loader.next_batch()
                if batch is None:
                    break
                bm = ff._run_train_step(step_fn, batch)
                bsz = next(iter(batch.values())).shape[0]
                # deferred accumulation: metrics stay on device; the
                # NaN screen is the fused all_finite flag checked at
                # flush points (every save below, print_freq, epoch
                # end). In sync-every-step mode the push flushes
                # immediately — old-loop semantics, but each metric is
                # converted exactly once (one device_get per step, no
                # float(np.asarray(loss)) + second np.asarray sweep).
                buf.push(ff._step - 1, bm, bsz)
                buf.raise_if_poisoned()
                nb += 1
                # dynamic recompilation hook — same contract as fit()
                # (model.py: reference RecompileState, model.cc:2422)
                rs = getattr(ff, "_recompile_state", None)
                if rs is not None and rs.step(ff):
                    step_fn = ff.executor.make_train_step()
                self._since_ckpt += 1
                if self._since_ckpt >= self.checkpoint_every:
                    # _save flushes + screens the pending window first
                    self._save(loader)
                self._update_ckpt_age_gauge()
                pf = ff.config.print_freq
                if self.verbose and pf > 0 and nb % pf == 0:
                    buf.flush()
                    buf.raise_if_poisoned()
                    rep = pm.report()
                    msg = " ".join(f"{k}={v:.4f}" for k, v in rep.items())
                    print(f"epoch {loader.epoch} iter {nb}/"
                          f"{loader.num_batches} {msg}")
            buf.flush()
            buf.raise_if_poisoned()
        finally:
            self._buffer = None
            ff._metrics_buffer = None
        if nb == 0:
            # resumed from a checkpoint taken at the epoch's last batch
            # (killed before the boundary save overwrote it): nothing
            # left to run — report None so a metric-less {} never lands
            # in history (consumers index history[-1]["loss"])
            return None
        dt = time.perf_counter() - t0
        rep = pm.report()
        rep["epoch_time_s"] = dt
        rep["samples_per_sec"] = pm.train_all / dt if dt > 0 else 0.0
        obs_events.record_span("supervisor.epoch", t0, dt,
                               epoch=loader.epoch, batches=nb)
        REGISTRY.gauge(
            "ff_train_samples_per_sec",
            "Training throughput of the last completed epoch"
        ).set(rep["samples_per_sec"])
        return rep

    # ------------------------------------------------------------------
    def _save(self, loader) -> None:
        from ..runtime.checkpoint import save_model_checkpoint
        if self._buffer is not None:
            # the deferred NaN screen ALWAYS runs immediately before a
            # checkpoint save: flush the in-flight window and raise on
            # the first non-finite step — the rollback happens INSTEAD
            # of the save, so a poisoned state never lands on disk
            self._buffer.flush()
            self._buffer.raise_if_poisoned()
        t0 = time.perf_counter()
        save_model_checkpoint(
            self.ff, self.directory, manager=self._mgr,
            extra_metadata={"loader": loader.state_dict(),
                            "supervisor": {"restarts": self.restarts}},
            blocking=not self.async_save)
        self._since_ckpt = 0
        self._last_save_t = time.monotonic()
        self._update_ckpt_age_gauge()
        obs_events.record_span("supervisor.save", t0,
                               time.perf_counter() - t0,
                               step=self.ff._step,
                               blocking=not self.async_save)

    def _try_resume(self, loader) -> bool:
        if self._mgr.latest_step() is None:
            return False
        try:
            self._restore(loader)
        except FileNotFoundError:
            return False  # every step corrupt: start fresh
        log.info("supervisor: resumed from checkpoint step %d "
                 "(epoch %d, batch %d)", self.ff._step, loader.epoch,
                 loader.idx)
        return True

    def _restore(self, loader) -> None:
        from ..runtime.checkpoint import restore_model_checkpoint
        self._mgr.wait()
        step, meta = restore_model_checkpoint(self.ff, self.directory,
                                              with_meta=True)
        ld = meta.get("loader")
        if ld is not None:
            loader.load_state_dict(ld)
        else:
            loader.reset()
        self._since_ckpt = 0

    # ------------------------------------------------------------------
    def _consume_restart(self, reason: str, err: BaseException) -> None:
        self.restarts += 1
        status.record("restarts")
        REGISTRY.counter("ff_resilience_restarts_total",
                         "Supervisor recoveries, any cause"
                         ).inc(reason=reason)
        obs_events.counter("resilience.restart")
        obs_events.instant("resilience.restart", reason=reason,
                           step=self.ff._step, attempt=self.restarts)
        if self.restarts > self.max_restarts:
            raise RestartBudgetExceeded(
                f"restart budget ({self.max_restarts}) exhausted; "
                f"last failure: {reason}: {err}") from err
        log.warning("supervisor: recovering from %s at step %d "
                    "(restart %d/%d): %s", reason, self.ff._step,
                    self.restarts, self.max_restarts, err)

    def _backoff(self) -> None:
        delay = min(self.backoff_max_s,
                    self.backoff_base_s * (2 ** (self.restarts - 1)))
        delay *= 1.0 + self.backoff_jitter * random.random()
        time.sleep(delay)

    def _recover(self, loader, reason: str, err: BaseException) -> None:
        self._consume_restart(reason, err)
        self._backoff()
        self._restore(loader)

    def _recover_device_loss(self, loader, err: DeviceLoss):
        """Elastic path: re-plan the strategy for the shrunken mesh,
        reshard the restored state onto it, rebuild the loader (its
        shardings reference the dead mesh), and resume in place."""
        if not self.elastic:
            raise err
        self._consume_restart("device_loss", err)
        self._backoff()
        from .elastic import replan_on_device_loss
        self._mgr.wait()
        replan_on_device_loss(self.ff, err.n_lost)
        self.elastic_replans += 1
        x, y, batch_size, shuffle = self._run_args
        new_loader = self.ff._combined_loader(x, y, batch_size,
                                              shuffle=shuffle)
        new_loader.epoch = loader.epoch
        self._restore(new_loader)
        return new_loader

    # ------------------------------------------------------------------
    def _update_ckpt_age_gauge(self) -> None:
        if self._last_save_t is not None:
            REGISTRY.gauge(
                "ff_time_since_last_checkpoint_seconds",
                "Age of the newest completed checkpoint"
            ).set(time.monotonic() - self._last_save_t)


def run_supervised(ff, directory: str, x=None, y=None,
                   epochs: Optional[int] = None,
                   batch_size: Optional[int] = None,
                   shuffle: bool = True, callbacks=None,
                   **supervisor_kwargs) -> List[Dict[str, float]]:
    """One-call resilient training: ``fit`` semantics under a
    :class:`Supervisor` (auto-resume + bounded restarts + rollback +
    elastic re-plan). ``run()``'s loop options are explicit parameters;
    ``supervisor_kwargs`` configure the :class:`Supervisor` itself."""
    sup = Supervisor(ff, directory, **supervisor_kwargs)
    return sup.run(x=x, y=y, epochs=epochs, batch_size=batch_size,
                   shuffle=shuffle, callbacks=callbacks)
