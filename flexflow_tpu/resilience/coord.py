"""Cross-process coordination: heartbeats, bounded barriers, world epoch.

The reference leans on Legion's runtime to notice a dead node (and then
aborts the whole job); jax gives us a distributed KV store + barrier
service (the coordination client behind ``jax.distributed.initialize``)
and nothing else. This module turns that into a failure-detection layer
for the multi-controller world (ISSUE 7):

  - **heartbeats**: every rank runs a daemon thread that bumps a
    per-rank sequence number in the KV store every
    ``heartbeat_interval_s``; a monitor on each rank watches its peers
    and attributes a rank whose sequence stops advancing for
    ``heartbeat_timeout_s`` (a crashed process stops beating instantly;
    a SIGSTOP'd/hung one stops within one interval — the writer thread
    is in-process);
  - **bounded barriers**: :meth:`Coordinator.barrier` never waits
    forever — on timeout it consults the heartbeat table and raises
    :class:`RankFailure` naming the suspected dead rank (or "unknown"
    when every peer still beats, i.e. a slow rank, not a dead one);
  - **world epoch**: a monotonic integer identifying the current
    incarnation of the world. The launcher (``resilience.supervisor.
    WorldSupervisor``) bumps it on every relaunch/shrink via
    ``FF_WORLD_EPOCH``; all heartbeat keys and barrier ids are
    epoch-scoped so debris from a dead epoch can never satisfy (or
    poison) a rendezvous in the next one;
  - **supervised exit**: under a world supervisor
    (``FF_WORLD_SUPERVISED=1``) a detected failure additionally arms a
    delayed hard-exit (:data:`EXIT_RANK_FAILURE`) so a survivor stuck
    inside a device collective — unreachable from Python — still dies
    within a bound and the supervisor can re-form the world.

Single-process worlds get a no-op coordinator (local KV, barriers
return immediately) so every call site stays unconditional.

Timeouts are configurable via ``FFConfig`` (``heartbeat_interval_s``,
``heartbeat_timeout_s``, ``barrier_timeout_s``) or the ``FF_HB_INTERVAL_S``
/ ``FF_HB_TIMEOUT_S`` / ``FF_BARRIER_TIMEOUT_S`` env vars (env wins; the
launcher uses it to tighten test worlds). See docs/distributed.md.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from . import status

log = logging.getLogger("flexflow_tpu")

#: process exit code meaning "I detected a peer rank failure and chose
#: to die so the world supervisor can re-form the world" — distinct from
#: a crash of this rank itself.
EXIT_RANK_FAILURE = 17


class RankFailure(RuntimeError):
    """A peer rank is dead or unreachable. ``rank`` is the suspected
    dead rank (None when the timeout could not be attributed), ``epoch``
    the world epoch it happened in."""

    def __init__(self, reason: str, rank: Optional[int] = None,
                 epoch: int = 0):
        who = f"rank {rank}" if rank is not None else "unknown rank"
        super().__init__(f"{who} failed (epoch {epoch}): {reason}")
        self.rank = rank
        self.epoch = epoch
        self.reason = reason


# ---------------------------------------------------------------------------
# KV backends
# ---------------------------------------------------------------------------
class LocalKV:
    """In-process stand-in for the distributed KV store: single-process
    worlds and unit tests run the same Coordinator code against it."""

    def __init__(self):
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: str) -> None:
        with self._lock:
            self._data[key] = value

    def dir_get(self, prefix: str) -> List[tuple]:
        with self._lock:
            return [(k, v) for k, v in self._data.items()
                    if k.startswith(prefix)]

    def barrier(self, name: str, timeout_s: float,
                world: int = 1) -> None:
        if world > 1:
            raise TimeoutError(
                f"LocalKV cannot rendezvous a {world}-process world")


class JaxKV:
    """The real thing: jax's distributed-runtime client (the same
    service that backed ``jax.distributed.initialize``'s rendezvous)."""

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value, allow_overwrite=True)

    def dir_get(self, prefix: str) -> List[tuple]:
        return list(self._client.key_value_dir_get(prefix))

    def barrier(self, name: str, timeout_s: float,
                world: int = 1) -> None:
        # raises (DEADLINE_EXCEEDED) on timeout; the Coordinator turns
        # that into an attributed RankFailure
        self._client.wait_at_barrier(name, int(timeout_s * 1000))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------
class Coordinator:
    """Per-process view of the multi-rank world. One per process
    (module singleton via :func:`ensure_started`); every public method
    is thread-safe."""

    def __init__(self, rank: int, world: int, *,
                 epoch: Optional[int] = None, kv=None,
                 heartbeat_interval_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 barrier_timeout_s: Optional[float] = None,
                 supervised: Optional[bool] = None):
        self.rank = int(rank)
        self.world = int(world)
        self.epoch = int(os.environ.get("FF_WORLD_EPOCH", "0")
                         if epoch is None else epoch)
        self.heartbeat_interval_s = _env_float(
            "FF_HB_INTERVAL_S", heartbeat_interval_s or 0.25)
        self.heartbeat_timeout_s = _env_float(
            "FF_HB_TIMEOUT_S", heartbeat_timeout_s or 10.0)
        self.barrier_timeout_s = _env_float(
            "FF_BARRIER_TIMEOUT_S", barrier_timeout_s or 60.0)
        self.supervised = (os.environ.get("FF_WORLD_SUPERVISED") == "1"
                           if supervised is None else supervised)
        if kv is None:
            if world > 1:
                from ..parallel import distributed as dist
                c = dist.client()
                if c is None:
                    raise RuntimeError(
                        "Coordinator for a multi-process world needs the "
                        "jax distributed client (jax.distributed."
                        "initialize first)")
                kv = JaxKV(c)
            else:
                kv = LocalKV()
        self.kv = kv
        self._seq = 0
        self._failure: Optional[RankFailure] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # rank -> (last seen seq, monotonic time the seq last advanced)
        self._peer_seen: Dict[int, tuple] = {}
        # cross-rank clock anchor from the last clock_sync() handshake:
        # {"perf_s", "wall_s", "name"} — perf_counter/wall sampled at
        # the barrier release, i.e. (near-)the same physical instant on
        # every rank. tools/fftrace.py uses it to place each rank's
        # monotonic span timestamps on one merged timeline.
        self.clock_anchor: Optional[Dict] = None
        status.set_value("world_epoch", self.epoch)
        status.set_value("world_rank", self.rank)
        status.set_value("world_size", self.world)
        REGISTRY.gauge("ff_world_epoch",
                       "Monotonic epoch of the current world incarnation"
                       ).set(float(self.epoch))

    # -- key naming ----------------------------------------------------
    def _hb_prefix(self) -> str:
        return f"ff/hb/e{self.epoch}/"

    def _hb_key(self, rank: int) -> str:
        return f"{self._hb_prefix()}{rank}"

    # -- heartbeats ----------------------------------------------------
    def start(self) -> "Coordinator":
        """Begin beating + monitoring. Idempotent."""
        if self._thread is not None or self.world <= 1:
            return self
        self.beat()  # first beat synchronously: peers see us immediately
        self._thread = threading.Thread(
            target=self._loop, name="ff-coord-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.heartbeat_interval_s + 1.0)
        self._thread = None

    def beat(self) -> None:
        self._seq += 1
        self.kv.set(self._hb_key(self.rank), str(self._seq))

    def _loop(self) -> None:
        misses_metric = REGISTRY.counter(
            "ff_heartbeat_misses_total",
            "Peer heartbeat timeouts observed by this rank")
        detected_at: Optional[float] = None
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self.beat()
                stale = self._scan_peers()
            except Exception as e:  # noqa: BLE001 — KV died = world died
                stale = None
                with self._lock:
                    if self._failure is None:
                        self._failure = RankFailure(
                            f"coordination service unreachable: {e}",
                            rank=None, epoch=self.epoch)
                        _record_failure(self._failure)
            if stale:
                with self._lock:
                    if self._failure is None:
                        misses_metric.inc()
                        self._failure = RankFailure(
                            f"no heartbeat for "
                            f"{self.heartbeat_timeout_s:.1f}s",
                            rank=stale[0], epoch=self.epoch)
                        _record_failure(self._failure)
            if self.failure() is not None and self.supervised:
                # the main thread may be stuck inside a device collective
                # (unreachable from Python) — give it one timeout's grace
                # to surface the failure via check()/barrier(), then die
                # loudly so the world supervisor can re-form the world
                if detected_at is None:
                    detected_at = time.monotonic()
                elif time.monotonic() - detected_at \
                        > self.heartbeat_timeout_s:
                    log.error(
                        "coordinator: rank failure unhandled for %.1fs "
                        "— exiting %d for the world supervisor",
                        self.heartbeat_timeout_s, EXIT_RANK_FAILURE)
                    os._exit(EXIT_RANK_FAILURE)

    def _scan_peers(self) -> List[int]:
        """Ranks whose heartbeat seq has not advanced within the
        timeout. A peer we have never seen is not stale until the
        timeout passes from OUR start — ranks join at different times.
        Callers race (heartbeat thread vs a timed-out barrier on the
        main/writer thread), so the peer table update is locked."""
        now = time.monotonic()
        seen: Dict[int, str] = {}
        for key, val in self.kv.dir_get(self._hb_prefix()):
            tail = key.rsplit("/", 1)[-1]
            if tail.isdigit():
                seen[int(tail)] = val
        stale = []
        with self._lock:
            for r in range(self.world):
                if r == self.rank:
                    continue
                cur = seen.get(r)
                prev = self._peer_seen.get(r)
                if cur is not None and (prev is None or prev[0] != cur):
                    self._peer_seen[r] = (cur, now)
                    continue
                if prev is None:
                    # never beat: count from monitor start
                    self._peer_seen[r] = (None, now)
                    continue
                if now - prev[1] > self.heartbeat_timeout_s:
                    stale.append(r)
        return stale

    # -- failure surface ----------------------------------------------
    def failure(self) -> Optional[RankFailure]:
        with self._lock:
            return self._failure

    def check(self) -> None:
        """Raise the pending :class:`RankFailure`, if any. Cheap — the
        train loop calls this every step."""
        f = self.failure()
        if f is not None:
            raise f

    # -- bounded barrier ----------------------------------------------
    def barrier(self, name: str,
                timeout_s: Optional[float] = None) -> None:
        """Epoch-scoped rendezvous of every rank in the world; raises
        :class:`RankFailure` (with the dead rank attributed from the
        heartbeat table) instead of waiting forever. ``name`` must be
        unique per logical use (checkpoint barriers include the step)."""
        self.check()
        if self.world <= 1:
            return
        timeout_s = timeout_s if timeout_s is not None \
            else self.barrier_timeout_s
        bid = f"ff:e{self.epoch}:{name}"
        t0 = time.perf_counter()
        try:
            self.kv.barrier(bid, timeout_s, world=self.world)
        except RankFailure:
            raise
        except Exception as e:  # timeout / connection loss
            stale = self._scan_peers()
            f = RankFailure(
                f"barrier {name!r} timed out after {timeout_s:.1f}s "
                f"({e})", rank=stale[0] if stale else None,
                epoch=self.epoch)
            with self._lock:
                if self._failure is None:
                    self._failure = f
            _record_failure(f)
            raise f from e
        finally:
            obs_events.record_span("coord.barrier", t0,
                                   time.perf_counter() - t0,
                                   barrier=name)


    # -- clock handshake ----------------------------------------------
    def clock_sync(self, name: str = "clock") -> Dict:
        """KV-store clock handshake for cross-rank trace alignment:
        every rank meets at one epoch-scoped bounded barrier, then
        samples ``(perf_counter, wall)`` at the release — the same
        physical instant (within barrier-release skew) everywhere — and
        publishes its wall sample to the KV store for diagnostics. The
        anchor is kept on ``self.clock_anchor``; the per-rank trace
        dump (obs/trace_export.dump_rank_trace) and the flight recorder
        embed it so ``tools/fftrace.py`` can align the rank timelines
        without trusting cross-host wall clocks. Single-process worlds
        anchor immediately (the barrier is a no-op)."""
        if self.world > 1:
            self.barrier(f"clock:{name}")
        t_perf = time.perf_counter()
        t_wall = time.time()
        self.clock_anchor = {"perf_s": t_perf, "wall_s": t_wall,
                             "name": name}
        try:
            self.kv.set(f"ff/clock/e{self.epoch}/{self.rank}",
                        repr(t_wall))
        except Exception:  # noqa: BLE001 — the KV copy is diagnostics
            pass
        return self.clock_anchor


def _record_failure(f: RankFailure) -> None:
    status.record("rank_failures")
    status.set_value("last_rank_failure",
                     f"rank={f.rank} epoch={f.epoch} {f.reason}")
    REGISTRY.counter("ff_rank_failures_total",
                     "Peer rank failures detected by this process").inc()
    obs_events.counter("resilience.rank_failure")
    obs_events.instant("resilience.rank_failure", rank=f.rank,
                       epoch=f.epoch, reason=f.reason)
    # black-box dump at the detection site: this survivor may be about
    # to exit for world re-formation, and its ring/counters/world facts
    # are the only record of what the world looked like at the failure
    from ..obs import flight
    flight.dump_flight_record("rank_failure", exc=f)
    log.error("coordinator: %s", f)


# ---------------------------------------------------------------------------
# process-wide singleton
# ---------------------------------------------------------------------------
_coord: Optional[Coordinator] = None
_coord_lock = threading.Lock()


def get() -> Optional[Coordinator]:
    # benign: atomic reference read; _coord_lock only orders
    # create/teardown, and a stale None here just means "no coordinator"
    return _coord  # ffcheck: ok(guarded-field)


def ensure_started(config=None) -> Coordinator:
    """The process coordinator, creating + starting it on first use.
    Called from ``FFModel.compile`` right after the world rendezvous;
    single-process worlds get the no-op local coordinator."""
    global _coord
    with _coord_lock:
        if _coord is not None:
            return _coord
        import atexit

        import jax
        # stop the heartbeat thread BEFORE interpreter teardown: a beat
        # in flight while the XLA distributed client is being destroyed
        # aborts the process (std::terminate) at exit
        atexit.register(reset)
        kw = {}
        if config is not None:
            for name in ("heartbeat_interval_s", "heartbeat_timeout_s",
                         "barrier_timeout_s"):
                v = getattr(config, name, None)
                if v:
                    kw[name] = float(v)
        _coord = Coordinator(jax.process_index(), jax.process_count(),
                             **kw).start()
        # an unhandled crash on a world member should leave a flight
        # record for the WorldSupervisor's per-rank report
        from ..obs import flight
        flight.install_excepthook()
        return _coord


def reset() -> None:
    """Tear down the singleton (tests)."""
    global _coord
    with _coord_lock:
        c, _coord = _coord, None
    if c is not None:
        c.stop()


def check() -> None:
    """Module-level pending-failure check: no-op without a coordinator."""
    # benign: atomic reference read on the per-step hot path (see get())
    c = _coord  # ffcheck: ok(guarded-field)
    if c is not None:
        c.check()


def barrier(name: str, timeout_s: Optional[float] = None) -> None:
    """Module-level bounded barrier: no-op without a coordinator (the
    single-process checkpoint path calls this unconditionally)."""
    # benign: atomic reference read (see get())
    c = _coord  # ffcheck: ok(guarded-field)
    if c is not None:
        c.barrier(name, timeout_s=timeout_s)
