"""Closed-loop plan adaptation: drift-triggered re-calibration,
background re-search, and bounded hot-swap.

The strategy search adopts a plan against the machine it measured at
compile time; the fleet the plan actually runs on then drifts — a DCN
uplink browns out, the workload's batch distribution shifts, a serving
replica's breaker opens. The pieces that *detect* each of these already
exist (``obs/drift.py`` marks mispriced calibration rows stale,
``resilience/faults.py`` registers degraded links, the scheduler's
circuit breaker and admission EWMA track serving health); this module
closes the loop:

  evidence -> debounce -> targeted re-calibration of exactly the
  stale-marked rows (``CalibrationTable.remeasure_stale``) -> re-search
  on the refreshed tables -> gated adoption (plan verifier + predicted
  win >= ``win_ratio``) -> hot-swap with bit-exact state carryover ->
  measured post-swap A/B guard that rolls back a regression.

Flap control is structural, not best-effort: every completed decision —
adopted, rejected, no-win or rolled back — arms a cooldown before the
next one, and non-adoptions grow it exponentially (``backoff`` up to
``max_cooldown_s``), so a fleet the controller cannot actually help
gets probed at exponentially sparser intervals instead of thrashing.
An adoption resets the backoff: the fleet changed, fresh evidence
deserves a fresh budget.

Training swaps ride the same machinery as checkpoint restore: the live
params/opt-state/state are snapshotted to host, the candidate strategy
is compiled through the ordinary ``FFModel.compile`` path (so the ZeRO
planner, qsync planner, kernel tier and plan verifier all re-bind on
it), and the snapshot is re-placed onto the new shardings via
``reshard.place_host`` — values bit-identical, only placement changes.
Serving swaps go through ``ModelRepository.hot_swap`` under graceful
drain and are re-scored from ``ServingPlanSession.measured_profile``.

Reference analog: FlexFlow's ``recompile_on_condition``
(``model.cc:2422``) evaluates a trigger each iteration and rebuilds the
task graph when it fires; this controller is that hook driven by the
calibration-drift evidence instead of a user lambda, which is also how
it attaches to a live training loop (``attach_training`` installs a
``runtime.recompile.RecompileState``).
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from . import status

__all__ = ["ReplanPolicy", "ReplanController"]


def _count(trigger: str, outcome: str) -> None:
    REGISTRY.counter(
        "ff_replans_total",
        "Closed-loop plan adaptations by trigger and outcome"
    ).inc(trigger=trigger, outcome=outcome)


@dataclass
class ReplanPolicy:
    """Knobs of the adaptation loop. Defaults are deliberately
    conservative: two consecutive evidence polls before acting, a 10%
    predicted win before a swap is even attempted, and a measured guard
    band wider than CPU-sim timing noise."""
    win_ratio: float = 1.1        # predicted incumbent/candidate floor
    debounce_polls: int = 2       # consecutive evidence polls to act
    cooldown_s: float = 60.0      # base gap between decisions
    backoff: float = 2.0          # cooldown growth on non-adoption
    max_cooldown_s: float = 3600.0
    guard_band: float = 1.05      # measured A/B regression tolerance
    search_budget: int = 200      # MCMC proposals per re-search
    search_seed: int = 0
    poll_every: int = 1           # training steps between polls
    ewma_ratio: float = 2.0       # scheduler batch-EWMA drift trigger
    measured_guard: bool = True   # run the post-swap A/B (off = adopt
                                  # on the predicted gate alone,
                                  # recorded as gate="deferred")
    background: bool = False      # search on a worker thread; the swap
                                  # itself always runs on the caller's
                                  # (training) thread at a step boundary


class ReplanController:
    """One controller per process; drive it either synchronously
    (``step_once`` — tests, smokes, serving) or hooked into a live
    training loop (``attach_training`` — the supervisor's per-step
    recompile hook evaluates it between steps)."""

    def __init__(self, ff=None, policy: Optional[ReplanPolicy] = None,
                 cache_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.ff = ff
        self.policy = policy or ReplanPolicy()
        self.cache_dir = cache_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._streak = 0
        self._cooldown_until = 0.0
        self._cooldown_s = self.policy.cooldown_s
        self.replans = 0              # adopted swaps
        self.rollbacks = 0            # A/B-guard reverts
        self.last_trigger: Optional[str] = None
        self.last_outcome: Optional[str] = None
        self.history: List[Dict[str, Any]] = []
        self._schedulers: List[Any] = []
        self._ewma_baseline: Dict[int, float] = {}
        # a fired workload_shift clause is consumed-on-read from the
        # fault registry; the controller holds it as live evidence until
        # the next completed decision so the debounce does not eat it
        self._shift: Optional[int] = None
        # background mode: (trigger, evidence, candidate) produced by
        # the worker thread, adopted by the next step_once on the
        # training thread
        self._pending: Optional[Tuple[str, list, Dict[str, Any]]] = None
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------ evidence --
    def attach_scheduler(self, sched) -> None:
        """Watch a serving ``BatchScheduler``: an open circuit breaker
        or a batch-latency EWMA ``ewma_ratio``x above its first-seen
        baseline becomes replan evidence."""
        self._schedulers.append(sched)

    def poll_evidence(self) -> List[Dict[str, Any]]:
        """Everything currently arguing for a re-plan, most actionable
        first. Pure read (except the one-shot workload-shift consume,
        which the controller keeps holding until it acts on it)."""
        ev: List[Dict[str, Any]] = []
        from . import faults
        # stale calibration rows: the drift detector (obs/drift.py)
        # marked predicted-vs-measured out-of-band rows for re-measure
        try:
            table = self._table()
            table._load_stale()
            stale = sorted(table._stale or ())
            if stale:
                ev.append({"trigger": "drift", "n_stale": len(stale),
                           "stale_keys": stale[:8]})
        except Exception:  # noqa: BLE001 — evidence intake is best-effort
            pass
        deg = faults.degraded_links()
        if deg:
            ev.append({"trigger": "degraded", "links": deg})
        shift = faults.pending_workload_shift()
        if shift is not None:
            self._shift = shift
        if self._shift is not None:
            ev.append({"trigger": "workload_shift", "batch": self._shift})
        for sched in self._schedulers:
            try:
                st = sched.stats()
                if st.get("circuit") == "open":
                    ev.append({"trigger": "breaker",
                               "model": st.get("model")})
                ewma = getattr(sched, "_ewma_batch_s", None)
                base = self._ewma_baseline.get(id(sched))
                if ewma:
                    if base is None:
                        self._ewma_baseline[id(sched)] = float(ewma)
                    elif ewma > base * self.policy.ewma_ratio:
                        ev.append({"trigger": "slo",
                                   "ewma_s": round(float(ewma), 6),
                                   "baseline_s": round(base, 6)})
            except Exception:  # noqa: BLE001
                pass
        return ev

    def _table(self):
        from ..search.calibration import CalibrationTable
        return CalibrationTable(self.cache_dir) if self.cache_dir \
            else CalibrationTable()

    # -------------------------------------------------- control loop --
    def step_once(self, ff=None) -> str:
        """One control-loop iteration; returns the outcome tag:
        ``quiet`` | ``debounce`` | ``cooldown`` | ``searching`` (a
        background search is in flight) | ``rejected`` | ``no_win`` |
        ``adopted`` | ``rolled_back`` | ``error``."""
        ff = ff if ff is not None else self.ff
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            trigger, ev, cand = pending
            return self._adopt(ff, trigger, ev, cand)
        if self._worker is not None and self._worker.is_alive():
            return "searching"
        ev = self.poll_evidence()
        if not ev:
            self._streak = 0
            return "quiet"
        self._streak += 1
        if self._streak < self.policy.debounce_polls:
            return "debounce"
        if self._clock() < self._cooldown_until:
            return "cooldown"
        trigger = ev[0]["trigger"]
        if self.policy.background:
            self._launch(ff, trigger, ev)
            return "searching"
        status.set_value("replan_candidate", "searching")
        t0 = time.perf_counter()
        try:
            cand = self._prepare(ff, trigger)
        except Exception as e:  # noqa: BLE001 — the loop must survive
            self._finish(ff, trigger, "error", {"error": repr(e)}, ev, t0)
            return "error"
        why = cand.pop("reject", None)
        if why is not None:
            self._finish(ff, trigger, why, cand, ev, t0)
            return why
        return self._adopt(ff, trigger, ev, cand, t0=t0)

    def _launch(self, ff, trigger: str, ev: list) -> None:
        """Background mode: re-calibration + search + gates run off the
        training thread; only the swap itself (next ``step_once``)
        touches the live model."""
        status.set_value("replan_candidate", "searching")

        def run():
            t0 = time.perf_counter()
            try:
                cand = self._prepare(ff, trigger)
            except Exception as e:  # noqa: BLE001
                self._finish(ff, trigger, "error", {"error": repr(e)},
                             ev, t0)
                return
            why = cand.pop("reject", None)
            if why is not None:
                self._finish(ff, trigger, why, cand, ev, t0)
                return
            with self._lock:
                self._pending = (trigger, ev, cand)
            status.set_value("replan_candidate", "pending")

        self._worker = threading.Thread(target=run, name="ff-replan",
                                        daemon=True)
        self._worker.start()

    # ------------------------------------- recalibrate + search + gate --
    def _prepare(self, ff, trigger: str) -> Dict[str, Any]:
        """Heal the tables, search a candidate, gate it. Returns the
        candidate bundle, or ``{"reject": "rejected"|"no_win", ...}``."""
        with obs_events.span("replan.recalibrate", trigger=trigger):
            table = self._table()
            remeasured = table.remeasure_stale(ff.dmesh)
        with obs_events.span("replan.search", trigger=trigger,
                             budget=self.policy.search_budget):
            cand = self._search(ff)
        cand["remeasured"] = sorted(remeasured)
        with obs_events.span("replan.gate", trigger=trigger):
            ok, why, gate = self._gate(ff, cand)
        cand.update(gate)
        if not ok:
            cand["reject"] = why
        return cand

    def _search(self, ff) -> Dict[str, Any]:
        """Re-search on freshly calibrated tables and price the
        incumbent under the SAME tables, so the predicted-win gate is a
        like-for-like comparison on current machine evidence."""
        from ..search.mcmc import (assignment_to_strategy,
                                   data_parallel_assignment, mcmc_search)
        cm = self._fresh_cost_model(ff)
        best, best_cost, sim = mcmc_search(
            ff.layers, ff.dmesh, cm, budget=self.policy.search_budget,
            seed=self.policy.search_seed)
        inc_assign, basis = self._incumbent_assignment(ff, sim)
        if inc_assign is None:
            inc_assign = data_parallel_assignment(ff.layers, ff.dmesh,
                                                  sim.options)
            basis = "dp"
        inc_cost = sim.evaluate(inc_assign).total
        strategy = assignment_to_strategy(ff.layers, ff.graph_inputs,
                                          best, ff.dmesh, sim)
        if cm.placement is not None:
            # re-price only the adopted assignment with cleared memos so
            # the recorded tree choices are its sites (optimizer.py does
            # the same after mcmc_search)
            cm.attach_placement(cm.placement, "hier")
            sim.evaluate(best)
            strategy.collective_trees = list(cm.algo_choices.values())
            strategy.axis_tiers = cm.placement.to_json()
        return {"strategy": strategy, "assign": best,
                "predicted_s": best_cost, "incumbent_s": inc_cost,
                "incumbent_basis": basis,
                "predicted_ratio": inc_cost / max(best_cost, 1e-12)}

    def _fresh_cost_model(self, ff):
        """A cost model calibrated the way ``optimize_strategy`` does it
        — measured collectives, persisted tables, kernel tier — so the
        re-search ranks plans on the machine as it is NOW (the refreshed
        rows from ``remeasure_stale``, the degradation factors from the
        fault registry)."""
        from ..search.costmodel import OpCostModel
        from ..search.optimizer import _attach_placement
        cfg, dmesh = ff.config, ff.dmesh
        cm = OpCostModel(dmesh.spec)
        cm.segment_size = max(1, cfg.simulator_segment_size)
        cm.max_segments = max(1, cfg.simulator_max_num_segments)
        _attach_placement(cfg, cm, dmesh)
        if not cfg.machine_model_file:
            cm.calibrate_collectives(dmesh)
            from ..search.calibration import (calibration_enabled,
                                              calibrate_mesh)
            if calibration_enabled(cfg):
                try:
                    cm.attach_calibration(calibrate_mesh(dmesh))
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        kpolicy = str(getattr(cfg, "kernel_impls", "auto") or
                      "auto").lower()
        if kpolicy not in ("off", "none") and cm.calib is not None:
            try:
                from ..search.calibration import calibrate_kernel_impls
                calibrate_kernel_impls(dmesh, cm.calib.table)
            except Exception:  # noqa: BLE001
                pass
            from ..kernels.registry import resolve_forced
            cm.attach_kernel_tier(dmesh, forced=resolve_forced(cfg))
        return cm

    def _incumbent_assignment(self, ff, sim):
        """Reconstruct the live strategy as a simulator assignment: per
        layer, walk the (small) degree lattice and keep the tuple whose
        materialized sharding equals the incumbent's specs. Returns
        (assign, basis) — basis ``"specs"`` when every sharded layer
        matched, ``"mixed"`` when some fell back to the DP degree, or
        (None, None) when fewer than half matched (caller prices the DP
        baseline instead and records it)."""
        from ..search.mcmc import (assignment_to_sharding,
                                   data_parallel_assignment)
        inc = getattr(ff, "strategy", None)
        ops = getattr(inc, "ops", {}) or {}
        if not ops:
            return None, None
        valid = sorted(set(ff.dmesh.valid_degrees()))
        dp = data_parallel_assignment(ff.layers, ff.dmesh, sim.options)
        assign: Dict[str, Tuple[int, ...]] = {}
        sharded = matched = 0
        for layer in ff.layers:
            opts = sim.options[layer.name]
            want = ops.get(layer.name)
            if want is None or not opts:
                assign[layer.name] = (1,) * len(opts)
                continue
            sharded += 1
            target = (tuple(want.outputs),
                      tuple(sorted(want.weights.items())))
            hit = None
            if len(valid) ** len(opts) <= 4096:
                for degs in itertools.product(valid, repeat=len(opts)):
                    res = assignment_to_sharding(layer, opts, degs,
                                                 ff.dmesh)
                    if res is None:
                        continue
                    got = (tuple(res[0]),
                           tuple(sorted(res[1].items())))
                    if got == target:
                        hit = degs
                        break
            if hit is not None:
                matched += 1
                assign[layer.name] = hit
            else:
                assign[layer.name] = dp.get(layer.name,
                                            (1,) * len(opts))
        if sharded and matched * 2 < sharded:
            return None, None
        return assign, ("specs" if matched == sharded else "mixed")

    def _gate(self, ff, cand) -> Tuple[bool, str, Dict[str, Any]]:
        """Candidate admission: statically sound AND predicted at least
        ``win_ratio`` faster than the incumbent under the same refreshed
        tables. A failed gate leaves the incumbent completely untouched."""
        gate: Dict[str, Any] = {}
        from ..analysis.plan_verifier import (PlanVerificationError,
                                              verify_plan)
        try:
            verify_plan(cand["strategy"], ff.layers,
                        machine_spec=ff.dmesh.spec,
                        graph_inputs=ff.graph_inputs,
                        optimizer=ff.optimizer,
                        context="replan").raise_if_failed()
        except PlanVerificationError as e:
            gate["verifier"] = str(e)[:400]
            return False, "rejected", gate
        ratio = cand["predicted_ratio"]
        gate["win_ratio_floor"] = self.policy.win_ratio
        if ratio < self.policy.win_ratio:
            return False, "no_win", gate
        return True, "", gate

    # ---------------------------------------------------- hot-swap --
    def _adopt(self, ff, trigger: str, ev: list, cand: Dict[str, Any],
               t0: Optional[float] = None) -> str:
        """Swap the candidate in with bit-exact state carryover, run the
        measured A/B guard, roll back on regression."""
        t0 = time.perf_counter() if t0 is None else t0
        status.set_value("replan_candidate", "pending")
        incumbent = ff.strategy
        snap, step = self._snapshot(ff)
        detail: Dict[str, Any] = {
            k: cand[k] for k in ("predicted_s", "incumbent_s",
                                 "incumbent_basis", "predicted_ratio",
                                 "remeasured") if k in cand}
        try:
            with obs_events.span("replan.swap", trigger=trigger):
                self._install(ff, cand["strategy"])
                self._replace_state(ff, snap, step)
        except Exception as e:  # noqa: BLE001 — a candidate that fails
            # to compile must heal back to the incumbent, not crash
            with obs_events.span("replan.swap", trigger=trigger,
                                 rollback=True):
                self._install(ff, incumbent)
                self._replace_state(ff, snap, step)
            detail["error"] = repr(e)
            self._finish(ff, trigger, "rejected", detail, ev, t0)
            return "rejected"
        guard = self._ab_guard(ff, incumbent, cand["strategy"]) \
            if self.policy.measured_guard else {"gate": "deferred"}
        detail.update(guard)
        if guard.get("gate") == "regression":
            with obs_events.span("replan.swap", trigger=trigger,
                                 rollback=True):
                self._install(ff, incumbent)
                self._replace_state(ff, snap, step)
            self.rollbacks += 1
            self._finish(ff, trigger, "rolled_back", detail, ev, t0)
            return "rolled_back"
        self.replans += 1
        self._finish(ff, trigger, "adopted", detail, ev, t0)
        return "adopted"

    @staticmethod
    def _snapshot(ff):
        """Host copies of the live training state — the same capture a
        checkpoint save makes, minus the disk round-trip."""
        import jax
        import numpy as np
        snap = {"params": jax.tree.map(np.asarray, ff.params),
                "opt_state": jax.tree.map(np.asarray, ff.opt_state),
                "state": jax.tree.map(np.asarray, ff.state)}
        return snap, ff._step

    @staticmethod
    def _install(ff, strategy) -> None:
        """Compile ``strategy`` through the ordinary path (warm
        recompile, same shape as ``elastic.replan_on_device_loss``) so
        the ZeRO/qsync/kernel planners and the plan verifier re-bind on
        exactly the plan the run will execute."""
        out_t = ff._output_tensor
        if out_t is not None and \
                getattr(out_t, "owner_layer", None) not in ff.layers:
            # the incumbent's search rewrote the graph (inserted
            # parallel ops): its output tensor is not producible from
            # ff.layers, which is what the candidate was searched over —
            # let compile() re-derive the user graph's output
            out_t = None
        ff.strategy = None
        ff.executor = None
        ff._prebuilt_executor = None
        ff.compile(optimizer=ff.optimizer, loss_type=ff.loss_type,
                   metrics=list(ff.metrics),
                   machine_spec=ff.dmesh.spec, strategy=strategy,
                   output_tensor=out_t)

    @staticmethod
    def _replace_state(ff, snap, step: int) -> None:
        """Re-place the snapshot onto the freshly compiled shardings —
        the checkpoint-restore pattern (``runtime/checkpoint.py``):
        values bit-identical, only placement changes, so the loss
        history continues exactly where the incumbent left it."""
        import jax
        import numpy as np
        from ..parallel.reshard import place_host
        from ..runtime.checkpoint import _restore_opt_state

        def replace(tmpl, new):
            return jax.tree.map(
                lambda t, n: place_host(
                    np.asarray(n).astype(t.dtype).reshape(t.shape),
                    t.sharding if hasattr(t, "sharding") else None),
                tmpl, new)

        ff.params = replace(ff.params, snap["params"])
        ff.opt_state = _restore_opt_state(ff, snap["opt_state"], replace)
        ff.state = replace(ff.state, snap["state"])
        ff._step = step

    def _ab_guard(self, ff, incumbent, candidate) -> Dict[str, Any]:
        """Post-swap measured A/B: time a few synthetic train steps of
        both plans back to back (the floor guard's ``_time_strategy`` —
        fresh executors and synthetic state, the live model untouched).
        ``regression`` = candidate measurably slower; ``measured_win`` =
        measurably faster; ``deferred`` = inside the noise band, adopt
        on the predicted gate (recorded so the audit shows which gate
        admitted the swap)."""
        from ..search.optimizer import _time_strategy
        with obs_events.span("replan.guard"):
            try:
                cand_s, _, _, _ = _time_strategy(ff, candidate, None)
                inc_s, _, _, _ = _time_strategy(ff, incumbent, None)
            except Exception as e:  # noqa: BLE001 — an unmeasurable
                # guard defers to the predicted gate rather than block
                return {"gate": "deferred", "guard_error": repr(e)}
            finally:
                # _time_strategy parks its executor for compile() to
                # adopt; nothing here will, so drop the hand-off
                ff._prebuilt_executor = None
        out = {"measured_candidate_s": cand_s, "measured_incumbent_s": inc_s,
               "measured_ratio": inc_s / max(cand_s, 1e-12)}
        if cand_s > inc_s * self.policy.guard_band:
            out["gate"] = "regression"
        elif cand_s * self.policy.guard_band < inc_s:
            out["gate"] = "measured_win"
        else:
            out["gate"] = "deferred"
        return out

    # -------------------------------------------------- bookkeeping --
    def _finish(self, ff, trigger: str, outcome: str, detail: Dict,
                ev: list, t0: float) -> None:
        now = self._clock()
        if outcome == "adopted":
            self._cooldown_s = self.policy.cooldown_s
        else:
            self._cooldown_s = min(self._cooldown_s * self.policy.backoff,
                                   self.policy.max_cooldown_s)
        self._cooldown_until = now + self._cooldown_s
        self._streak = 0
        self._shift = None
        self.last_trigger, self.last_outcome = trigger, outcome
        rec = {"trigger": trigger, "outcome": outcome,
               "cooldown_s": self._cooldown_s,
               "elapsed_s": round(time.perf_counter() - t0, 3),
               "evidence": ev, **detail}
        # strategies don't serialize; the audit record carries numbers
        rec.pop("strategy", None)
        rec.pop("assign", None)
        self.history.append(rec)
        _count(trigger, outcome)
        status.set_value("replan_last_trigger", trigger)
        status.set_value("replan_last_outcome", outcome)
        status.set_value("replan_candidate", "idle")
        status.set_value("replan_cooldown_until_unix_s",
                         time.time() + max(0.0, self._cooldown_until - now))
        if outcome == "adopted":
            status.record("replans")
        elif outcome == "rolled_back":
            status.record("replan_rollbacks")
        obs_events.instant("replan.decision", trigger=trigger,
                           outcome=outcome)
        path = getattr(ff, "_strategy_audit_path", None) if ff else None
        if path:
            from ..obs.audit import annotate_strategy_audit
            annotate_strategy_audit(path, {"replan": {
                "events": list(self.history)}})
        if outcome in ("adopted", "rolled_back"):
            # every swap decision leaves a black box: which evidence,
            # which gates, what the A/B measured
            try:
                from ..obs.flight import dump_flight_record
                dump_flight_record(f"replan_{outcome}",
                                   extra={"replan": rec})
            except Exception:  # noqa: BLE001
                pass

    # -------------------------------------------- training attach --
    def attach_training(self, ff):
        """Install the controller as the model's dynamic-recompilation
        hook: ``fit()`` and the Supervisor evaluate ``trigger`` once per
        step and rebuild the jitted step when a swap happened — the
        reference ``recompile_on_condition`` contract."""
        every = max(1, self.policy.poll_every)

        def trigger(rs) -> bool:
            if rs.iteration % every:
                return False
            return self.step_once(ff) in ("adopted", "rolled_back")

        return ff.recompile_on_condition(trigger, lambda rs: None)

    # ------------------------------------------------- serving side --
    def serve_replan(self, repo, name: str, *, scheduler=None,
                     builder: Optional[Callable[[], Any]] = None,
                     dmesh=None, session=None) -> str:
        """One serving-side adaptation pass for model ``name`` in
        ``repo``: serving drift (measured decode vs the plan's
        predictions) / an open breaker / degraded links trigger targeted
        re-calibration, then ``builder()`` produces the re-searched
        session (``optimize_serving_strategy`` +
        ``build_serving_plan_session`` in a real deployment; tests pass
        a lightweight factory) and the swap rides ``repo.hot_swap``
        under graceful drain. Returns the outcome tag; call
        :meth:`rescore_serving` after post-swap traffic to arm the
        measured rollback."""
        session = session if session is not None else repo.get(name)
        t0 = time.perf_counter()
        ev: List[Dict[str, Any]] = []
        try:
            from ..obs.drift import serving_drift_report
            rep = serving_drift_report(session, cache_dir=self.cache_dir)
            if rep and rep.get("n_out_of_band"):
                ev.append({"trigger": "serving_drift",
                           "n_out_of_band": rep["n_out_of_band"]})
        except Exception:  # noqa: BLE001
            pass
        if scheduler is not None:
            try:
                if scheduler.stats().get("circuit") == "open":
                    ev.append({"trigger": "breaker", "model": name})
            except Exception:  # noqa: BLE001
                pass
        from . import faults
        if faults.degraded_links():
            ev.append({"trigger": "degraded",
                       "links": faults.degraded_links()})
        if not ev:
            return "quiet"
        if self._clock() < self._cooldown_until:
            return "cooldown"
        trigger = ev[0]["trigger"]
        status.set_value("replan_candidate", "searching")
        with obs_events.span("replan.recalibrate", trigger=trigger,
                             mode="serving"):
            table = self._table()
            remeasured = table.remeasure_stale(dmesh)
        if builder is None:
            # evidence handled as far as this process can: tables are
            # healed; re-search/rebuild belongs to the deployment layer
            self._finish(None, trigger, "recalibrated",
                         {"remeasured": sorted(remeasured)}, ev, t0)
            return "recalibrated"
        with obs_events.span("replan.search", trigger=trigger,
                             mode="serving"):
            new_session = builder()
        old = list(repo.get_instances(name))
        baseline = {}
        try:
            baseline = dict(session.measured_profile())
        except Exception:  # noqa: BLE001
            pass
        with obs_events.span("replan.swap", trigger=trigger,
                             mode="serving"):
            repo.hot_swap(name, new_session, scheduler=scheduler)
        self.replans += 1
        self._swap_ctx = {"repo": repo, "name": name, "old": old,
                          "scheduler": scheduler, "baseline": baseline}
        self._finish(None, trigger, "adopted",
                     {"remeasured": sorted(remeasured),
                      "mode": "serving"}, ev, t0)
        return "adopted"

    def rescore_serving(self, session=None) -> str:
        """The serving analog of the training A/B guard: compare the
        swapped-in session's measured decode profile (needs post-swap
        traffic) against the pre-swap baseline on shared buckets; a
        ``guard_band`` regression swaps the old instances back under the
        same drain path. Returns ``adopted`` | ``rolled_back`` |
        ``pending`` (no comparable traffic yet)."""
        ctx = getattr(self, "_swap_ctx", None)
        if ctx is None:
            return "pending"
        repo, name = ctx["repo"], ctx["name"]
        session = session if session is not None else repo.get(name)
        try:
            prof = dict(session.measured_profile())
        except Exception:  # noqa: BLE001
            prof = {}
        worse = []
        for bucket, base in (ctx["baseline"] or {}).items():
            cur = prof.get(bucket)
            if not cur or not base:
                continue
            b, c = base.get("decode_step_s"), cur.get("decode_step_s")
            if b and c and c > b * self.policy.guard_band:
                worse.append((bucket, b, c))
        if not worse:
            if prof:
                self._swap_ctx = None
            return "adopted" if prof else "pending"
        with obs_events.span("replan.swap", mode="serving",
                             rollback=True):
            repo.hot_swap(name, ctx["old"],
                          scheduler=ctx["scheduler"])
        self.rollbacks += 1
        self._swap_ctx = None
        status.record("replan_rollbacks")
        status.set_value("replan_last_outcome", "rolled_back")
        _count("serving_guard", "rolled_back")
        obs_events.instant("replan.decision", trigger="serving_guard",
                           outcome="rolled_back")
        return "rolled_back"
