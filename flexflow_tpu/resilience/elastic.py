"""Elastic re-plan: shrink the machine, re-search, reshard, continue.

The search layer's whole premise is that the parallelization adapts to
the machine it has (PAPER.md); losing a device mid-run just means the
machine changed. The arrays-redistribution line of work (PAPERS.md,
arxiv 2112.01075 + 2004.13336) treats resharding a live state onto a
different device layout as a first-class operation — here it rides the
existing ``restore_model_checkpoint`` replace path, which places host
numpy leaves against the CURRENT template shardings through the reshard
planner's host→device step (``parallel/reshard.place_host``): each
surviving device is handed only its own shard of a sharded leaf, so the
restore never stages whole-array per-device replicas on the shrunken
mesh (``FF_NAIVE_RESHARD=1`` restores the old ``device_put`` path).

Flow on (injected) device loss:

  1. rebuild the :class:`MachineSpec` for the shrunken mesh — the
     adopted device count is the largest count <= the surviving devices
     that divides the global batch (batch divisibility is the same
     constraint the search itself obeys);
  2. recompile: ``FFModel.compile`` with the new spec re-runs the
     strategy search **warm** from the persistent calibration tables
     (PR 1: zero re-measurement on warm load) — or the DP preset under
     ``--only-data-parallel`` — on the new mesh;
  3. the caller restores the last checkpoint, which reshards the saved
     host state onto the new strategy's placements;
  4. the adoption is recorded: obs counters/instants, the always-on
     :mod:`.status` block, and an ``elastic_replan`` annotation on the
     search's strategy audit record when one was written.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

from ..obs import events as obs_events
from ..obs.metrics_registry import REGISTRY
from . import status

log = logging.getLogger("flexflow_tpu")


def surviving_device_count(n_alive: int, batch_size: int) -> int:
    """Largest usable device count <= ``n_alive``: the global batch must
    divide over the data-parallel shards (the constraint every strategy
    the search emits already satisfies)."""
    for n in range(max(1, n_alive), 0, -1):
        if batch_size % n == 0:
            return n
    return 1


def shrunken_world_size(n_alive_ranks: int, batch_size: int,
                        devices_per_rank: int = 1) -> int:
    """Largest usable PROCESS count <= ``n_alive_ranks`` after rank
    loss: the global batch must divide over the shrunken world's total
    devices, same constraint as :func:`surviving_device_count` one
    level up. ``batch_size`` 0/unknown accepts any survivor count.
    Used by the world supervisor's shrink path (cross-process elastic
    recovery, ISSUE 7)."""
    n_alive_ranks = max(1, n_alive_ranks)
    if batch_size <= 0:
        return n_alive_ranks
    for n in range(n_alive_ranks, 0, -1):
        if batch_size % (n * max(1, devices_per_rank)) == 0:
            return n
    return 1


def shrunken_spec(spec, n_devices: int):
    """A :class:`MachineSpec` for the post-loss machine: same hardware
    generation/constants, fewer devices. The physical ICI shape and any
    explicit fabric no longer describe the surviving set — drop them so
    the mesh refactorizes from the device count (the detect() path)."""
    return dataclasses.replace(
        spec, num_devices=n_devices, ici_shape=None,
        topology_override=None, num_slices=1, num_hosts=1)


def replan_on_device_loss(ff, n_lost: int,
                          batch_size: Optional[int] = None) -> int:
    """Re-plan ``ff`` for a mesh that lost ``n_lost`` devices; returns
    the adopted device count. Leaves params freshly initialized on the
    new mesh — the caller restores the checkpoint to reshard the real
    state onto it (``Supervisor._recover_device_loss`` does both)."""
    t0 = time.perf_counter()
    old_n = ff.dmesh.num_devices
    alive = max(1, old_n - max(1, n_lost))
    bs = int(batch_size or ff.config.batch_size)
    new_n = surviving_device_count(alive, bs)
    log.warning(
        "elastic re-plan: %d -> %d devices (%d lost, batch %d divides "
        "over %d); re-running strategy search on the shrunken mesh",
        old_n, new_n, n_lost, bs, new_n)
    spec = shrunken_spec(ff.dmesh.spec, new_n)
    # the old mesh's explicit layout cannot describe the survivor set
    ff.config.mesh_shape = None
    out_t = ff._output_tensor
    ff.strategy = None
    ff.executor = None
    ff._prebuilt_executor = None
    with obs_events.span("resilience.replan", old_devices=old_n,
                         new_devices=new_n):
        ff.compile(optimizer=ff.optimizer, loss_type=ff.loss_type,
                   metrics=list(ff.metrics), machine_spec=spec,
                   output_tensor=out_t)
    dt = time.perf_counter() - t0
    status.record("elastic_replans")
    REGISTRY.counter("ff_elastic_replans_total",
                     "Strategy re-plans after device loss").inc()
    REGISTRY.gauge("ff_mesh_devices",
                   "Devices in the active execution mesh"
                   ).set(float(ff.dmesh.num_devices))
    obs_events.counter("resilience.elastic_replan")
    obs_events.instant("resilience.elastic_replan", old_devices=old_n,
                       new_devices=ff.dmesh.num_devices, n_lost=n_lost,
                       replan_s=round(dt, 3))
    # the searched path wrote a fresh audit record for the new adoption;
    # stamp it as an elastic re-plan so the decision trail shows WHY the
    # strategy changed mid-run
    audit_path = getattr(ff, "_strategy_audit_path", None)
    if audit_path:
        from ..obs.audit import annotate_strategy_audit
        annotate_strategy_audit(audit_path, {
            "elastic_replan": {"old_devices": old_n,
                               "new_devices": ff.dmesh.num_devices,
                               "n_lost": n_lost, "step": ff._step,
                               "replan_s": round(dt, 3)}})
    return ff.dmesh.num_devices
