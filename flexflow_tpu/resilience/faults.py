"""Deterministic fault injection for resilience testing.

The reference has no fault-tolerance story (SURVEY.md §5: "failure
detection / elastic recovery: absent"); this harness makes failures a
*first-class, reproducible input* so the recovery paths (supervisor
restarts, checkpoint fallback, elastic re-plan) are exercised by normal
tests and CI instead of waiting for a real preemption.

Fault plan grammar (``FF_FAULT_PLAN`` env var or :func:`install`)::

    plan   := clause (';' clause)*          # ',' also accepted
    clause := kind '@' step (':' arg)*
    kind   := crash | nan | inf | corrupt_ckpt | truncate_ckpt
              | lose_device | infer_fail     # aliases: nan_grad, corrupt,
              | rank_crash | rank_hang       # truncate, lose, infer
              | corrupt_shard | crash_after_stage
              | infer_crash                  # hard replica death on the
                                             # N-th inference call
              | degrade_link                 # tier bandwidth drill
              | workload_shift               # live batch-shape drill

Examples::

    FF_FAULT_PLAN="crash@2"                  # raise SimulatedCrash before
                                             # global step 2 executes
    FF_FAULT_PLAN="nan@5"                    # poison params + loss with NaN
                                             # after step 5 runs
    FF_FAULT_PLAN="corrupt_ckpt@3"           # flip bytes in the step-3
                                             # checkpoint right after its save
    FF_FAULT_PLAN="truncate_ckpt@3"          # truncate its meta.json instead
    FF_FAULT_PLAN="lose_device@4:2"          # virtual loss of 2 devices
                                             # before step 4
    FF_FAULT_PLAN="crash@2;nan@6;lose@9"     # compose freely

Rank-scoped kinds (multi-process worlds, ISSUE 7) take the target rank
as the arg and fire ONLY in the process whose ``jax.process_index()``
matches (every rank parses the same plan; non-matching ranks simply
never consume the clause)::

    FF_FAULT_PLAN="rank_crash@3:1"           # rank 1 hard-exits (os._exit,
                                             # no cleanup) before step 3
    FF_FAULT_PLAN="rank_hang@3:1"            # rank 1 SIGSTOPs itself —
                                             # heartbeats stop, survivors
                                             # attribute it
    FF_FAULT_PLAN="corrupt_shard@2:1"        # flip bytes in rank 1's shard
                                             # of the committed step-2
                                             # multi-host checkpoint
    FF_FAULT_PLAN="crash_after_stage@2:1"    # rank 1 dies BETWEEN staging
                                             # its step-2 shard and the
                                             # manifest commit (torn-
                                             # checkpoint drill)

Closed-loop adaptation drills (ISSUE 20) — the chaos inputs the
``ReplanController`` (resilience/replan.py) heals. ``degrade_link``
scales a fabric tier's modeled bandwidth mid-run (the CPU-sim timing
path scales measured collective seconds by the factor, since a virtual
mesh has no physical link to slow), so prediction-vs-reality drift
fires deterministically; ``workload_shift`` changes the live global
batch shape. Both are one-shot and rank-scopable via a trailing rank
arg::

    FF_FAULT_PLAN="degrade_link@3:dcn:4"     # before step 3 the dcn tier
                                             # runs 4x slower (factor >= 1)
    FF_FAULT_PLAN="degrade_link@3:dcn:4:1"   # ...only in rank 1's process
    FF_FAULT_PLAN="workload_shift@5:16"      # before step 5 the live
                                             # global batch becomes 16
    FF_FAULT_PLAN="workload_shift@5:16:0"    # ...only in rank 0's process

Semantics:

  - steps are the **global** train-step counter (``FFModel._step``:
    number of completed optimizer steps, so "``crash@k``" fires before
    the k-th step runs and after checkpoint ``k`` — if any — was saved);
  - every clause fires **exactly once per process**: an in-process
    restart (the supervisor's recovery loop) does not re-fire it, which
    is what makes crash-and-resume runs terminate deterministically;
  - injection sites are the train-step driver (``FFModel.
    _run_train_step``) and the checkpoint writer
    (``CheckpointManager``); both check :func:`active` first, so a run
    with no plan pays one cached attribute read per step.

Every firing is counted in :mod:`.status` (always on) and as an
``obs.events`` instant + counter (when tracing is enabled).
"""
from __future__ import annotations

import dataclasses
import itertools
import os
import re
from typing import List, Optional

from ..obs import events as obs_events
from . import status

ENV_VAR = "FF_FAULT_PLAN"

#: alias -> canonical kind
_KINDS = {
    "crash": "crash",
    "nan": "nan", "nan_grad": "nan",
    "inf": "inf",
    "corrupt_ckpt": "corrupt_ckpt", "corrupt": "corrupt_ckpt",
    "truncate_ckpt": "truncate_ckpt", "truncate": "truncate_ckpt",
    "lose_device": "lose_device", "lose": "lose_device",
    "infer_fail": "infer_fail", "infer": "infer_fail",
    "infer_crash": "infer_crash",
    "rank_crash": "rank_crash",
    "rank_hang": "rank_hang",
    "corrupt_shard": "corrupt_shard",
    "crash_after_stage": "crash_after_stage",
    "degrade_link": "degrade_link", "degrade": "degrade_link",
    "workload_shift": "workload_shift", "shift": "workload_shift",
}

#: exit code of an injected hard rank crash (``rank_crash`` /
#: ``crash_after_stage``): ``os._exit`` with no cleanup, so to the rest
#: of the world it is indistinguishable from a SIGKILL'd process.
RANK_CRASH_EXIT = 13

#: multi-arg clauses (``degrade_link@N:tier:factor[:rank]``) extend the
#: original single-arg grammar; ``.`` is an arg char so float factors
#: parse. ``Fault.arg`` stays the FIRST arg for back-compat.
_CLAUSE_RE = re.compile(r"^([a-z_]+)@(\d+)((?::[A-Za-z0-9_.]+)*)$")


class FaultError(RuntimeError):
    """Base of all injected failures."""


class SimulatedCrash(FaultError):
    """Injected process crash (``crash@N``)."""

    def __init__(self, step: int):
        super().__init__(f"injected crash before step {step}")
        self.step = step


class DeviceLoss(FaultError):
    """Injected loss of ``n_lost`` devices (``lose_device@N:k``) — the
    supervisor's elastic path catches this and re-plans for the
    shrunken mesh."""

    def __init__(self, step: int, n_lost: int = 1):
        super().__init__(
            f"injected loss of {n_lost} device(s) before step {step}")
        self.step = step
        self.n_lost = n_lost


@dataclasses.dataclass
class Fault:
    kind: str
    step: int
    arg: Optional[str] = None
    fired: bool = False
    #: full arg tuple of a multi-arg clause; synced with ``arg`` (the
    #: first element) so hand-built single-arg faults keep working
    args: tuple = ()

    def __post_init__(self):
        if not self.args and self.arg is not None:
            self.args = (self.arg,)
        elif self.args and self.arg is None:
            self.arg = self.args[0]


class FaultPlan:
    """An ordered list of one-shot fault clauses."""

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = list(faults or [])

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        faults = []
        for raw in re.split(r"[;,]", text or ""):
            raw = raw.strip()
            if not raw:
                continue
            m = _CLAUSE_RE.match(raw)
            if m is None:
                raise ValueError(
                    f"bad fault clause {raw!r} (grammar: "
                    f"kind@step[:arg]*, "
                    f"kinds: {sorted(set(_KINDS.values()))})")
            kind = _KINDS.get(m.group(1))
            if kind is None:
                raise ValueError(
                    f"unknown fault kind {m.group(1)!r} in {raw!r} "
                    f"(known: {sorted(_KINDS)})")
            args = tuple(m.group(3).split(":")[1:]) if m.group(3) else ()
            faults.append(Fault(kind, int(m.group(2)), args=args))
        return cls(faults)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        """``FF_FAULT_PLAN`` plus — only in world epoch 0 —
        ``FF_FAULT_PLAN_EPOCH0``. The epoch-gated variant is how a
        world-supervised run injects a rank fault exactly once: clauses
        fire once per *process*, so a relaunched world (fresh processes,
        same environment) would re-fire a plain ``FF_FAULT_PLAN`` clause
        forever; the epoch-0 plan dies with the epoch it wounded."""
        parts = [os.environ.get(ENV_VAR, "")]
        if int(os.environ.get("FF_WORLD_EPOCH", "0") or 0) == 0:
            parts.append(os.environ.get(ENV_VAR + "_EPOCH0", ""))
        return cls.parse(";".join(p for p in parts if p))

    # ------------------------------------------------------------------
    def unfired(self) -> int:
        return sum(1 for f in self.faults if not f.fired)

    def fire(self, kind: str, step: int,
             rank: Optional[int] = None,
             rank_index: int = 0) -> Optional[Fault]:
        """Consume and return the first unfired clause of ``kind`` due
        at ``step``; None otherwise. ``rank`` (rank-scoped kinds: the
        caller's process index) must match the clause's rank arg — the
        arg at ``rank_index`` (0 for the classic single-arg kinds; the
        trailing position for multi-arg kinds like ``degrade_link``) —
        a clause targeting another rank is left unfired for THAT rank's
        process to consume."""
        for f in self.faults:
            if f.fired or f.kind != kind or f.step != step:
                continue
            if rank is not None:
                a = f.args[rank_index] \
                    if len(f.args) > rank_index else None
                if a is not None and int(a) != rank:
                    continue
            f.fired = True
            status.record_fault(kind, step)
            obs_events.counter(f"resilience.fault.{kind}")
            obs_events.instant("resilience.fault_injected",
                               kind=kind, step=step, arg=f.arg)
            return f
        return None


# ---------------------------------------------------------------------------
# process-wide plan
# ---------------------------------------------------------------------------
_plan: Optional[FaultPlan] = None


def get_plan() -> FaultPlan:
    global _plan
    if _plan is None:
        _plan = FaultPlan.from_env()
    return _plan


def install(plan) -> FaultPlan:
    """Set the process-wide plan (a :class:`FaultPlan` or a grammar
    string); the API analog of the ``FF_FAULT_PLAN`` env var. The
    inference-call counter restarts at 0 so ``infer_fail@N`` indices in
    the new plan count from ITS installation, not from whatever calls a
    previous plan saw."""
    global _plan, _infer_calls
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    _infer_calls = itertools.count()
    return _plan


def clear() -> None:
    """Drop the installed plan; the env var is re-read on next use.
    Also restarts the inference-call counter (see :func:`install`) and
    heals any registered link degradation / pending workload shift."""
    global _plan, _infer_calls, _workload_shift
    _plan = None
    _infer_calls = itertools.count()
    _link_degradation.clear()
    _workload_shift = None


def active() -> bool:
    """Cheap per-step check: does any unfired clause remain?"""
    return get_plan().unfired() > 0


# ---------------------------------------------------------------------------
# closed-loop adaptation drills (ISSUE 20): link degradation + workload
# shift state the replan controller and the CPU-sim timing path consult
# ---------------------------------------------------------------------------

#: tier name -> slowdown factor (>= 1.0); empty = healthy fabric
_link_degradation: dict = {}

#: global batch size requested by a fired workload_shift clause, until
#: a reader consumes it via :func:`pending_workload_shift`
_workload_shift: Optional[int] = None


def set_link_degradation(tier: str, factor: float) -> None:
    """Register (or heal, with ``factor <= 1``) a modeled bandwidth
    slowdown for one fabric tier (``"ici"`` / ``"dcn"`` / ``"host"``).
    Consulted by the analytic cost model's tier pricing and by the
    calibration microbenches, so predictions AND fresh measurements
    both see the degraded link."""
    f = float(factor)
    if f <= 1.0:
        _link_degradation.pop(tier, None)
    else:
        _link_degradation[tier] = f


def link_degradation(tier: str) -> float:
    """Current slowdown factor of one tier (1.0 = healthy)."""
    return _link_degradation.get(tier, 1.0)


def degraded_links() -> dict:
    """``{tier: factor}`` of every currently degraded tier."""
    return dict(_link_degradation)


def pending_workload_shift() -> Optional[int]:
    """The new global batch size requested by a fired
    ``workload_shift`` clause; consumed (cleared) by the read — the
    replan controller treats it as a live-shape trigger."""
    global _workload_shift
    b, _workload_shift = _workload_shift, None
    return b


def maybe_degrade(step: int) -> Optional[tuple]:
    """``degrade_link@N:tier:factor[:rank]`` clauses due before ``step``
    executes: register the tier slowdown and return ``(tier, factor)``
    (None = no clause due). One-shot like every clause; the degradation
    itself persists until :func:`clear` or a healing
    :func:`set_link_degradation` call."""
    f = get_plan().fire("degrade_link", step, rank=_rank(),
                        rank_index=2)
    if f is None:
        return None
    tier = (f.args[0] if len(f.args) > 0 else "") or "dcn"
    factor = float(f.args[1]) if len(f.args) > 1 and f.args[1] else 2.0
    set_link_degradation(tier, factor)
    return (tier, factor)


def maybe_workload_shift(step: int) -> Optional[int]:
    """``workload_shift@N[:batch][:rank]`` clauses due before ``step``
    executes: record the requested global batch size (default: double
    the unknown current one, encoded as 0 for 'caller decides') and
    return it (None = no clause due)."""
    global _workload_shift
    f = get_plan().fire("workload_shift", step, rank=_rank(),
                        rank_index=1)
    if f is None:
        return None
    b = int(f.args[0]) if len(f.args) > 0 and f.args[0] else 0
    _workload_shift = b
    return b


def _rank() -> int:
    """This process's rank; 0 when jax is not importable yet."""
    try:
        import jax
        return jax.process_index()
    except Exception:  # pragma: no cover - pre-jax callers
        return 0


def raise_pending(step: int) -> None:
    """Crash / device-loss / rank-scoped clauses due before ``step``
    executes. The non-raising adaptation drills (``degrade_link`` /
    ``workload_shift``) fire here too — one injection site in the
    train-step driver covers every step-indexed kind."""
    maybe_degrade(step)
    maybe_workload_shift(step)
    plan = get_plan()
    if plan.fire("crash", step) is not None:
        raise SimulatedCrash(step)
    f = plan.fire("lose_device", step)
    if f is not None:
        raise DeviceLoss(step, n_lost=int(f.arg or 1))
    if plan.fire("rank_crash", step, rank=_rank()) is not None:
        # hard death — no atexit, no finally, heartbeats just stop;
        # the surviving world must notice via resilience/coord.py
        os._exit(RANK_CRASH_EXIT)
    if plan.fire("rank_hang", step, rank=_rank()) is not None:
        # freeze the WHOLE process (heartbeat thread included): the
        # truthful simulation of a wedged rank. SIGKILL still works on
        # a stopped process — the world supervisor reaps it.
        import signal
        os.kill(os.getpid(), signal.SIGSTOP)


def maybe_crash_after_stage(step: int) -> None:
    """``crash_after_stage@N:r``: die between staging this rank's shard
    (fsynced, debris-only) and the manifest commit — the torn-multi-host-
    checkpoint drill. Called by the two-phase writer right after the
    shard fsync."""
    if get_plan().fire("crash_after_stage", step, rank=_rank()) \
            is not None:
        os._exit(RANK_CRASH_EXIT)


def maybe_corrupt_shard(step: int, shard_path: str) -> None:
    """``corrupt_shard@N:r``: flip bytes in THIS rank's shard of the
    committed multi-host checkpoint ``step`` — quorum restore must rule
    the step out on every rank."""
    if get_plan().fire("corrupt_shard", step, rank=_rank()) is not None:
        if os.path.exists(shard_path):
            _flip_bytes(shard_path)


#: process-wide inference-call counter for ``infer_fail@N`` clauses.
#: Advances only while a plan is active (``InferenceSession.infer``
#: gates on :func:`active` first), so call indices are deterministic
#: for a plan installed before serving starts. ``itertools.count`` is
#: safe under the serving workers' concurrency in CPython.
_infer_calls = itertools.count()


def raise_infer_fault() -> None:
    """Inference-path clauses (``infer_fail@N``): the N-th
    ``InferenceSession.infer`` call made while a plan is active raises
    :class:`FaultError` — the serving chaos harness for circuit-breaker
    and batch-poison paths. Each clause is one-shot like every other
    kind; compose K consecutive clauses to trip a breaker with
    threshold K."""
    step = next(_infer_calls)
    if get_plan().fire("infer_fail", step) is not None:
        raise FaultError(f"injected inference failure at call {step}")
    if get_plan().fire("infer_crash", step) is not None:
        # hard death of a serving REPLICA mid-request (``infer_crash@N``):
        # no drain, no socket close — the fleet router must notice via
        # its health poll / transport errors and reroute. Same exit
        # code as a rank crash: to everything else it is a SIGKILL.
        os._exit(RANK_CRASH_EXIT)


def poison_value(step: int) -> Optional[float]:
    """NaN/Inf gradient-corruption clauses due after ``step`` ran:
    returns the poison value, or None."""
    plan = get_plan()
    if plan.fire("nan", step) is not None:
        return float("nan")
    if plan.fire("inf", step) is not None:
        return float("inf")
    return None


def _pick_state_file(step_dir: str) -> Optional[str]:
    """The checkpoint payload file to corrupt: the pickle when present,
    else the largest file under the orbax state dir."""
    pkl = os.path.join(step_dir, "state.pkl")
    if os.path.exists(pkl):
        return pkl
    sdir = os.path.join(step_dir, "state")
    best, best_sz = None, -1
    for root, _, files in os.walk(sdir):
        for fn in files:
            p = os.path.join(root, fn)
            sz = os.path.getsize(p)
            if sz > best_sz:
                best, best_sz = p, sz
    return best


def _flip_bytes(path: str) -> None:
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        off = size // 2
        n = min(64, max(1, size - off))
        f.seek(off)
        chunk = f.read(n)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


def maybe_corrupt_checkpoint(step: int, step_dir: str) -> None:
    """Checkpoint-corruption clauses, applied right after the save of
    ``step`` lands (called by ``CheckpointManager``)."""
    plan = get_plan()
    if plan.fire("corrupt_ckpt", step) is not None:
        target = _pick_state_file(step_dir)
        if target is not None:
            _flip_bytes(target)
    if plan.fire("truncate_ckpt", step) is not None:
        meta = os.path.join(step_dir, "meta.json")
        if os.path.exists(meta):
            with open(meta, "r+b") as f:
                f.truncate(max(1, os.path.getsize(meta) // 2))
