"""Resilience subsystem: fault injection, verified atomic checkpoints,
auto-resume, elastic re-plan, and cross-process world recovery.

The reference has no fault-tolerance mechanism (SURVEY.md §5); TPU pods
are preemptible by design, so this layer makes failure a normal input:

  - :mod:`.faults` — deterministic fault injection
    (``FF_FAULT_PLAN="crash@2;nan@5;lose_device@9:2"`` or
    :func:`faults.install`): crash-at-step, NaN/Inf gradient
    corruption, checkpoint corruption/truncation, virtual device loss,
    and rank-scoped multi-process faults (``rank_crash@N:r``,
    ``rank_hang@N:r``, ``corrupt_shard@N:r``, ``crash_after_stage@N:r``);
  - :mod:`.coord` — multi-process failure detection: per-rank
    heartbeats over the jax coordination KV store, bounded barriers
    (never hang forever — timeouts raise :class:`~.coord.RankFailure`
    with the dead rank attributed), and the monotonic world epoch;
  - hardened checkpoints (``runtime/checkpoint.py``) — atomic
    staging-dir + rename saves, a per-leaf shape/dtype/CRC32 manifest
    verified on restore, async background saves, restore that falls
    back past corrupt or partial steps, and (multi-host) a two-phase
    stage/commit protocol with all-rank quorum restore;
  - :mod:`.supervisor` — a resilient training driver: auto-resume from
    the newest valid checkpoint (exact dataloader rng/epoch/position
    resume), bounded restarts with exponential backoff + jitter,
    NaN-loss rollback to the last good checkpoint; plus the
    launcher-side :class:`~.supervisor.WorldSupervisor` that re-forms a
    multi-process world after rank failure (relaunch under a restart
    budget, else shrink to a batch-divisible survivor world);
  - :mod:`.elastic` — on device loss, rebuild the machine spec for the
    shrunken mesh, re-run the strategy search warm from the persistent
    calibration tables, and reshard the restored state onto the new
    strategy via the checkpoint replace path;
  - :mod:`.replan` — closed-loop plan adaptation: drift-marked
    calibration rows re-measured in place, a background re-search on
    the refreshed tables, and verifier-gated hot-swap with bit-exact
    state carryover, a measured A/B guard, and hysteresis + exponential
    cooldown so a degraded fleet heals without flapping;
  - :mod:`.status` — always-on restart/fault/checkpoint/world facts,
    merged into both HTTP front-ends' ``/healthz``.

See docs/resilience.md and docs/distributed.md.
"""
from . import coord, elastic, faults, replan, status
from .coord import EXIT_RANK_FAILURE, Coordinator, RankFailure
from .faults import (DeviceLoss, FaultError, FaultPlan, SimulatedCrash,
                     install as install_fault_plan)
from .replan import ReplanController, ReplanPolicy
from .supervisor import (RestartBudgetExceeded, Supervisor, WorldFailure,
                         WorldSupervisor, run_supervised,
                         run_world_member)

__all__ = [
    "faults", "status", "elastic", "coord", "replan",
    "FaultPlan", "FaultError", "SimulatedCrash", "DeviceLoss",
    "install_fault_plan",
    "ReplanController", "ReplanPolicy",
    "Supervisor", "run_supervised", "RestartBudgetExceeded",
    "Coordinator", "RankFailure", "EXIT_RANK_FAILURE",
    "WorldSupervisor", "WorldFailure", "run_world_member",
]
