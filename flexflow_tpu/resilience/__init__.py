"""Resilience subsystem: fault injection, verified atomic checkpoints,
auto-resume, and elastic re-plan on device loss.

The reference has no fault-tolerance mechanism (SURVEY.md §5); TPU pods
are preemptible by design, so this layer makes failure a normal input:

  - :mod:`.faults` — deterministic fault injection
    (``FF_FAULT_PLAN="crash@2;nan@5;lose_device@9:2"`` or
    :func:`faults.install`): crash-at-step, NaN/Inf gradient
    corruption, checkpoint corruption/truncation, virtual device loss;
  - hardened checkpoints (``runtime/checkpoint.py``) — atomic
    staging-dir + rename saves, a per-leaf shape/dtype/CRC32 manifest
    verified on restore, async background saves, and restore that falls
    back past corrupt or partial steps;
  - :mod:`.supervisor` — a resilient training driver: auto-resume from
    the newest valid checkpoint (exact dataloader rng/epoch/position
    resume), bounded restarts with exponential backoff + jitter, and
    NaN-loss rollback to the last good checkpoint;
  - :mod:`.elastic` — on device loss, rebuild the machine spec for the
    shrunken mesh, re-run the strategy search warm from the persistent
    calibration tables, and reshard the restored state onto the new
    strategy via the checkpoint replace path;
  - :mod:`.status` — always-on restart/fault/checkpoint facts, merged
    into both HTTP front-ends' ``/healthz``.

See docs/resilience.md.
"""
from . import elastic, faults, status
from .faults import (DeviceLoss, FaultError, FaultPlan, SimulatedCrash,
                     install as install_fault_plan)
from .supervisor import RestartBudgetExceeded, Supervisor, run_supervised

__all__ = [
    "faults", "status", "elastic",
    "FaultPlan", "FaultError", "SimulatedCrash", "DeviceLoss",
    "install_fault_plan",
    "Supervisor", "run_supervised", "RestartBudgetExceeded",
]
