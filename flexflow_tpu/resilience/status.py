"""Process-wide resilience status — the always-on half of the
resilience telemetry.

``obs.events`` counters vanish when tracing is disabled; a ``/healthz``
probe or a test asserting "the supervisor really did restart once" needs
numbers that exist regardless. This module is that: a thread-safe dict of
restart/fault/checkpoint facts, mirrored into the Prometheus registry by
the writers (supervisor, checkpoint manager, elastic re-plan) and merged
into both HTTP front-ends' ``/healthz`` response.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

_lock = threading.Lock()


def _fresh() -> Dict[str, Any]:
    return {
        "restarts": 0,                    # supervisor recoveries, any cause
        "nan_rollbacks": 0,               # restarts caused by non-finite loss
        "elastic_replans": 0,             # device-loss re-plan + reshard
        "faults_injected": 0,             # fault-plan clauses that fired
        "checkpoints_saved": 0,
        "corrupt_checkpoints_skipped": 0,  # restore fallbacks past bad steps
        "last_fault": None,               # "kind@step" of the newest firing
        "last_checkpoint_step": None,
        "last_checkpoint_unix_s": None,
        # multi-process world (resilience/coord.py); epoch/rank/size are
        # set when a coordinator starts, failures as they are detected
        "world_epoch": 0,
        "world_rank": 0,
        "world_size": 1,
        "rank_failures": 0,               # peer failures detected here
        "last_rank_failure": None,        # "rank=R epoch=E reason"
        # newest flight-recorder dump of this process (obs/flight.py):
        # the bounded black-box written at RankFailure / NaN-rollback /
        # crash sites, referenced from /healthz so a probe can point an
        # operator straight at the evidence
        "last_flight_record": None,
        # closed-loop plan adaptation (resilience/replan.py): the
        # controller mirrors its state machine here so /healthz answers
        # "is the fleet healing itself, and did the last swap stick"
        "replans": 0,                     # adopted plan swaps
        "replan_rollbacks": 0,            # A/B-guard reverts
        "replan_last_trigger": None,      # "drift" | "degraded" | ...
        "replan_last_outcome": None,      # "adopted" | "rolled_back" |
                                          # "rejected" | "no_win" | ...
        "replan_candidate": None,         # "idle"|"searching"|"pending"
        "replan_cooldown_until_unix_s": None,
    }


_data: Dict[str, Any] = _fresh()


def record(key: str, n: int = 1) -> None:
    with _lock:
        _data[key] = (_data.get(key) or 0) + n


def set_value(key: str, value: Any) -> None:
    with _lock:
        _data[key] = value


def record_fault(kind: str, step: int) -> None:
    with _lock:
        _data["faults_injected"] += 1
        _data["last_fault"] = f"{kind}@{step}"


def record_checkpoint(step: int) -> None:
    with _lock:
        _data["checkpoints_saved"] += 1
        _data["last_checkpoint_step"] = int(step)
        _data["last_checkpoint_unix_s"] = time.time()


def snapshot() -> Dict[str, Any]:
    with _lock:
        return dict(_data)


def reset() -> None:
    """Back to process-start state (tests)."""
    with _lock:
        _data.clear()
        _data.update(_fresh())


def checkpoint_age_s() -> Optional[float]:
    with _lock:
        t = _data.get("last_checkpoint_unix_s")
    return None if t is None else max(0.0, time.time() - t)


def health_fields() -> Dict[str, Any]:
    """The resilience block of the ``/healthz`` response: the snapshot
    plus a derived time-since-last-checkpoint age (probes alert on age,
    not on a unix timestamp)."""
    out = snapshot()
    age = checkpoint_age_s()
    if age is not None:
        out["checkpoint_age_s"] = round(age, 3)
    # cooldown as a remaining-seconds age (probes alert on remaining,
    # not on a unix timestamp), clamped at 0 once it elapsed
    until = out.pop("replan_cooldown_until_unix_s", None)
    out["replan_cooldown_remaining_s"] = \
        0.0 if until is None else round(max(0.0, until - time.time()), 3)
    return out
