"""fftrace: merge per-rank trace dumps into one Chrome trace.

Each rank of a multi-process world dumps its span ring at the end of
training (``flexflow_tpu.obs.trace_export.dump_rank_trace`` →
``.ffcache/trace_rank<r>_epoch<e>.json``) with a clock anchor sampled
at the coordinator's epoch-scoped KV barrier release
(``resilience/coord.py::Coordinator.clock_sync``) — the same physical
instant on every rank. This tool places all the dumps on ONE timeline:

  - events from rank r are shifted so the anchor instant is t=0 —
    monotonic per-rank clocks align without trusting cross-host wall
    clocks (dumps without an anchor are rebased to their own earliest
    event and flagged);
  - every (rank, world-epoch) pair becomes its own process lane, named
    ``rank R · epoch E`` and sorted epoch-major — a re-formed world's
    epochs stack as separate lanes instead of interleaving;
  - counters export as Chrome 'C' counter events, thread names as 'M'
    metadata, so the merge is readable in Perfetto / chrome://tracing.

Flight-recorder dumps (``flight_rank<r>_epoch<e>.json``) are accepted
as inputs too — their bounded event tails merge the same way.

Serving-process dumps (``trace_serving_<pid>.json``, written by
``trace_export.dump_serving_trace``) merge as their own
``serving pid P`` lanes, placed after the worker ranks: one Chrome
trace shows a request's full lifecycle spans — admission, queue wait,
batch assembly, prefill, per-segment decode, response — linked across
the serving front-end and scheduler threads by per-request flow events
(spans sharing one ``trace`` id).

Usage:
    python tools/fftrace.py                      # merge .ffcache dumps
    python tools/fftrace.py a.json b.json -o merged.json
    python tools/fftrace.py --cache-dir /path/.ffcache
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".ffcache")


def _load_dump(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception as e:  # noqa: BLE001 — skip unreadable inputs
        print(f"fftrace: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(doc.get("events"), list):
        print(f"fftrace: skipping {path}: no events list",
              file=sys.stderr)
        return None
    doc["_path"] = path
    return doc


def _anchor_perf(doc: Dict[str, Any]) -> Optional[float]:
    clock = doc.get("clock") or {}
    v = clock.get("perf_s")
    try:
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def _rank_num(d: Dict[str, Any]) -> int:
    """Numeric sort key for a dump's rank. Worker ranks are ints;
    launcher-side flight records carry ``rank="launcher"`` — sort those
    after every worker instead of crashing the merge. Serving dumps sit
    outside the training world entirely: clamp them past the launcher
    so their lanes trail every rank."""
    if d.get("role") == "serving":
        return (1 << 20) + 1
    r = d.get("rank", 0)
    try:
        return int(r)
    except (TypeError, ValueError):
        return 1 << 20


def merge_rank_traces(paths: List[str]) -> Dict[str, Any]:
    """Merge rank dump files into one Chrome trace-event document.
    Per-dump event conversion delegates to
    ``flexflow_tpu.obs.trace_export.to_chrome_trace`` — one exporter,
    whether the trace is single-rank or merged."""
    from flexflow_tpu.obs.trace_export import to_chrome_trace
    dumps = [d for d in (_load_dump(p) for p in paths) if d is not None]
    if not dumps:
        raise ValueError("no readable rank dumps to merge")
    # lane order: epoch-major, then rank (flight records after their
    # rank's full dump) — each world incarnation reads as its own block
    dumps.sort(key=lambda d: (int(d.get("world_epoch") or 0),
                              _rank_num(d), bool(d.get("reason"))))
    # one shared origin: the earliest anchor-relative (or raw) instant
    # across all dumps, so no event lands at negative time
    rel_starts = []
    for d in dumps:
        anchor = _anchor_perf(d)
        tss = [e["ts"] for e in d["events"]]
        if not tss:
            continue
        base = anchor if anchor is not None else min(tss)
        rel_starts.append(min(t - base for t in tss))
    origin = min(rel_starts, default=0.0)
    events: List[Dict[str, Any]] = []
    lanes = []
    for i, d in enumerate(dumps):
        rank = d.get("rank", 0)
        epoch = int(d.get("world_epoch") or 0)
        # pid is the lane identity: strictly per-dump (enumerate), so a
        # rank's full dump and its flight record for the same epoch can
        # never collapse into one mislabeled lane
        pid = i + 1
        anchor = _anchor_perf(d)
        aligned = anchor is not None
        base = anchor if aligned else min(
            (e["ts"] for e in d["events"]), default=0.0)
        serving = d.get("role") == "serving"
        if serving:
            name = f"serving pid {d.get('pid', '?')}"
        else:
            name = f"rank {rank} · epoch {epoch}"
        if not aligned:
            name += " (unaligned)"
        reason = d.get("reason")
        if reason:                    # a flight record, not a full dump
            name += f" [flight: {reason}]"
        # sort: epoch block, then rank, flights after full dumps, the
        # launcher (rank_num clamped) at its epoch's tail, serving
        # lanes (rank_num clamped one past the launcher) after that
        sort_index = (epoch * 4096 + min(_rank_num(d), 1025)
                      + (2048 if reason else 0))
        sub = to_chrome_trace(d["events"], d.get("counters") or {},
                              pid=pid, process_name=name,
                              sort_index=sort_index,
                              base=base + origin)
        events.extend(sub["traceEvents"])
        lanes.append({"pid": pid, "rank": rank, "epoch": epoch,
                      "role": d.get("role", "rank"),
                      "aligned": aligned,
                      "n_events": len(d["events"]),
                      "dropped": d.get("dropped",
                                       d.get("dropped_events", 0)),
                      "source": d["_path"]})
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"lanes": lanes}}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fftrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("inputs", nargs="*",
                    help="rank dump files (default: every "
                         "trace_rank*_epoch*.json and "
                         "trace_serving_*.json in the cache dir)")
    ap.add_argument("-o", "--output", default=None,
                    help="merged Chrome trace path "
                         "(default: <cache>/trace_merged.json)")
    ap.add_argument("--cache-dir", default=_DEFAULT_CACHE,
                    help="where rank dumps live (default: repo "
                         ".ffcache)")
    ap.add_argument("--include-flights", action="store_true",
                    help="also merge flight_rank*_epoch*.json records")
    a = ap.parse_args(argv)
    paths = list(a.inputs)
    if not paths:
        paths = sorted(glob.glob(os.path.join(
            a.cache_dir, "trace_rank*_epoch*.json")))
        paths += sorted(glob.glob(os.path.join(
            a.cache_dir, "trace_serving_*.json")))
        if a.include_flights:
            paths += sorted(glob.glob(os.path.join(
                a.cache_dir, "flight_rank*_epoch*.json")))
    if not paths:
        print("fftrace: no rank dumps found (run with FF_TRACE=1 in a "
              "multi-process world, or FF_TRACE_DUMP=1 anywhere)",
              file=sys.stderr)
        return 2
    doc = merge_rank_traces(paths)
    out = a.output or os.path.join(a.cache_dir, "trace_merged.json")
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    lanes = doc["otherData"]["lanes"]
    print(f"fftrace: merged {len(lanes)} lane(s), "
          f"{len(doc['traceEvents'])} event(s) -> {out}")
    for ln in lanes:
        tag = "" if ln["aligned"] else " (unaligned)"
        who = ("serving" if ln.get("role") == "serving"
               else f"rank {ln['rank']} epoch {ln['epoch']}")
        print(f"  {who}: "
              f"{ln['n_events']} events, {ln['dropped']} dropped{tag}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
