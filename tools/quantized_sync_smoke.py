"""CI quantized-collectives parity smoke (ci.sh fast tier, ISSUE 15).

Three gates on the 8-virtual-device mesh, on a BERT encoder (the
bert_base architecture at smoke scale — base head/FFN ratios, reduced
depth/width so the fast tier stays fast; dropout off so the only
difference between the legs is the sync precision):

  1. **bit-exact off** — with ``quantized_collectives=off`` (the
     default) the training path is byte-for-byte the legacy one: two
     runs produce IDENTICAL loss histories, and so does a run of this
     build vs the flag never having existed (the implicit GSPMD sync).
  2. **bit-comparable auto** — ``quantized_collectives=auto`` must
     adopt a plan that actually quantizes something, run the explicit
     int8 sync with error feedback, and converge with the baseline:
     per-step relative loss gap within tolerance and the SAME
     monotonic trend.
  3. **import honors the plan verbatim** — the exported strategy
     carries the qsync section; re-importing it re-adopts the exact
     per-tensor, per-phase wire choice.

    python tools/quantized_sync_smoke.py
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()

STEPS = 6
BATCH, SEQ = 16, 32
REL_TOL = 0.08      # per-step relative loss gap, quantized vs baseline


def bert_cfg():
    from flexflow_tpu.models import BertConfig
    # bert_base ratios (heads = hidden/64, ffn = 4x hidden) at smoke
    # scale; dropout off so precision is the only degree of freedom
    return BertConfig(vocab_size=2048, hidden_size=128, num_layers=2,
                      num_heads=2, intermediate_size=512,
                      max_position=SEQ, dropout=0.0, num_labels=4)


def build(mode: str, import_file=None, export_file=None):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.models import build_bert
    cfg = FFConfig()
    cfg.batch_size = BATCH
    # --import takes the search path (only_data_parallel would bypass
    # the file entirely); everything else trains the canonical DP plan
    cfg.only_data_parallel = not import_file
    cfg.quantized_collectives = mode
    cfg.seed = 7
    if import_file:
        cfg.import_strategy_file = import_file
    ff = FFModel(cfg)
    out = build_bert(ff, BATCH, SEQ, bert_cfg())
    ff.compile(AdamOptimizer(0.005), "sparse_categorical_crossentropy",
               [], output_tensor=out)
    if export_file:
        from flexflow_tpu.search.serialization import save_strategy
        save_strategy(export_file, ff.strategy)
    return ff


def batch():
    import numpy as np
    rng = np.random.default_rng(1)
    return {
        "input_ids": rng.integers(0, 2048, size=(BATCH, SEQ)
                                  ).astype(np.int32),
        "position_ids": np.tile(np.arange(SEQ, dtype=np.int32),
                                (BATCH, 1)),
        "label": rng.integers(0, 4, size=(BATCH, 1)).astype(np.int32),
    }


def run(ff, steps=STEPS):
    import numpy as np
    b = batch()
    step = ff.executor.make_train_step()
    return [float(np.asarray(ff._run_train_step(step, b)["loss"]))
            for _ in range(steps)]


def main():
    import jax
    n = len(jax.devices())
    if n != 8:
        raise SystemExit(f"expected the 8-virtual-device mesh, got {n}")

    # -- gate 1: flag off is bit-exact --------------------------------
    losses_off_a = run(build("off"))
    losses_off_b = run(build("off"))
    if losses_off_a != losses_off_b:
        raise SystemExit(f"off-mode runs diverge (nondeterminism):\n"
                         f"  {losses_off_a}\n  {losses_off_b}")

    # -- gate 2: auto adopts, runs the explicit sync, converges -------
    with tempfile.TemporaryDirectory() as d:
        export = os.path.join(d, "qsync_strategy.json")
        ff_q = build("auto", export_file=export)
        plan = ff_q.strategy.qsync
        if plan is None or not plan.quantized_params():
            raise SystemExit("auto mode adopted no quantized syncs — "
                             "the parity gate would be vacuous")
        if ff_q.executor._qsync is None:
            raise SystemExit("plan adopted but the runtime schedule "
                             "did not resolve (implicit-sync fallback)")
        losses_q = run(ff_q)
        for i, (lq, lb) in enumerate(zip(losses_q, losses_off_a)):
            gap = abs(lq - lb) / max(abs(lb), 1e-9)
            if gap > REL_TOL:
                raise SystemExit(
                    f"quantized-vs-baseline loss gap {gap:.4f} at step "
                    f"{i} exceeds {REL_TOL}:\n  quantized: {losses_q}\n"
                    f"  baseline:  {losses_off_a}")
        if not losses_q[-1] < losses_q[0]:
            raise SystemExit(f"quantized run is not converging: "
                             f"{losses_q}")

        # -- gate 3: --import honors the plan verbatim ----------------
        with open(export) as f:
            doc = json.load(f)
        if not doc.get("qsync"):
            raise SystemExit("exported strategy carries no qsync "
                             "section")
        ff_i = build("off", import_file=export)
        plan_i = ff_i.strategy.qsync
        if plan_i is None or plan_i.to_json() != plan.to_json():
            raise SystemExit("imported strategy does not carry the "
                             "exported qsync plan verbatim")
        if ff_i.executor._qsync is None:
            raise SystemExit("imported plan did not resolve a runtime "
                             "schedule")

    s = plan.summary()
    print(f"quantized sync smoke OK: {s['n_quantized']}/{s['n_params']}"
          f" grad syncs on wire {s['wire']}, {STEPS} steps within "
          f"{REL_TOL:.0%} of the full-precision baseline "
          f"(final {losses_q[-1]:.6f} vs {losses_off_a[-1]:.6f}), "
          f"off-mode bit-exact, import verbatim")


if __name__ == "__main__":
    main()
