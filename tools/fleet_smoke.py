"""Fleet chaos smoke: crash a replica mid-load, reroute, warm-replace.

The push-blocking drill for ``serving/fleet`` (docs/serving.md ·
Fleet), on the 8-device CPU sim:

1. Two gpt2-tiny replica processes come up behind the
   :class:`FleetRouter`, sharing one persistent compile-cache dir;
   the fleet ``/healthz`` must converge (every replica polled
   healthy).
2. One replica carries ``FF_FAULT_PLAN=infer_crash@K``: its (K+1)-th
   generate call hard-kills the process (``os._exit``, no drain, no
   socket close) while client load is in flight.
3. Every request the router admitted must still return 200 — the
   in-flight request on the dead replica fails over to the survivor;
   zero client-visible failures, failovers counter > 0.
4. The autoscaler (``min_replicas=2``) must notice the dead replica
   and bring a REPLACEMENT up through the shared compile cache:
   warm start asserted two ways — the cache directory gains no new
   program entries, and the replacement's ``ff_model_compiles_total``
   shows exactly the one per-process model build (flat counter +
   cache hits = warm; a cold replacement would mint new cache files).
5. Fleet ``/healthz`` converges again at 2 healthy replicas, and the
   merged ``ffstat --endpoint ... --endpoint ...`` fleet view renders
   against the live fleet (``--once``, CI-safe).
"""
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

MODEL = "gpt2-tiny"
CRASH_AT = 2          # victim dies on its 3rd generate call
N_REQUESTS = 16
CONVERGE_S = 150.0    # CPU-sim compile budget per replica


def _post_generate(base: str, timeout_s: float = 90.0):
    body = json.dumps({
        "inputs": [{"name": "input_ids", "shape": [1, 32],
                    "datatype": "int32",
                    "data": [5, 9, 11, 13] + [0] * 28}],
        "parameters": {"prompt_len": 4, "max_new_tokens": 6,
                       "eos_token_id": 7}}).encode()
    req = urllib.request.Request(
        base + f"/v2/models/{MODEL}/generate", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.status, json.loads(resp.read())


def _wait_converged(router, want_alive: int, deadline_s: float) -> dict:
    t_end = time.monotonic() + deadline_s
    doc = {}
    while time.monotonic() < t_end:
        doc = router.fleet_health()
        alive = sum(1 for r in doc["replicas"].values() if r["alive"])
        if doc["converged"] and alive >= want_alive:
            return doc
        time.sleep(0.5)
    raise AssertionError(
        f"fleet /healthz did not converge at {want_alive} replicas "
        f"within {deadline_s:.0f}s: {json.dumps(doc)[:500]}")


def main() -> int:
    from flexflow_tpu.serving.fleet import (Autoscaler,
                                            AutoscalerConfig,
                                            FleetRouter, serve_fleet)

    cache_dir = tempfile.mkdtemp(prefix="ff_fleet_cache_")
    spawn_argv = [
        sys.executable, "-m", "flexflow_tpu.serving.fleet.replica",
        "--port", "{port}", "--name", "{name}", "--model", MODEL,
        "--decode-segment", "4", "--compile-cache", cache_dir]
    spawn_env = {"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                 "PYTHONPATH": REPO,
                 # replicas must NOT inherit a fault plan from the CI
                 # environment; the victim gets its own below
                 "FF_FAULT_PLAN": ""}
    router = FleetRouter(spawn_argv=spawn_argv, spawn_env=spawn_env)
    handle = serve_fleet(router)
    scaler = None
    try:
        t0 = time.monotonic()
        survivor = router.spawn(name="replica-a")
        victim = router.spawn(
            name="replica-b",
            extra_env={"FF_FAULT_PLAN": f"infer_crash@{CRASH_AT}"})
        _wait_converged(router, want_alive=2, deadline_s=CONVERGE_S)
        cold_ttr = max(r.ready_at - r.spawned_at
                       for r in router.replicas())
        print(f"[fleet_smoke] 2 replicas converged in "
              f"{time.monotonic() - t0:.1f}s (slowest cold "
              f"time-to-ready {cold_ttr:.1f}s)")

        # warm-start baseline: program entries minted by the cold pair
        # (forward program; decode programs appear with first traffic)
        scaler = Autoscaler(router, AutoscalerConfig(
            min_replicas=2, max_replicas=3, poll_interval_s=0.25,
            deadline_ms=60000.0, idle_polls=10 ** 6))
        scaler.start()

        # -- 2+3: crash mid-load; every admitted request succeeds ----
        statuses = []
        errors = []
        lock = threading.Lock()

        def client(k):
            try:
                st, _ = _post_generate(handle.url)
                with lock:
                    statuses.append(st)
            except urllib.error.HTTPError as e:
                with lock:
                    errors.append(f"request {k}: HTTP {e.code} "
                                  f"{e.read().decode()[:200]}")
            except Exception as e:  # noqa: BLE001 — any client-visible
                # failure fails the smoke below
                with lock:
                    errors.append(f"request {k}: {e}")

        threads = []
        for k in range(N_REQUESTS):
            t = threading.Thread(target=client, args=(k,), daemon=True)
            t.start()
            threads.append(t)
            time.sleep(0.3)  # paced load so the crash lands mid-burst
        for t in threads:
            t.join(timeout=120.0)
        assert not errors, f"client-visible failures: {errors}"
        assert len(statuses) == N_REQUESTS and \
            all(s == 200 for s in statuses), statuses
        assert victim.proc.poll() is not None, \
            "victim replica did not crash — fault plan never fired"
        st = router.fleet_health()["fleet"]
        assert st["failovers"] >= 1, \
            f"router never failed over: {st}"
        print(f"[fleet_smoke] {N_REQUESTS}/{N_REQUESTS} requests OK "
              f"across the crash (failovers={st['failovers']})")

        # decode programs are all minted now (both cold replicas +
        # post-crash traffic) — the replacement must add NOTHING
        entries_before = len(glob.glob(
            os.path.join(cache_dir, "*-cache")))

        # -- 4+5: warm replacement, fleet converges at 2 again -------
        doc = _wait_converged(router, want_alive=2,
                              deadline_s=CONVERGE_S)
        names = set(doc["replicas"])
        assert "replica-b" not in names, \
            f"dead replica still in the routable view: {names}"
        repl = next(r for r in router.replicas()
                    if r.name not in ("replica-a", "replica-b"))
        warm_ttr = repl.ready_at - repl.spawned_at
        entries_after = len(glob.glob(
            os.path.join(cache_dir, "*-cache")))
        assert entries_after <= entries_before, (
            f"replacement minted {entries_after - entries_before} new "
            f"compile-cache entries — cold start, cache not hit")
        mtext = urllib.request.urlopen(
            repl.url + "/metrics", timeout=10).read().decode()
        m = re.search(r'ff_model_compiles_total\{[^}]*model="'
                      + re.escape(MODEL) + r'"[^}]*\}\s+([0-9.]+)',
                      mtext)
        assert m and float(m.group(1)) >= 1.0, (
            "replacement's ff_model_compiles_total must witness its "
            "per-process program builds (each a cache hit — the flat "
            f"cache dir above proves warm): {m and m.group(0)}")
        acts = [a["action"] for a in scaler.actions()]
        assert "repair" in acts or "scale_up" in acts, acts
        print(f"[fleet_smoke] warm replacement {repl.name} ready in "
              f"{warm_ttr:.1f}s (cold was {cold_ttr:.1f}s); compile "
              f"cache flat at {entries_after} entries, "
              f"ff_model_compiles_total={m.group(1)}")

        # -- merged ffstat fleet view against the live fleet ---------
        eps = []
        for r in router.replicas():
            eps += ["--endpoint", r.url]
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools/ffstat.py")]
            + eps + ["--once"],
            capture_output=True, text=True, timeout=120,
            cwd=REPO)
        assert out.returncode == 0, (out.returncode, out.stderr[-500:])
        assert "ffstat fleet" in out.stdout and MODEL in out.stdout, \
            out.stdout[-500:]
        print("[fleet_smoke] merged ffstat fleet view:")
        print("\n".join("    " + ln
                        for ln in out.stdout.splitlines()[:8]))
        print("[fleet_smoke] OK")
        return 0
    finally:
        if scaler is not None:
            scaler.stop()
        handle.stop()


if __name__ == "__main__":
    sys.exit(main())
