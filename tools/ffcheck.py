#!/usr/bin/env python
"""ffcheck: static plan verifier + invariant/concurrency/SPMD linters.

The command-line front end of ``flexflow_tpu.analysis`` (see
``docs/static_analysis.md``), run by ``ci.sh``'s fast tier as a hard
gate:

    python tools/ffcheck.py --lint --concurrency --spmd \\
        --verify-strategies --budget-s 15

  --lint [PATH ...]        run the invariant linter over files/trees
                           (no paths: the whole package)
  --concurrency [PATH ...] run the lock-discipline/thread-lifecycle
                           analyzer (analysis/concurrency.py; no
                           paths: the whole package)
  --spmd [PATH ...]        run the SPMD-divergence checker
                           (analysis/spmd.py; no paths: the package,
                           scope-filtered to the multi-rank modules)
  --rules r1,r2            restrict the rule set (applies per engine)
  --budget-s S             fail (exit 1) if the analyzers' combined
                           wall time exceeds S seconds — the CI gate
                           cannot silently bloat
  --verify-strategies [DIR]
                           statically verify every strategy JSON under
                           DIR (default: strategies/): structural
                           mesh/spec soundness always; full shape-level
                           verification (divisibility, seams, memory,
                           collective order) for strategies whose
                           workload builder is known (bert/dlrm)
  --json                   machine-readable report on stdout
                           (``schema: 2``: stable per-finding IDs —
                           rule + path + symbol hash — diffable across
                           runs)
  --verbose                print per-strategy pass lines

Exit status: 0 = clean, 1 = findings/budget exceeded, 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATHS = [os.path.join(REPO, "flexflow_tpu")]


# ---------------------------------------------------------------------------
# workload builders for the checked-in strategies: filename prefix →
# the graph the strategy was searched on (regeneration commands are in
# tests/test_strategies_repo.py)
# ---------------------------------------------------------------------------

def _build_dlrm():
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import DLRMConfig, build_dlrm
    ff = FFModel(FFConfig())
    out = build_dlrm(ff, 32, DLRMConfig())
    return ff, out


def _build_bert():
    # batch/seq must match the searched program (its reshapes bake the
    # batch in); the checked-in artifact was searched at (4, 128)
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import BertConfig, build_bert
    ff = FFModel(FFConfig())
    out = build_bert(ff, 4, 128, BertConfig.base())
    return ff, out


def _build_mlp():
    # the placement-annotated 2-slice artifact (strategies/
    # mlp_searched_2slice8.json) was searched at batch 32
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import build_mlp
    ff = FFModel(FFConfig())
    out = build_mlp(ff, 32, in_dim=64, hidden=(256, 256),
                    num_classes=10)
    return ff, out


def _build_gpt2():
    # the serving-plan artifact (strategies/gpt2_serving_8dev.json) was
    # searched at (8, 32) on the tiny config — the same graph
    # tools/serving_plan_smoke.py serves
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models.nlp import GPTConfig, build_gpt2
    ff = FFModel(FFConfig())
    out = build_gpt2(ff, 8, 32, GPTConfig.tiny())
    return ff, out


BUILDERS = {"dlrm": _build_dlrm, "bert": _build_bert,
            "mlp": _build_mlp, "gpt2": _build_gpt2}


def _full_verify(path: str, doc: dict, builder):
    """Shape-level verification: rebuild the workload graph, load the
    saved strategy (and its serialized rewritten program) against a
    structural mesh, and run the full plan verifier. No jax devices are
    required — nothing executes."""
    from flexflow_tpu.analysis.plan_verifier import (StructMesh,
                                                     verify_plan)
    from flexflow_tpu.search.serialization import (load_strategy,
                                                   program_from_json)
    ff, out = builder()
    consumed = {t.guid for l in ff.layers for t in l.inputs}
    graph_inputs = [t for t in ff.input_tensors
                    if t.guid in consumed and t.get_tensor() is None]
    const_inputs = [t for t in ff.input_tensors
                    if t.guid in consumed and t.get_tensor() is not None]
    dmesh = StructMesh(doc["mesh_axes"])
    strategy = load_strategy(path, ff.layers, dmesh)
    layers = ff.layers
    if doc.get("program"):
        layers, _ = program_from_json(doc["program"],
                                      graph_inputs + const_inputs)
    return verify_plan(strategy, layers, machine_spec=dmesh.spec,
                       graph_inputs=graph_inputs,
                       context=os.path.basename(path))


def verify_strategies(directory: str, verbose: bool = False,
                      stream=None):
    """Verify every ``*.json`` strategy under ``directory``. Returns
    (reports, failures) where reports is {path: PlanReport}. Progress/
    failure lines go to ``stream`` (default stdout; ``--json`` passes
    stderr so stdout stays one parseable document)."""
    stream = stream or sys.stdout
    from flexflow_tpu.analysis.plan_verifier import verify_strategy_file
    reports = {}
    failures = []
    names = sorted(fn for fn in os.listdir(directory)
                   if fn.endswith(".json"))
    for fn in names:
        path = os.path.join(directory, fn)
        with open(path) as f:
            doc = json.load(f)
        report = verify_strategy_file(path, doc=doc)
        builder = next((b for prefix, b in BUILDERS.items()
                        if fn.startswith(prefix)), None)
        if builder is not None and report.ok():
            try:
                full = _full_verify(path, doc, builder)
                report.findings.extend(full.findings)
                report.memory = full.memory
                report.collectives = full.collectives
                report.duration_s += full.duration_s
            except Exception as e:  # noqa: BLE001 — surface as finding
                report.add("seam", "error", path,
                           f"full verification crashed: "
                           f"{type(e).__name__}: {e}")
        reports[path] = report
        if report.errors:
            failures.append(path)
        if verbose or report.errors:
            status = "FAIL" if report.errors else "ok"
            print(f"ffcheck: verify {path}: {status} "
                  f"({len(report.findings)} finding(s), "
                  f"{report.duration_s * 1e3:.0f} ms)", file=stream)
            for f_ in report.findings:
                print(f"  {f_.format()}", file=stream)
    return reports, failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ffcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--lint", nargs="*", metavar="PATH",
                    help="lint these files/trees (default: the "
                         "package)")
    ap.add_argument("--concurrency", nargs="*", metavar="PATH",
                    help="lock-discipline/thread-lifecycle analysis "
                         "(default: the package)")
    ap.add_argument("--spmd", nargs="*", metavar="PATH",
                    help="SPMD-divergence analysis (default: the "
                         "package, scope-filtered)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (per engine)")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="fail if analyzer wall time exceeds this")
    ap.add_argument("--verify-strategies", nargs="?", metavar="DIR",
                    const=os.path.join(REPO, "strategies"), default=None,
                    help="verify strategy JSONs (default dir: "
                         "strategies/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="JSON report on stdout")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.lint is None and args.concurrency is None \
            and args.spmd is None and not args.verify_strategies:
        ap.error("nothing to do: pass --lint / --concurrency / --spmd "
                 "and/or --verify-strategies")

    from flexflow_tpu.analysis.lint import JSON_SCHEMA_VERSION
    rc = 0
    doc = {"schema": JSON_SCHEMA_VERSION}
    rules = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    analysis_s = 0.0
    engines = []
    if args.lint is not None:
        from flexflow_tpu.analysis.lint import lint_paths
        engines.append(("lint", lint_paths, args.lint or DEFAULT_PATHS))
    if args.concurrency is not None:
        from flexflow_tpu.analysis.concurrency import \
            analyze_paths as conc_paths
        engines.append(("concurrency", conc_paths,
                        args.concurrency or DEFAULT_PATHS))
    if args.spmd is not None:
        from flexflow_tpu.analysis.spmd import \
            analyze_paths as spmd_paths
        engines.append(("spmd", spmd_paths, args.spmd or DEFAULT_PATHS))
    if engines:
        from flexflow_tpu.analysis.lint import render_json, render_text
        for name, run, paths in engines:
            t0 = time.perf_counter()
            findings = run(paths, rules=rules)
            analysis_s += time.perf_counter() - t0
            if args.as_json:
                doc[name] = json.loads(render_json(findings))
            elif findings:
                print(render_text(findings))
            elif args.verbose:
                print(f"ffcheck: {name} clean")
            if findings:
                rc = 1
        if not args.as_json and rc == 0:
            print(f"ffcheck: clean "
                  f"({'/'.join(n for n, _, _ in engines)}, "
                  f"{analysis_s:.2f}s)")
        doc["analysis_s"] = round(analysis_s, 4)
        if args.budget_s is not None and analysis_s > args.budget_s:
            print(f"ffcheck: analyzers took {analysis_s:.2f}s — over "
                  f"the {args.budget_s:.0f}s budget (the CI gate must "
                  f"not silently bloat; profile or split the pass)",
                  file=sys.stderr)
            rc = 1
    if args.verify_strategies:
        if not os.path.isdir(args.verify_strategies):
            print(f"ffcheck: strategy directory "
                  f"{args.verify_strategies!r} does not exist",
                  file=sys.stderr)
            return 2
        reports, failures = verify_strategies(
            args.verify_strategies, verbose=args.verbose,
            stream=sys.stderr if args.as_json else sys.stdout)
        if args.as_json:
            doc["verify"] = {p: r.to_json() for p, r in reports.items()}
        elif not failures:
            print(f"ffcheck: {len(reports)} strategy file(s) verified")
        if failures:
            rc = 1
    if args.as_json:
        doc["ok"] = rc == 0
        print(json.dumps(doc, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
