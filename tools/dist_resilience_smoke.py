"""CI multi-host resilience smoke (ci.sh fast tier, ISSUE 7).

Launcher mode (default): a :class:`WorldSupervisor` drives a 2-process
CPU world training a tiny MLP under per-process Supervisors with
per-step multi-host checkpoints. ``FF_FAULT_PLAN_EPOCH0`` injects
``rank_crash@3:1`` — rank 1 hard-dies (``os._exit``, no cleanup)
before global step 3 in world epoch 0. The world must notice (bounded
heartbeat/barrier timeouts, never a hang), re-form at epoch 1
(relaunch under the restart budget — or shrink when exhausted), resume
from the last committed two-phase checkpoint, and finish with a finite
loss on every rank. Exit code 0 = the cross-process recovery path
works end-to-end.

Worker mode (``--worker``; world env injected by the WorldSupervisor):
one controller of the world. Env knobs: ``FF_SMOKE_CKPT_DIR`` (shared
checkpoint dir), ``FF_LOCAL_DEVICES`` (default 1), ``FF_SMOKE_POLICY``.

Bounded: tight heartbeat (0.1s) / failure (3s) / barrier (20s)
timeouts and a 240s world timeout keep the whole smoke well inside the
fast tier's budget (typically ~60s).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if "--worker" in sys.argv:
    # worker env setup must precede any jax import
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                               + os.environ.get("FF_LOCAL_DEVICES", "1"))


def worker() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu import ActiMode, FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.resilience import Supervisor, run_world_member

    def train():
        cfg = FFConfig()
        cfg.batch_size = 8
        cfg.only_data_parallel = True
        cfg.heartbeat_interval_s = 0.1
        ff = FFModel(cfg)
        x = ff.create_tensor((cfg.batch_size, 16), name="x")
        t = ff.dense(x, 32, activation=ActiMode.AC_MODE_RELU)
        ff.softmax(ff.dense(t, 4))
        ff.compile(SGDOptimizer(lr=0.1),
                   "sparse_categorical_crossentropy", [])
        rng = np.random.default_rng(0)  # same data on every rank
        xs = rng.normal(size=(48, 16)).astype(np.float32)
        ys = rng.integers(0, 4, size=48).astype(np.int32)
        sup = Supervisor(ff, os.environ["FF_SMOKE_CKPT_DIR"],
                         checkpoint_every=1)
        # the committed step this incarnation resumes from (-1 = fresh
        # world): lets the launcher/test prove the relaunched epoch
        # really resumed instead of silently retraining from scratch
        from flexflow_tpu.runtime.checkpoint import CheckpointManager
        start = CheckpointManager(
            os.environ["FF_SMOKE_CKPT_DIR"]).latest_step()
        hist = sup.run(x=xs, y=ys, epochs=2, shuffle=False)
        loss = hist[-1]["loss"]
        assert np.isfinite(loss), f"non-finite final loss {loss}"
        print(f"SMOKE_OK rank={jax.process_index()} "
              f"epoch={os.environ.get('FF_WORLD_EPOCH', '0')} "
              f"world={jax.process_count()} "
              f"start={-1 if start is None else start} "
              f"loss={loss:.6f}", flush=True)

    run_world_member(train)


def launch() -> None:
    import glob
    import tempfile

    from flexflow_tpu.resilience import WorldSupervisor

    ckpt = tempfile.mkdtemp(prefix="ff_dist_smoke_")
    policy = os.environ.get("FF_SMOKE_POLICY", "auto")
    env = {
        "FF_SMOKE_CKPT_DIR": ckpt,
        "FF_FAULT_PLAN_EPOCH0": os.environ.get(
            "FF_FAULT_PLAN_EPOCH0", "rank_crash@3:1"),
        "FF_HB_INTERVAL_S": "0.1",
        "FF_HB_TIMEOUT_S": "3",
        "FF_BARRIER_TIMEOUT_S": "20",
        "FF_LOCAL_DEVICES": "1",
        # span tracing ON in the workers: each surviving rank dumps its
        # ring at the end of training (trace_rank<r>_epoch<e>.json) so
        # the fftrace merge below has real multi-rank input, and the
        # crash drill's flight record carries spans
        "FF_TRACE": "1",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }
    env.pop("JAX_PLATFORMS", None)
    # stale dumps from an earlier run must not satisfy this run's
    # assertions
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".ffcache")
    for pat in ("flight_rank*_epoch*.json", "trace_rank*_epoch*.json"):
        for p in glob.glob(os.path.join(cache, pat)):
            os.remove(p)
    ws = WorldSupervisor(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        nprocs=2, max_world_restarts=1, policy=policy,
        batch_size=8, devices_per_rank=1, world_timeout_s=240.0,
        env=env)
    records = ws.run()
    assert ws.world_restarts + ws.shrinks >= 1, \
        "fault injected but the world never needed re-forming"
    # the crash drill must leave a flight record (the survivor dumps
    # its black box at the RankFailure detection site), and the
    # WorldSupervisor report must reference it
    import json
    flights = glob.glob(os.path.join(cache, "flight_rank*_epoch*.json"))
    assert flights, "rank-crash drill left no flight record"
    fdoc = json.load(open(flights[0]))
    assert fdoc["reason"] in ("rank_failure", "crash",
                              "world_restart"), fdoc["reason"]
    assert "world" in fdoc and "counters" in fdoc
    assert any(r.get("flight_records") for r in ws.report), \
        "WorldSupervisor report references no flight record"
    # the final (successful) epoch's per-rank trace dumps must merge
    # into one valid Chrome trace with one lane per rank
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fftrace
    dumps = sorted(glob.glob(os.path.join(
        cache, f"trace_rank*_epoch{ws.epoch}.json")))
    assert len(dumps) == ws.nprocs, \
        f"expected {ws.nprocs} rank dumps for epoch {ws.epoch}, " \
        f"got {dumps}"
    merged = fftrace.merge_rank_traces(dumps)
    evs = merged["traceEvents"]
    assert evs and all("ts" in e and "pid" in e for e in evs
                       if e["ph"] != "M")
    lanes = merged["otherData"]["lanes"]
    assert len(lanes) == ws.nprocs and all(ln["aligned"]
                                           for ln in lanes), lanes
    assert len({ln["pid"] for ln in lanes}) == ws.nprocs
    assert any(e["ph"] == "X" for e in evs), "merged trace has no spans"
    losses = []
    for rec in records:
        toks = [t for ln in rec["out"].splitlines()
                if ln.startswith("SMOKE_OK") for t in ln.split()
                if t.startswith("loss=")]
        assert toks, f"rank {rec['rank']} printed no SMOKE_OK:\n" \
            f"{rec['out'][-800:]}\n{rec['err'][-800:]}"
        losses.append(float(toks[-1].split("=")[1]))
    assert len(set(losses)) == 1, f"final losses disagree: {losses}"
    import shutil
    shutil.rmtree(ckpt, ignore_errors=True)
    print(f"dist resilience smoke OK: {len(ws.report)} world epoch(s) "
          f"{ws.report}, {ws.world_restarts} relaunch(es), "
          f"{ws.shrinks} shrink(s), final world {ws.nprocs} proc(s), "
          f"loss {losses[0]:.6f}; {len(flights)} flight record(s), "
          f"{len(dumps)} rank dump(s) merged into "
          f"{len(merged['traceEvents'])} trace event(s)")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        launch()
