#!/usr/bin/env python
"""Closed-loop replan smoke (ci.sh fast tier): on the virtual 2-slice
(DCN-joined) 8-device CPU config, run the whole adaptation loop of
``resilience/replan.py`` end to end and assert its contract:

  - a ``degrade_link`` fault drill fires mid-training (one-shot, step
    indexed) and drift-marked calibration rows become replan evidence;
  - the controller debounces, then heals the tables in place — exactly
    the stale-marked rows are re-measured and re-filed
    (``ff_calibration_rows_remeasured_total`` moves by that count), and
    because the drill is active while they re-measure, the refreshed
    rows price the fabric as it is NOW;
  - the re-search on the refreshed tables produces a candidate the
    predicted-win gate admits (``predicted_ratio >= win_ratio``, the
    measured A/B deferred — a virtual drill slows the cost model, not
    real CPU steps);
  - the hot-swap carries the live training state over bit-exactly
    (params identical, step counter preserved) and the adopted plan
    takes a real finite train step;
  - the decision is observable everywhere it should be: the strategy
    audit record's ``replan.events``, ``ff_replans_total``, and the
    resilience status mirrored into ``/healthz``;
  - flap control holds: evidence persists but the armed cooldown keeps
    adoptions at exactly one.

The incumbent is pinned to the plain data-parallel plan before the
drill (deterministic baseline — the smoke asserts the LOOP, not search
luck): its per-step grad-sync all-reduce is exactly the collective the
degraded tier slows, and the re-search finds a weight-sharded plan
that does not pay it.

See docs/resilience.md ("Closed-loop plan adaptation"). The behavioral
unit coverage lives in tests/test_replan.py; this smoke keeps the fast
tier honest about the pieces composing on a multi-tier mesh.
"""
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_mlp
    from flexflow_tpu.obs.audit import load_strategy_audit
    from flexflow_tpu.obs.metrics_registry import REGISTRY
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.resilience import (ReplanController, ReplanPolicy,
                                         faults)
    from flexflow_tpu.resilience import status as rstatus
    from flexflow_tpu.search import calibration

    n = len(jax.devices())
    if n < 8:
        print(f"replan smoke: need 8 virtual devices, have {n}",
              file=sys.stderr)
        return 1
    # isolate the calibration cache: the smoke marks rows stale and
    # re-files them, which must not touch the repo's shared .ffcache
    calibration._DEFAULT_DIR = tempfile.mkdtemp(prefix="ff_replan_smoke_")

    spec = MachineSpec.detect()
    spec.num_devices = 8
    spec.num_slices = 2
    spec.num_hosts = 2
    spec.dcn_bandwidth_gbps = 1.0
    spec.dcn_latency_us = 20.0
    assert spec.tier_graph.multi_tier, spec.tier_graph

    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 8
    cfg.search_floor_guard = "false"
    cfg.trace = "true"                 # the audit record must be written
    cfg.calibration_v2 = "true"        # measured tables: what drifts
    ff = FFModel(cfg)
    out = build_mlp(ff, 32, in_dim=64, hidden=(256, 256), num_classes=10)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               machine_spec=spec, output_tensor=out)

    # pin the incumbent to the plain data-parallel plan (through the
    # explicit-strategy compile path, the same install the swap uses):
    # a deterministic baseline whose grad-sync all-reduce is exactly
    # what the drill below degrades
    from flexflow_tpu.search.costmodel import OpCostModel
    from flexflow_tpu.search.mcmc import (StrategySimulator,
                                          assignment_to_strategy,
                                          data_parallel_assignment)
    sim = StrategySimulator(ff.layers, ff.dmesh, OpCostModel(ff.dmesh.spec))
    dp = assignment_to_strategy(
        ff.layers, ff.graph_inputs,
        data_parallel_assignment(ff.layers, ff.dmesh, sim.options),
        ff.dmesh, sim)
    ReplanController._install(ff, dp)

    # --- the degradation drill fires mid-training, not at setup time --
    faults.install("degrade_link@3:ici:6.0")
    rng = np.random.default_rng(0)
    batch = {"input": rng.normal(size=(32, 64)).astype(np.float32),
             "label": rng.integers(0, 10, size=(32, 1)).astype(np.int32)}
    step_fn = ff.executor.make_train_step()
    for _ in range(4):                 # drill due before step 3 executes
        bm = ff._run_train_step(step_fn, batch)
    assert faults.degraded_links() == {"ici": 6.0}, faults.degraded_links()

    # the drift detector's mark (obs/drift.py files these from
    # predicted-vs-measured mismatch; the smoke plants them directly so
    # the assertion is on the LOOP, not on timing noise): every
    # collective row of this backend — the drill slowed the fabric, so
    # every collective measurement is now mispriced
    table = calibration.CalibrationTable()
    backend = jax.default_backend()
    stale_marked = sorted(k for k in table._load()
                          if k.startswith(backend + "|coll_"))
    assert stale_marked, "compile-time calibration filed no rows"
    assert table.mark_stale(stale_marked) == len(stale_marked)

    params_before = jax.tree.map(np.asarray, ff.params)
    step_before = ff._step
    remeasured_before = REGISTRY.counter(
        "ff_calibration_rows_remeasured_total").value()

    ctl = ReplanController(ff, ReplanPolicy(
        debounce_polls=2, cooldown_s=300.0, search_budget=1500,
        measured_guard=False))        # virtual drill: the degradation
    # exists in the cost model, not in real CPU step time, so adoption
    # rides the predicted gate and is recorded as gate="deferred"
    assert ctl.step_once() == "debounce"
    outcome = ctl.step_once()
    rec = ctl.history[-1]
    assert outcome == "adopted", (outcome, rec)
    assert rec["trigger"] == "drift", rec
    assert rec["predicted_ratio"] >= 1.1, rec
    assert rec["gate"] == "deferred", rec

    # targeted re-calibration: the stale rows were re-measured in place
    # (re-filed via put, which clears the mark) and the meter moved by
    # exactly that count
    assert rec["remeasured"], rec
    assert set(rec["remeasured"]) <= set(stale_marked), rec
    assert not set(rec["remeasured"]) & set(table._load_stale()), \
        "re-filed rows still marked stale"
    moved = REGISTRY.counter(
        "ff_calibration_rows_remeasured_total").value() - remeasured_before
    assert moved == len(rec["remeasured"]), (moved, rec["remeasured"])

    # bit-exact carryover: values identical, only placement changed
    for a, b in zip(jax.tree.leaves(params_before),
                    jax.tree.leaves(ff.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert ff._step == step_before, (ff._step, step_before)
    bm = ff._run_train_step(ff.executor.make_train_step(), batch)
    loss = float(np.asarray(bm["loss"]))
    assert np.isfinite(loss), loss

    # the decision is visible in every observability surface
    assert REGISTRY.counter("ff_replans_total").value(
        trigger="drift", outcome="adopted") == 1
    st = rstatus.snapshot()
    assert st["replans"] == 1 and st["replan_last_outcome"] == "adopted", st
    events = load_strategy_audit(ff._strategy_audit_path)["replan"]["events"]
    assert events[-1]["outcome"] == "adopted", events
    assert events[-1]["predicted_ratio"] >= 1.1, events

    # flap control: the link is still degraded (evidence persists) but
    # the adoption reset the debounce streak and armed the cooldown, so
    # the loop is bounded to one adoption per window
    assert [ctl.step_once() for _ in range(3)] == \
        ["debounce", "cooldown", "cooldown"]
    assert ctl.replans == 1 and ctl.rollbacks == 0

    faults.clear()
    print(f"replan smoke OK: drift-triggered swap adopted "
          f"(predicted {rec['predicted_ratio']:.2f}x on "
          f"{rec['incumbent_basis']}-priced incumbent, "
          f"{len(rec['remeasured'])} rows re-measured, gate deferred), "
          f"bit-exact carryover, post-swap loss={loss:.4f}, "
          f"cooldown held at 1 adoption")
    return 0


if __name__ == "__main__":
    sys.exit(main())
