"""CI async-dispatch parity smoke (ci.sh fast tier).

Runs the same tiny fit twice — once with the sync-every-step fallback
(``FF_SYNC_EVERY_STEP=1``) and once with the default deferred
async-dispatch loop — and asserts the final losses are IDENTICAL
(bit-exact, not approximately equal): the deferred path batches the
host fetches, it must never change the numbers. Exit code 0 = the
async path has not silently diverged.

    python tools/async_parity_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_fit():
    import numpy as np
    from flexflow_tpu import ActiMode, FFConfig, FFModel, SGDOptimizer

    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.only_data_parallel = True
    cfg.seed = 11
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 16), name="x")
    t = ff.dense(x, 32, activation=ActiMode.AC_MODE_RELU)
    ff.softmax(ff.dense(t, 4))
    ff.compile(SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
               ["accuracy"])
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(192, 16)).astype(np.float32)
    ys = rng.integers(0, 4, size=192).astype(np.int32)
    return ff.fit(x=xs, y=ys, epochs=2, verbose=False)


def main():
    import numpy as np

    os.environ["FF_SYNC_EVERY_STEP"] = "1"
    h_sync = run_fit()
    os.environ.pop("FF_SYNC_EVERY_STEP", None)
    h_async = run_fit()

    assert len(h_sync) == len(h_async), (len(h_sync), len(h_async))
    for e, (a, b) in enumerate(zip(h_sync, h_async)):
        for k in ("loss", "accuracy"):
            assert a[k] == b[k], \
                f"epoch {e} {k}: sync {a[k]!r} != async {b[k]!r}"
    assert np.isfinite(h_async[-1]["loss"])
    print(f"async parity smoke OK: {len(h_async)} epochs, final loss "
          f"{h_async[-1]['loss']:.6f} identical sync vs deferred")


if __name__ == "__main__":
    main()
