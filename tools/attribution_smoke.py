"""CI attribution smoke (ci.sh fast tier, ISSUE 12).

Search → a few train steps with ``FF_ATTRIB=1`` → the strategy audit
record must carry a ``measured`` side keyed 1:1 to the predicted
entries, and a drift report must exist for the same workload key —
the prediction-vs-reality loop exercised end-to-end on every push.

Runs on the 8-virtual-device CPU mesh like the rest of the fast tier.
Exit 0 = the attribution pipeline works.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()
os.environ["FF_ATTRIB"] = "1"


def main() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_mlp

    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.search_budget = 4          # searched plan -> audit record
    ff = FFModel(cfg)
    out = build_mlp(ff, 16, in_dim=32, hidden=(64,), num_classes=8)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy",
               [], output_tensor=out)
    audit_path = getattr(ff, "_strategy_audit_path", None)
    if not audit_path or not os.path.exists(audit_path):
        raise SystemExit("FF_ATTRIB=1 must imply tracing, and a "
                         "searched compile must write an audit record")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(48, 32)).astype(np.float32)   # 3 steps @ 16
    y = rng.integers(0, 8, size=(48, 1)).astype(np.int32)
    ff.fit(x=x, y=y, epochs=1, verbose=False)

    with open(audit_path) as f:
        doc = json.load(f)
    measured = doc.get("measured")
    if not measured:
        raise SystemExit("fit under FF_ATTRIB=1 left no measured side "
                         "in the audit record")
    pred = [e["name"] for e in doc["adopted"]["per_op"]]
    meas = [e["name"] for e in measured["per_op"]]
    if pred != meas:
        raise SystemExit(f"measured side not keyed 1:1 to predicted: "
                         f"{pred} vs {meas}")
    n_measured = sum(1 for e in measured["per_op"] if e["measured"])
    if measured["mode"] == "spans" and n_measured == 0:
        raise SystemExit("spans mode measured nothing")
    drift_path = doc.get("drift_report")
    if not drift_path or not os.path.exists(drift_path):
        raise SystemExit("attribution must leave a drift report")
    with open(drift_path) as f:
        drift = json.load(f)
    if drift.get("workload_key") != doc.get("workload_key"):
        raise SystemExit("drift report keyed to the wrong workload")
    print(f"attribution smoke OK: mode={measured['mode']} "
          f"{n_measured}/{len(meas)} entries measured, "
          f"step_wall={measured['step_wall_s'] * 1e3:.2f} ms, "
          f"jit_wall={(measured.get('jit_step_wall_s') or 0) * 1e3:.2f}"
          f" ms, drift compared={drift['n_compared']} "
          f"out_of_band={drift['n_out_of_band']} "
          f"stale_marked={drift['stale_marked']}")


if __name__ == "__main__":
    main()
