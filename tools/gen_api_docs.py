"""Static API-docs generator (stdlib-only — the image has no
sphinx/mkdocs; reference analog: ``/root/reference/docs/`` +
``doxygen``).

Walks ``flexflow_tpu``, introspects public modules/classes/functions
(signatures + docstrings), and writes one markdown file per subpackage
under ``docs/api/`` plus an index. Deterministic output so the docs diff
cleanly in git.

  python tools/gen_api_docs.py          # writes docs/api/*.md
"""
from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "api")

# subpackages documented, with a one-line blurb for the index
SECTIONS = [
    ("flexflow_tpu", "top-level API (FFModel, FFConfig, optimizers)"),
    ("flexflow_tpu.core", "lazy Layer/Tensor graph"),
    ("flexflow_tpu.ops", "operator definitions (infer/weights/emit/flops)"),
    ("flexflow_tpu.models", "model zoo (BERT/GPT-2/LLaMA/Mixtral/DLRM/...)"),
    ("flexflow_tpu.parallel", "meshes, strategies, pipeline, banks, topology"),
    ("flexflow_tpu.pcg", "parallel computation graph"),
    ("flexflow_tpu.search", "auto-parallelization search + simulators"),
    ("flexflow_tpu.runtime", "optimizers/losses/metrics/dataloader/checkpoint"),
    ("flexflow_tpu.kernels", "Pallas TPU kernels (flash/ring attention)"),
    ("flexflow_tpu.frontends", "Keras / torch.fx / ONNX importers"),
    ("flexflow_tpu.serving", "inference serving (sessions/batcher/HTTP)"),
    ("flexflow_tpu.serving.fleet",
     "serving fleet (continuous batching/router/autoscaler)"),
    ("flexflow_tpu.obs",
     "telemetry (spans, Prometheus metrics, strategy audit records)"),
    ("flexflow_tpu.resilience",
     "fault injection, supervisor auto-resume, elastic re-plan"),
    ("flexflow_tpu.analysis",
     "static analysis (plan verifier, framework-invariant linter)"),
    ("flexflow_tpu.utils", "profiling, logging, compilation cache"),
]


# stdlib-default docstrings (EnumMeta injects one per Python version):
# their wording changes across interpreters and churned every docs
# regeneration, so they document as empty, deterministically
_STDLIB_DEFAULT_DOCS = {
    "An enumeration.",
    "Enum where members are also (and must be) ints",
    "Enum where members are also (and must be) strings",
}


def _clean_doc(obj) -> str:
    if inspect.isclass(obj) and "__doc__" not in vars(obj):
        return ""       # inherited docstring — not this class's own
    doc = (inspect.getdoc(obj) or "").strip()
    return "" if doc in _STDLIB_DEFAULT_DOCS else doc


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _public_members(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod) if not n.startswith("_")]
    out = []
    for n in sorted(names):
        obj = getattr(mod, n, None)
        if obj is None:
            continue
        if inspect.ismodule(obj):
            continue
        # only document things DEFINED here (skip re-exports of jax etc.)
        owner = getattr(obj, "__module__", "") or ""
        if not owner.startswith("flexflow_tpu"):
            continue
        out.append((n, obj))
    return out


def _doc_class(name, cls, lines):
    lines.append(f"### class `{name}{_sig(cls.__init__) if cls.__init__ is not object.__init__ else '()'}`\n")
    doc = _clean_doc(cls)
    if doc:
        lines.append(doc + "\n")
    for mname, m in sorted(vars(cls).items()):
        if mname.startswith("_"):
            continue
        if isinstance(m, (staticmethod, classmethod)):
            m = m.__func__
        if inspect.isfunction(m):
            mdoc = _clean_doc(m)
            lines.append(f"- **`.{mname}{_sig(m)}`** — "
                         f"{mdoc.splitlines()[0] if mdoc else ''}")
        elif isinstance(m, property):
            mdoc = _clean_doc(m.fget) if m.fget else ""
            lines.append(f"- **`.{mname}`** *(property)* — "
                         f"{mdoc.splitlines()[0] if mdoc else ''}")
    lines.append("")


def doc_module(qualname: str) -> str:
    mod = importlib.import_module(qualname)
    lines = [f"# `{qualname}`\n"]
    mdoc = _clean_doc(mod)
    if mdoc:
        lines.append(mdoc + "\n")
    # submodules (for package pages): one-line summaries
    if hasattr(mod, "__path__"):
        subs = []
        for info in sorted(pkgutil.iter_modules(mod.__path__),
                           key=lambda i: i.name):
            if info.name.startswith("_"):
                continue
            try:
                sub = importlib.import_module(f"{qualname}.{info.name}")
            except Exception:  # noqa: BLE001 — optional deps may be absent
                continue
            sdoc = _clean_doc(sub)
            first = sdoc.splitlines()[0] if sdoc else ""
            subs.append(f"- `{qualname}.{info.name}` — {first}")
        if subs:
            lines.append("## Modules\n")
            lines.extend(subs)
            lines.append("")
    members = _public_members(mod)
    classes = [(n, o) for n, o in members if inspect.isclass(o)]
    funcs = [(n, o) for n, o in members if inspect.isfunction(o)]
    if classes:
        lines.append("## Classes\n")
        for n, c in classes:
            _doc_class(n, c, lines)
    if funcs:
        lines.append("## Functions\n")
        for n, f in funcs:
            fdoc = _clean_doc(f)
            lines.append(f"### `{n}{_sig(f)}`\n")
            if fdoc:
                lines.append(fdoc + "\n")
    return "\n".join(lines) + "\n"


def main():
    os.makedirs(OUT, exist_ok=True)
    index = ["# flexflow_tpu API reference\n",
             "Generated by `tools/gen_api_docs.py` (stdlib "
             "introspection — regenerate after API changes).\n"]
    for qualname, blurb in SECTIONS:
        fname = qualname.replace(".", "_") + ".md"
        text = doc_module(qualname)
        with open(os.path.join(OUT, fname), "w") as f:
            f.write(text)
        index.append(f"- [`{qualname}`]({fname}) — {blurb}")
        print(f"wrote docs/api/{fname} ({len(text.splitlines())} lines)")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    print("wrote docs/api/index.md")


if __name__ == "__main__":
    main()
