"""CI fault-injection smoke (ci.sh fast tier).

Runs a tiny MLP under the resilience supervisor with the fault plan
taken from ``FF_FAULT_PLAN`` (the fast tier injects ``crash@2``) and
asserts the run auto-resumes and completes with a finite, decreasing
loss. Exit code 0 = the recovery path works end-to-end.

    FF_FAULT_PLAN="crash@2" python tools/resilience_smoke.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import numpy as np
    from flexflow_tpu import ActiMode, FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.resilience import Supervisor, faults

    plan = faults.get_plan()
    n_clauses = len(plan.faults)
    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 16), name="x")
    t = ff.dense(x, 32, activation=ActiMode.AC_MODE_RELU)
    ff.softmax(ff.dense(t, 4))
    ff.compile(SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", [])
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(192, 16)).astype(np.float32)
    ys = rng.integers(0, 4, size=192).astype(np.int32)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        sup = Supervisor(ff, ckpt_dir, checkpoint_every=1)
        hist = sup.run(x=xs, y=ys, epochs=2)

    loss = hist[-1]["loss"]
    assert np.isfinite(loss), f"non-finite final loss {loss}"
    assert loss < hist[0]["loss"], (hist[0]["loss"], loss)
    if n_clauses:
        assert sup.restarts >= 1, \
            "fault plan installed but the supervisor never restarted"
        assert plan.unfired() == 0, \
            f"{plan.unfired()} fault clause(s) never fired"
    print(f"resilience smoke OK: {len(hist)} epochs, "
          f"{sup.restarts} restart(s), final loss {loss:.4f}")


if __name__ == "__main__":
    main()
