"""ffstat: one-screen live view of a serving fleet.

Polls a running FlexFlow-TPU serving front-end (either HTTP front —
they share the routes) and renders one line per model:

    MODEL     CIRC    Q  INST    REQ/S   P50MS   P99MS  P99.9  SLO  EXP

  - ``CIRC`` — circuit-breaker state (closed / half-open / open);
  - ``Q`` / ``INST`` — bounded-queue depth and instances draining it;
  - ``REQ/S`` — admission rate, differenced between frames (the first
    frame shows ``-``: one sample has no rate);
  - ``P50MS/P99MS/P99.9`` — streaming-sketch latency quantiles
    (``obs/sketch.py`` — the same numbers ``/healthz`` and the
    ``ff_request_latency_quantile`` gauges report);
  - ``SLO`` / ``EXP`` — SLO-violation and expired-request totals.

A second block lists per-bucket p99s for any model whose sketch has
per-bucket traffic, so a single hot bucket is visible without Grafana.
Once the closed-loop replan controller (``resilience/replan.py``) has
decided anything, a ``replan:`` status line shows its candidate state,
adoption/rollback counts, newest outcome and remaining cooldown; the
fleet view adds a per-replica ``REPLAN`` column (``adoptions/last``).

Everything comes from two GETs per frame (``/healthz`` +
``/v2/metrics``), both cheap by contract — safe to leave running
against a production port.

Fleet mode: pass ``--endpoint`` more than once to scrape several
replicas and render ONE merged view — per-model counters summed and
latency quantiles recomputed from the union of the replicas' serialized
sketches (``QuantileSketch.merge``: exact, never an average of
per-replica percentiles), plus a per-replica block with each replica's
circuit state, queue depth, and estimated wait. A replica that stops
answering shows as ``DOWN`` in the per-replica block; the merged view
keeps rendering from the rest.

Usage:
    python tools/ffstat.py --port 8000             # live, 2 s frames
    python tools/ffstat.py --port 8000 --once      # one frame (CI)
    python tools/ffstat.py --url http://host:8000 --interval 5
    python tools/ffstat.py --endpoint http://h:8101 \
        --endpoint http://h:8102 --once            # merged fleet view

Exit status: 0 on a clean run, 2 when the server was unreachable
(fleet mode: when EVERY endpoint was unreachable).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

_TIMEOUT_S = 5.0     # per-request bound: a stat tool must never hang


def _get_json(base: str, path: str) -> Dict[str, Any]:
    with urllib.request.urlopen(base + path, timeout=_TIMEOUT_S) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch(base: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """One frame's raw facts: (/healthz doc, /v2/metrics models map)."""
    health = _get_json(base, "/healthz")
    metrics = _get_json(base, "/v2/metrics").get("models", {})
    return health, metrics


def _fmt_rate(cur: Dict, prev: Optional[Dict], dt: float) -> str:
    if prev is None or dt <= 0:
        return "-"
    d = cur.get("requests", 0) - prev.get("requests", 0)
    return f"{d / dt:.1f}"


def render_frame(health: Dict[str, Any], metrics: Dict[str, Any],
                 prev: Optional[Dict[str, Any]] = None,
                 dt: float = 0.0) -> str:
    """Render one frame as text. Pure — the smoke test calls this with
    canned docs; ``main`` adds the polling/diffing around it."""
    lines = []
    draining = bool(health.get("draining"))
    trace = health.get("trace") or {}
    head = (f"ffstat · {len(metrics)} model(s)"
            f"{' · DRAINING' if draining else ''}"
            f" · trace={'on' if trace.get('enabled') else 'off'}")
    lines.append(head)
    # closed-loop plan adaptation (resilience/replan.py): shown once the
    # controller has ever decided anything, so a healing — or flapping —
    # fleet is visible in the same screen as the symptom it heals
    res = health.get("resilience") or {}
    if res.get("replans") or res.get("replan_rollbacks") \
            or res.get("replan_last_outcome"):
        cool = res.get("replan_cooldown_remaining_s") or 0.0
        lines.append(
            f"replan: {res.get('replan_candidate') or 'idle'}"
            f" · adoptions={res.get('replans', 0)}"
            f" rollbacks={res.get('replan_rollbacks', 0)}"
            f" last={res.get('replan_last_outcome')}"
            f"({res.get('replan_last_trigger')})"
            f" cooldown={cool:.0f}s")
    lines.append(f"{'MODEL':<14}{'CIRC':<10}{'Q':>4}{'INST':>5}"
                 f"{'REQ/S':>8}{'P50MS':>8}{'P99MS':>8}{'P99.9':>8}"
                 f"{'SLO':>6}{'EXP':>6}")
    for name in sorted(metrics):
        m = metrics[name]
        lines.append(
            f"{name[:13]:<14}"
            f"{str(m.get('circuit', '?'))[:9]:<10}"
            f"{m.get('queue_depth', 0):>4}"
            f"{m.get('instances', 0):>5}"
            f"{_fmt_rate(m, (prev or {}).get(name), dt):>8}"
            f"{m.get('latency_p50_ms', 0.0):>8.2f}"
            f"{m.get('latency_p99_ms', 0.0):>8.2f}"
            f"{m.get('latency_p999_ms', 0.0):>8.2f}"
            f"{m.get('slo_violations', 0):>6}"
            f"{m.get('expired', 0):>6}")
    bucket_rows = []
    for name in sorted(metrics):
        for b, q in sorted((metrics[name].get("latency_by_bucket_ms")
                            or {}).items(), key=lambda kv: kv[0]):
            if q.get("count"):
                bucket_rows.append(
                    f"  {name[:13]:<14}bucket {b:>6}  "
                    f"n={q['count']:<8}p99={q.get('p99', 0.0):.2f}ms")
    if bucket_rows:
        lines.append("per-bucket p99:")
        lines.extend(bucket_rows)
    return "\n".join(lines)


def _sketch_cls():
    """The serving sketch class, imported lazily: only the fleet-merge
    path needs it (single-endpoint ffstat stays stdlib-only)."""
    try:
        from flexflow_tpu.obs.sketch import QuantileSketch
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from flexflow_tpu.obs.sketch import QuantileSketch
    return QuantileSketch


#: counters that sum across replicas in the merged fleet view
_FLEET_SUM = ("requests", "completed", "failed", "rejected",
              "expired", "deadline_rejected", "slo_violations",
              "queue_depth", "instances")


def merge_fleet_metrics(per_endpoint: Dict[str, Dict[str, Any]]
                        ) -> Dict[str, Dict[str, Any]]:
    """Merge per-endpoint ``/v2/metrics`` model maps into one fleet
    view: counters sum; ``latency_p*_ms`` are recomputed from the
    merged ``sketches.all`` docs. Pure — the fleet tests feed canned
    scrapes and compare against single-stream ingestion."""
    QuantileSketch = _sketch_cls()
    merged: Dict[str, Dict[str, Any]] = {}
    sketches: Dict[str, Any] = {}
    for metrics in per_endpoint.values():
        for model, m in metrics.items():
            agg = merged.setdefault(
                model, {f: 0 for f in _FLEET_SUM})
            agg["replicas"] = agg.get("replicas", 0) + 1
            for f in _FLEET_SUM:
                agg[f] += int(m.get(f, 0))
            doc = (m.get("sketches") or {}).get("all")
            if doc:
                sk = QuantileSketch.from_dict(doc)
                if model in sketches:
                    sketches[model].merge(sk)
                else:
                    sketches[model] = sk
    for model, agg in merged.items():
        sk = sketches.get(model)
        n = getattr(sk, "count", 0)
        for q, field in ((0.5, "latency_p50_ms"),
                         (0.99, "latency_p99_ms"),
                         (0.999, "latency_p999_ms")):
            agg[field] = round(sk.quantile(q) * 1e3, 3) if n else 0.0
        agg["sketch_count"] = n
    return merged


def render_fleet_frame(per_endpoint: Dict[str, Optional[Tuple]],
                       prev: Optional[Dict[str, Any]] = None,
                       dt: float = 0.0) -> str:
    """Render one merged fleet frame. ``per_endpoint`` maps endpoint
    -> (health, metrics) or None for an unreachable replica. Pure,
    like :func:`render_frame`."""
    up = {ep: hm for ep, hm in per_endpoint.items() if hm is not None}
    merged = merge_fleet_metrics(
        {ep: hm[1] for ep, hm in up.items()})
    lines = [f"ffstat fleet · {len(up)}/{len(per_endpoint)} "
             f"endpoint(s) up · {len(merged)} model(s)"]
    lines.append(f"{'MODEL':<14}{'REPL':>5}{'REQ/S':>8}{'P50MS':>8}"
                 f"{'P99MS':>8}{'P99.9':>8}{'SLO':>6}{'EXP':>6}")
    for name in sorted(merged):
        m = merged[name]
        lines.append(
            f"{name[:13]:<14}"
            f"{m.get('replicas', 0):>5}"
            f"{_fmt_rate(m, (prev or {}).get(name), dt):>8}"
            f"{m.get('latency_p50_ms', 0.0):>8.2f}"
            f"{m.get('latency_p99_ms', 0.0):>8.2f}"
            f"{m.get('latency_p999_ms', 0.0):>8.2f}"
            f"{m.get('slo_violations', 0):>6}"
            f"{m.get('expired', 0):>6}")
    lines.append("per-replica:")
    lines.append(f"  {'ENDPOINT':<26}{'MODEL':<14}{'CIRC':<10}"
                 f"{'Q':>4}{'INST':>5}{'WAIT_S':>8}{'REPLAN':>12}")
    for ep in sorted(per_endpoint):
        hm = per_endpoint[ep]
        short = ep.replace("http://", "")[:25]
        if hm is None:
            lines.append(f"  {short:<26}{'-':<14}{'DOWN':<10}"
                         f"{'-':>4}{'-':>5}{'-':>8}{'-':>12}")
            continue
        health, metrics = hm
        serving = (health.get("serving")
                   or {}) if isinstance(health, dict) else {}
        res = (health.get("resilience")
               or {}) if isinstance(health, dict) else {}
        # per-process adaptation state (resilience/replan.py): adopted
        # swap count plus the newest outcome, so a replica that healed
        # itself — or keeps rolling back — stands out in the fleet view
        replan = "-"
        if res.get("replans") or res.get("replan_rollbacks") \
                or res.get("replan_last_outcome"):
            replan = (f"{res.get('replans', 0)}/"
                      f"{res.get('replan_last_outcome') or '-'}")
        for name in sorted(metrics):
            m = metrics[name]
            wait = (serving.get(name) or {}).get(
                "estimated_wait_s", 0.0)
            lines.append(
                f"  {short:<26}{name[:13]:<14}"
                f"{str(m.get('circuit', '?'))[:9]:<10}"
                f"{m.get('queue_depth', 0):>4}"
                f"{m.get('instances', 0):>5}"
                f"{wait:>8.3f}"
                f"{replan[:11]:>12}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ffstat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", default=None,
                    help="server base url (default http://127.0.0.1:<port>)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between frames (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (CI / scripting)")
    ap.add_argument("--endpoint", action="append", default=None,
                    help="replica base url; repeat for a merged fleet "
                         "view (sketch-merged quantiles + per-replica "
                         "circuit/queue columns)")
    a = ap.parse_args(argv)
    if a.endpoint and len(a.endpoint) > 1:
        return _main_fleet([e.rstrip("/") for e in a.endpoint], a)
    base = (a.endpoint[0] if a.endpoint else None) \
        or a.url or f"http://{a.host}:{a.port}"
    base = base.rstrip("/")
    prev: Optional[Dict[str, Any]] = None
    t_prev = 0.0
    while True:
        try:
            health, metrics = fetch(base)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"ffstat: {base} unreachable: {e}", file=sys.stderr)
            return 2
        now = time.perf_counter()
        print(render_frame(health, metrics, prev, now - t_prev))
        if a.once:
            return 0
        prev, t_prev = metrics, now
        sys.stdout.flush()
        time.sleep(max(0.2, a.interval))
        # frame separator, not a screen clear: scrollback keeps history
        print()


def _main_fleet(endpoints: List[str], a) -> int:
    prev: Optional[Dict[str, Any]] = None
    t_prev = 0.0
    while True:
        frame: Dict[str, Optional[Tuple]] = {}
        for ep in endpoints:
            try:
                frame[ep] = fetch(ep)
            except (urllib.error.URLError, OSError, ValueError):
                frame[ep] = None  # rendered as DOWN, not fatal
        if all(v is None for v in frame.values()):
            print(f"ffstat: all {len(endpoints)} endpoints "
                  f"unreachable", file=sys.stderr)
            return 2
        now = time.perf_counter()
        print(render_fleet_frame(frame, prev, now - t_prev))
        if a.once:
            return 0
        prev = merge_fleet_metrics(
            {ep: hm[1] for ep, hm in frame.items()
             if hm is not None})
        t_prev = now
        sys.stdout.flush()
        time.sleep(max(0.2, a.interval))
        print()


if __name__ == "__main__":
    sys.exit(main())
