#!/usr/bin/env python
"""Serving-plan smoke (ci.sh fast tier): the inference-native search
end to end on the 8-device CPU mesh —

  - search a per-batch-class serving plan for the small causal LM
    (``build_gpt2`` at (8, 32), ``GPTConfig.tiny``), one sub-strategy
    per batch bucket, ranked by prefill + per-token decode-step
    latency with the KV cache inside the memory envelope;
  - the searched plan must pass ``verify_serving_plan`` and the
    checked-in artifact (``strategies/gpt2_serving_8dev.json``) must
    pass the static verifier (``ffcheck --verify-strategies`` path);
  - the KV envelope gate must BIND: at an artificially small HBM
    budget, a plan whose largest bucket only fits with the KV cache
    sharded verifies, and the replicated-KV analog fails with a typed
    ``PlanVerificationError`` — at compile/verify time, not OOM at
    request time;
  - the checked-in plan's per-bucket instances must serve decode
    requests BIT-IDENTICALLY to the training-plan (pure-DP) baseline
    session at every bucket, segmented lock holds included.

Regenerate the artifact with ``--regen`` (same seed/budget — commit the
diff). The perf gate (paired decode-step latency >= 1.0x vs the
reused-training-plan baseline on the 2-slice virtual mesh) lives in
``bench.py``'s ``serving_plan`` stage; this smoke keeps the fast tier
honest in ~60 s.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

ARTIFACT = os.path.join(REPO, "strategies", "gpt2_serving_8dev.json")
BUCKETS = (1, 4, 8)


def _compile_gpt2(mutate=None):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models.nlp import GPTConfig, build_gpt2
    cfg = FFConfig()
    cfg.only_data_parallel = True
    if mutate is not None:
        mutate(cfg)
    ff = FFModel(cfg)
    out = build_gpt2(ff, 8, 32, GPTConfig.tiny())
    ff.compile(SGDOptimizer(0.0), "identity", [], output_tensor=out)
    return ff


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    n = len(jax.devices())
    if n < 8:
        print(f"serving-plan smoke: need 8 virtual devices, have {n}",
              file=sys.stderr)
        return 1

    # -- 1. search: one plan per bucket, verified inside ---------------
    from flexflow_tpu.search.serving_plan import (optimize_serving_strategy,
                                                  save_serving_plan)
    ff = _compile_gpt2(lambda c: (setattr(c, "only_data_parallel", False),
                                  setattr(c, "search_budget", 120)))
    plan = optimize_serving_strategy(ff, buckets=BUCKETS, budget=120)
    assert sorted(plan.buckets) == sorted(BUCKETS), plan.buckets
    axis_sizes = dict(ff.dmesh.axis_sizes)

    def _dim0_degree(spec):
        if spec is None or not len(spec):
            return 1
        entry = spec[0]
        if entry is None:
            return 1
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        d = 1
        for a in names:
            d *= axis_sizes.get(a, 1)
        return d

    for b, p in plan.buckets.items():
        assert np.isfinite(p.cost.decode_step) and p.cost.decode_step > 0
        # batch-dim (sample) degrees must divide the bucket — the
        # constraint that makes small buckets lean TP, large DP
        for name, op in p.strategy.ops.items():
            for sp in op.outputs:
                d = _dim0_degree(sp)
                assert b % max(d, 1) == 0, (b, name, sp)
    print(f"serving smoke: searched {len(plan.buckets)} bucket plans; "
          f"decode-step predictions "
          f"{ {b: round(p.cost.decode_step * 1e6, 1) for b, p in sorted(plan.buckets.items())} } us")

    if "--regen" in sys.argv:
        save_serving_plan(ARTIFACT, plan)
        print(f"serving smoke: regenerated {ARTIFACT}")

    # -- 2. the checked-in artifact passes the static verifier --------
    from flexflow_tpu.analysis.plan_verifier import verify_strategy_file
    with open(ARTIFACT) as f:
        doc = json.load(f)
    report = verify_strategy_file(ARTIFACT, doc=doc)
    assert report.ok(), [f_.format() for f_ in report.errors]
    assert sorted(int(k) for k in doc["serving"]["buckets"]) \
        == sorted(BUCKETS), doc["serving"]["buckets"]
    print("serving smoke: checked-in artifact verifies "
          f"({len(report.findings)} finding(s))")

    # -- 3. the KV envelope gate binds ---------------------------------
    # At an HBM budget sized between the sharded and replicated KV
    # footprints, the sharded-KV plan verifies and the replicated one
    # fails TYPED — the gate is enforced statically, before serving.
    from flexflow_tpu.analysis.plan_verifier import (PlanVerificationError,
                                                     verify_serving_plan)
    import copy
    big = max(plan.buckets)
    block = plan.to_block()
    sub = block["buckets"][str(big)]
    assert sub["kv"], "no causal attention layers in the gpt2 graph"

    def kv_variant(shard_degree):
        v = copy.deepcopy(sub)
        for kv in v["kv"].values():
            kv["shard_degree"] = shard_degree
            kv["bytes"] = (2 * big * block["max_seq"]
                           * kv["num_kv_heads"] * kv["head_dim"]
                           * 4) // shard_degree
        return v

    shard, repl = kv_variant(2), kv_variant(1)
    # pin the HBM budget BETWEEN the two variants' envelopes, using the
    # verifier's own arithmetic so the gate decision is never off by a
    # rounding term
    from flexflow_tpu.analysis.plan_verifier import serving_envelope
    by_name = {l.name: l for l in ff.layers}
    axes = dict(ff.dmesh.axis_sizes)
    env_shard = serving_envelope(shard, big, by_name, axes)
    env_repl = serving_envelope(repl, big, by_name, axes)
    assert env_shard["envelope_bytes"] < env_repl["envelope_bytes"]
    hbm = (env_shard["envelope_bytes"] + env_repl["envelope_bytes"]) / 2.0

    def envelope_check(variant):
        from flexflow_tpu.analysis.plan_verifier import (PlanReport,
                                                         _check_serving)
        rep = PlanReport()
        _check_serving(rep, {"version": 1, "max_seq": block["max_seq"],
                             "decode_tokens": block["decode_tokens"],
                             "buckets": {str(big): variant}},
                       by_name, axes, ff.dmesh.spec, hbm)
        return rep

    rep_ok = envelope_check(shard)
    assert rep_ok.ok(), [f_.format() for f_ in rep_ok.errors]
    rep_bad = envelope_check(repl)
    assert not rep_bad.ok(), "replicated-KV plan verified under a " \
                             "budget it cannot fit"
    assert any(f_.seam == "serving-memory" for f_ in rep_bad.errors), \
        [f_.format() for f_ in rep_bad.errors]
    # and the typed path: verify_serving_plan raises, not OOMs
    try:
        verify_serving_plan(
            {"version": 1, "max_seq": block["max_seq"],
             "decode_tokens": block["decode_tokens"],
             "buckets": {str(big): repl}},
            ff.layers, ff.dmesh, hbm_bytes=hbm, context="smoke-gate")
    except PlanVerificationError as e:
        print(f"serving smoke: KV envelope gate binds "
              f"({len(e.findings)} typed finding(s))")
    else:
        print("serving smoke: FAIL — replicated-KV plan passed the "
              "envelope gate", file=sys.stderr)
        return 1

    # -- 4. serve the checked-in plan; decode bit-exact vs baseline ---
    from flexflow_tpu.search.serving_plan import bucket_strategy_doc
    from flexflow_tpu.serving.session import (InferenceSession,
                                              ServingPlanSession)
    import tempfile
    per_bucket = {}
    for b in BUCKETS:
        sub_doc = bucket_strategy_doc(doc, b)
        fd, p = tempfile.mkstemp(suffix=f".bucket{b}.json")
        with os.fdopen(fd, "w") as f:
            json.dump(sub_doc, f)
        try:
            fb = _compile_gpt2(
                lambda c, p=p: (setattr(c, "only_data_parallel", False),
                                setattr(c, "import_strategy_file", p)))
        finally:
            os.unlink(p)
        per_bucket[b] = InferenceSession(fb, [b], decode_segment=4)
    serving = ServingPlanSession(per_bucket)
    baseline = InferenceSession(_compile_gpt2(), BUCKETS,
                                decode_segment=0)

    rng = np.random.default_rng(0)
    checks = 0
    for n_rows, plen, eos in [(1, 6, None), (3, 5, 7), (4, 4, None),
                              (8, 7, 3)]:
        ids = np.zeros((n_rows, 32), np.int32)
        ids[:, :plen] = rng.integers(1, 500, (n_rows, plen))
        got = serving.generate(ids, plen, 12, temperature=0.0,
                               eos_token_id=eos)
        want = baseline.generate(ids, plen, 12, temperature=0.0,
                                 eos_token_id=eos)
        assert np.array_equal(got, want), \
            f"decode mismatch at n={n_rows} eos={eos}"
        checks += 1
    # ragged prompts through the router too
    pl = np.array([6, 2, 5], np.int32)
    ids = np.zeros((3, 32), np.int32)
    for r, p_ in enumerate(pl):
        ids[r, :p_] = rng.integers(1, 500, p_)
    got = serving.generate(ids, pl, 10, temperature=0.0, eos_token_id=7)
    want = baseline.generate(ids, pl, 10, temperature=0.0,
                             eos_token_id=7)
    assert np.array_equal(got, want), "ragged decode mismatch"
    checks += 1
    print(f"serving smoke: {checks} decode request shapes bit-exact vs "
          f"the training-plan baseline")
    print("serving smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
