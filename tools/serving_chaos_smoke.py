"""CI serving-chaos smoke (ci.sh fast tier).

Injects consecutive inference failures via ``FF_FAULT_PLAN`` (kind
``infer_fail@N``: the N-th ``InferenceSession.infer`` call made while
a plan is active raises), drives the HTTP front end-to-end, and
asserts the overload-robustness contract:

  1. K consecutive session failures OPEN the per-model circuit breaker
     — further requests fast-fail 503 + ``Retry-After`` without
     touching the device, and ``/healthz`` reports the open circuit;
  2. after the cooldown, the half-open probe succeeds and RESTORES
     service (circuit closed, 200s again);
  3. ``drain()`` finishes in-flight requests and the process exits
     cleanly.

Exit code 0 = the breaker cycle and graceful drain work end-to-end.

    FF_FAULT_PLAN="infer_fail@0;infer_fail@1;infer_fail@2" \
        python tools/serving_chaos_smoke.py
"""
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BREAKER_THRESHOLD = 3
BREAKER_COOLDOWN_S = 0.5


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def main():
    import numpy as np
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_mlp
    from flexflow_tpu.resilience import faults
    from flexflow_tpu.serving import (InferenceSession, ModelRepository,
                                      serve_http)

    plan = faults.get_plan()
    if not plan.faults:
        faults.install(";".join(f"infer_fail@{i}"
                                for i in range(BREAKER_THRESHOLD)))
        plan = faults.get_plan()
    n_clauses = len(plan.faults)
    assert n_clauses >= BREAKER_THRESHOLD, \
        f"need >= {BREAKER_THRESHOLD} infer_fail clauses, got {n_clauses}"

    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    out = build_mlp(ff, 16, in_dim=8, hidden=(16,), num_classes=4)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    repo = ModelRepository()
    repo.register("m", InferenceSession(ff, batch_buckets=(1, 4)))
    handle = serve_http(repo, port=_free_port(), block=False,
                        max_batch=1,
                        breaker_threshold=BREAKER_THRESHOLD,
                        breaker_cooldown_s=BREAKER_COOLDOWN_S)
    base = f"http://127.0.0.1:{handle.server.server_address[1]}"
    body = json.dumps({"inputs": [{
        "name": "input", "shape": [1, 8], "data": [0.0] * 8}]}).encode()

    def post():
        req = urllib.request.Request(f"{base}/v2/models/m/infer",
                                     data=body)
        try:
            r = urllib.request.urlopen(req, timeout=60)
            return r.status, dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    def healthz():
        try:
            return json.loads(urllib.request.urlopen(
                f"{base}/healthz", timeout=10).read())
        except urllib.error.HTTPError as e:
            return json.loads(e.read())

    try:
        # phase 1: K injected session failures -> breaker opens
        codes = [post()[0] for _ in range(BREAKER_THRESHOLD)]
        assert all(c != 200 for c in codes), \
            f"injected failures must surface as errors, got {codes}"
        h = healthz()
        assert h["serving"]["m"]["circuit"] == "open", h["serving"]
        t0 = time.perf_counter()
        st, hdrs = post()
        fast = time.perf_counter() - t0
        assert st == 503, f"open circuit must 503, got {st}"
        assert int(hdrs.get("Retry-After", 0)) >= 1, hdrs
        assert fast < 1.0, f"open-circuit rejection took {fast:.2f}s"
        mtext = urllib.request.urlopen(f"{base}/metrics",
                                       timeout=10).read().decode()
        assert 'ff_breaker_opens_total{model="m"} 1' in mtext, \
            "breaker open not visible in /metrics"
        assert 'ff_circuit_state{model="m"} 2' in mtext

        # phase 2: cooldown -> half-open probe succeeds -> closed
        time.sleep(BREAKER_COOLDOWN_S + 0.1)
        st, _ = post()
        assert st == 200, f"half-open probe should restore service: {st}"
        h = healthz()
        assert h["serving"]["m"]["circuit"] == "closed", h["serving"]
        st, _ = post()
        assert st == 200, f"service not restored after close: {st}"
        assert plan.unfired() == 0, \
            f"{plan.unfired()} fault clause(s) never fired"

        # phase 3: graceful drain with work in flight
        results = []

        def fire():
            results.append(post()[0])

        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.02)
        clean = handle.drain(deadline_s=10)
        t.join()
        assert results and all(
            c in (200, 503) for c in results), results
        assert clean, "drain abandoned in-flight work"
    except BaseException:
        handle.stop()
        raise
    print(f"serving chaos smoke OK: {n_clauses} injected failures "
          f"opened the breaker, probe restored service, drain clean")


if __name__ == "__main__":
    main()
