"""CI per-parameter ZeRO parity smoke (ci.sh fast tier, ISSUE 10).

Two gates on the 8-virtual-device mesh:

  1. **parity** — the same training run with a searched per-parameter
     ZeRO assignment (``zero_policy=auto``) and fully replicated
     optimizer state must produce BIT-IDENTICAL loss histories:
     optimizer-state sharding is placement, never math. The adopted
     assignment must actually shard something (a vacuous pass proves
     nothing).
  2. **shrunken-world restore** — a checkpoint saved under the ZeRO
     assignment restores into a 4-device world (the elastic device-loss
     path: new mesh, new searched assignment) and the next step's loss
     matches the 8-device continuation.

    python tools/zero_parity_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()

STEPS = 6
HIDDEN = (512, 512)


def build(policy: str, machine_spec=None):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.models import build_mlp
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.only_data_parallel = True
    cfg.zero_policy = policy
    cfg.seed = 5
    ff = FFModel(cfg)
    out = build_mlp(ff, cfg.batch_size, in_dim=32, hidden=HIDDEN,
                    num_classes=8)
    ff.compile(AdamOptimizer(0.01), "sparse_categorical_crossentropy",
               [], output_tensor=out, machine_spec=machine_spec)
    return ff


def batch():
    import numpy as np
    rng = np.random.default_rng(0)
    return {"input": rng.normal(size=(16, 32)).astype(np.float32),
            "label": rng.integers(0, 8, size=(16, 1)).astype(np.int32)}


def run(ff, steps):
    import numpy as np
    b = batch()
    step = ff.executor.make_train_step()
    return [float(np.asarray(ff._run_train_step(step, b)["loss"]))
            for _ in range(steps)]


def main():
    import tempfile

    import numpy as np
    import jax
    n = len(jax.devices())
    if n != 8:
        raise SystemExit(f"expected the 8-virtual-device mesh, got {n}")

    # -- gate 1: searched assignment vs replicated, bit-exact ---------
    ff_z = build("auto")
    za = ff_z.strategy.zero
    if za is None or not za.sharded_params():
        raise SystemExit("zero plan adopted nothing — the parity gate "
                         "would be vacuous")
    losses_z = run(ff_z, STEPS)
    ff_r = build("off")
    losses_r = run(ff_r, STEPS)
    if losses_z != losses_r:
        raise SystemExit(f"ZeRO-vs-replicated loss histories diverge:\n"
                         f"  zero: {losses_z}\n  repl: {losses_r}")
    s = za.summary()

    # -- gate 2: save under ZeRO -> restore into a shrunken world -----
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.runtime.checkpoint import (
        restore_model_checkpoint, save_model_checkpoint)
    with tempfile.TemporaryDirectory() as d:
        save_model_checkpoint(ff_z, d)
        b = batch()
        l_ref = float(np.asarray(ff_z._run_train_step(
            ff_z.executor.make_train_step(), b)["loss"]))
        ff4 = build("auto", machine_spec=MachineSpec(
            num_devices=4, generation="cpu-sim"))
        if ff4.dmesh.num_devices != 4:
            raise SystemExit("shrunken world did not build at 4 devices")
        restore_model_checkpoint(ff4, d)
        l4 = float(np.asarray(ff4._run_train_step(
            ff4.executor.make_train_step(), b)["loss"]))
        if not np.isfinite(l4) or abs(l4 - l_ref) > 1e-5 * abs(l_ref):
            raise SystemExit(f"shrunken-world restore diverged: 8-dev "
                             f"continuation {l_ref!r} vs 4-dev {l4!r}")
    print(f"zero parity smoke OK: {s['n_sharded']}/{s['n_params']} opt "
          f"states sharded ({s['bytes_saved_total'] / 2**20:.2f} "
          f"MiB/device saved), {STEPS} steps bit-identical to "
          f"replicated, 8->4 device restore loss {l4:.6f} == "
          f"{l_ref:.6f}")


if __name__ == "__main__":
    main()
