#!/usr/bin/env python
"""CI overlap parity smoke (ci.sh fast tier) — ISSUE 13.

On a virtual 2-slice (DCN-joined) 8-device CPU config, run the SAME
searched multi-tier plan twice — once on the serial update path and
once with ``FF_OVERLAP=1`` (the bucketed barrier-chained grad-sync
schedule, ``runtime/overlap.py``) — and assert the loss histories are
IDENTICAL (bit-exact, not approximately equal): the overlap schedule
is schedule shaping, it must never change the numbers. Mirrors
``tools/async_parity_smoke.py``.

The plan is pinned across the two runs by exporting the searched
strategy from the serial compile and importing it into the overlapped
one (the overlap-aware cost model scores plans differently, so two
independent searches could adopt different — individually correct but
not bit-comparable — plans). The overlapped run must actually build a
bucket schedule, and its strategy record must pass the plan verifier's
overlapped-ordering check (it runs inside compile).

    python tools/overlap_parity_smoke.py
"""
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def _machine_spec():
    from flexflow_tpu.parallel.machine import MachineSpec
    spec = MachineSpec.detect()
    spec.num_devices = 8
    spec.num_slices = 2
    spec.num_hosts = 2
    spec.dcn_bandwidth_gbps = 1.0
    spec.dcn_latency_us = 20.0
    return spec


def run_fit(overlap: bool, strategy_file: str):
    import numpy as np
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_mlp

    os.environ.pop("FF_OVERLAP", None)
    if overlap:
        os.environ["FF_OVERLAP"] = "1"
    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.seed = 11
    cfg.search_budget = 8
    cfg.search_floor_guard = "false"
    if overlap:
        cfg.import_strategy_file = strategy_file
        # fractional cap: several buckets on this ~360 KB model
        cfg.overlap_bucket_mb = 0.1
    else:
        cfg.export_strategy_file = strategy_file
    ff = FFModel(cfg)
    out = build_mlp(ff, 32, in_dim=64, hidden=(256, 256),
                    num_classes=10)
    ff.compile(SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
               ["accuracy"], machine_spec=_machine_spec(),
               output_tensor=out)
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(192, 64)).astype(np.float32)
    ys = rng.integers(0, 10, size=192).astype(np.int32)
    hist = ff.fit(x=xs, y=ys, epochs=2, verbose=False)
    os.environ.pop("FF_OVERLAP", None)
    return hist, ff


def main():
    import numpy as np

    with tempfile.TemporaryDirectory(prefix="ff_overlap_smoke_") as d:
        sf = os.path.join(d, "strategy.json")
        h_serial, ff_serial = run_fit(False, sf)
        if ff_serial.executor._overlap_schedule is not None:
            raise SystemExit("serial run built an overlap schedule")
        h_overlap, ff_overlap = run_fit(True, sf)
        sched = ff_overlap.executor._overlap_schedule
        if sched is None:
            raise SystemExit("FF_OVERLAP=1 built no overlap schedule")
        rec = getattr(ff_overlap.strategy, "overlap", None)
        if not rec or not rec.get("buckets"):
            raise SystemExit("strategy carries no overlap record")

    if len(h_serial) != len(h_overlap):
        raise SystemExit(f"epoch count diverged: {len(h_serial)} vs "
                         f"{len(h_overlap)}")
    for e, (a, b) in enumerate(zip(h_serial, h_overlap)):
        for k in ("loss", "accuracy"):
            if a[k] != b[k]:
                raise SystemExit(
                    f"epoch {e} {k}: serial {a[k]!r} != overlapped "
                    f"{b[k]!r} — the overlap schedule changed the "
                    f"numbers")
    if not np.isfinite(h_overlap[-1]["loss"]):
        raise SystemExit("non-finite final loss")
    print(f"overlap parity smoke OK: {len(h_overlap)} epochs on a "
          f"searched 2-slice plan, {len(sched.buckets)} bucket(s), "
          f"final loss {h_overlap[-1]['loss']:.6f} identical serial vs "
          f"overlapped")


if __name__ == "__main__":
    main()
