#!/usr/bin/env python
"""Hierarchical-placement smoke (ci.sh fast tier): on a virtual 2-slice
(DCN-joined) 8-device CPU config, run the placement-aware search end to
end — search → static plan verification → one real train step — and
assert the placement artifacts exist:

  - the adopted strategy carries an axis→tier placement and at least
    one recorded reduction-tree choice;
  - the strategy audit record's ``placement`` section predicts the
    hierarchical placement no worse than the flat baseline;
  - the gradient-sync collective lowered to a multi-phase tree
    (intra-slice reduce-scatter → inter-slice all-reduce → intra-slice
    all-gather), not one flat DCN-bottlenecked ring;
  - the verifier's placement check passes on the adopted plan.

See docs/topology.md. The heavyweight gate (paired median-of-ratios
>= 1.1x over workloads) lives in the MULTICHIP dryrun
(``__graft_entry__.dryrun_multichip``); this smoke keeps the fast tier
honest in ~30 s.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_mlp
    from flexflow_tpu.obs.audit import load_strategy_audit
    from flexflow_tpu.parallel.machine import MachineSpec

    n = len(jax.devices())
    if n < 8:
        print(f"placement smoke: need 8 virtual devices, have {n}",
              file=sys.stderr)
        return 1
    spec = MachineSpec.detect()
    spec.num_devices = 8
    spec.num_slices = 2
    spec.num_hosts = 2
    spec.dcn_bandwidth_gbps = 1.0      # meaningfully below cpu-sim ICI
    spec.dcn_latency_us = 20.0
    assert spec.tier_graph.multi_tier, spec.tier_graph

    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 8
    cfg.search_floor_guard = "false"
    cfg.trace = "true"                 # the audit record must be written
    ff = FFModel(cfg)
    out = build_mlp(ff, 32, in_dim=64, hidden=(256, 256), num_classes=10)
    # compile = search -> plan verify (cfg.plan_verify default-on) ->
    # executor build; a placement the verifier rejects raises here
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               machine_spec=spec, output_tensor=out)

    st = ff.strategy
    assert getattr(st, "axis_tiers", None), \
        "adopted strategy carries no axis->tier placement"
    assert "dcn" in set(st.axis_tiers.values()), st.axis_tiers
    trees = getattr(st, "collective_trees", None) or []
    assert trees, "adopted strategy recorded no reduction-tree choices"

    audit_path = getattr(ff, "_strategy_audit_path", None)
    assert audit_path, "search wrote no strategy audit record"
    rec = load_strategy_audit(audit_path).get("placement")
    assert rec, "audit record has no placement section"
    assert rec["flat_over_searched"] >= 1.0 - 1e-9, rec
    gs = [c for c in rec["collectives"]
          if c["site"] == "grad_sync" and len(c["phases"]) > 1]
    assert gs, ("gradient sync did not lower to a multi-phase tree: "
                + repr(rec["collectives"])[:400])
    tiers_used = [p["tier"] for p in gs[0]["phases"]]
    assert "dcn" in tiers_used and "ici" in tiers_used, gs[0]

    rng = np.random.default_rng(0)
    batch = {"input": rng.normal(size=(32, 64)).astype(np.float32),
             "label": rng.integers(0, 10, size=(32, 1)).astype(np.int32)}
    bm = ff._run_train_step(ff.executor.make_train_step(), batch)
    loss = float(np.asarray(bm["loss"]))
    assert np.isfinite(loss), loss

    print(f"placement smoke OK: {gs[0]['algo']} grad-sync tree "
          f"{[p['tier'] for p in gs[0]['phases']]}, flat/searched "
          f"{rec['flat_over_searched']:.2f}x, one train step "
          f"loss={loss:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
