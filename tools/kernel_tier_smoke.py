#!/usr/bin/env python
"""Kernel-tier smoke (ci.sh fast tier): on the 2-slice virtual CPU mesh
with a seq=4 sequence axis, run the searched kernel tier end to end —
calibrated search → adopted strategy carries a NON-DEFAULT kernel
choice → static plan verification → one real train step — and assert
the serialization contract:

  - the adopted ``kernel_impls`` block exports with the strategy and
    ``--import`` honors it verbatim (imported model trains to a
    BIT-IDENTICAL first-step loss — the plan fully determines the
    lowering);
  - the audit-visible kernel record prices the searched choice against
    the forced-XLA baseline (searched-vs-forced-XLA delta);
  - a forced ``attention:xla`` control on the same mesh agrees
    numerically (the kernels are implementations, not different math).

See docs/kernels.md. The long-context memory-envelope gate lives in
``bench.py stage_long_context``; this smoke keeps the fast tier honest.
"""
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
# searched (non-forced) kernel planning requires calibration evidence
os.environ["FF_CALIBRATION_V2"] = "1"

# out of the measured calibration payload range on the CPU sim, so the
# analytic tier prices the choice — the geometry where ring wins
BATCH, SEQ, EMBED, HEADS = 4, 2048, 512, 8


def _build(mutate=None, export=None, imp=None):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.parallel.machine import MachineSpec

    spec = MachineSpec.detect()
    spec.num_devices = 8
    spec.num_slices = 2
    spec.num_hosts = 2
    spec.dcn_bandwidth_gbps = 1.0
    spec.dcn_latency_us = 20.0

    cfg = FFConfig()
    cfg.batch_size = BATCH
    cfg.seq_parallel_degree = 4
    cfg.search_budget = 8
    cfg.search_floor_guard = "false"
    if export:
        cfg.export_strategy_file = export
    if imp:
        cfg.import_strategy_file = imp
    if mutate is not None:
        mutate(cfg)
    ff = FFModel(cfg)
    q = ff.create_tensor((BATCH, SEQ, EMBED), name="q")
    ff.multihead_attention(q, q, q, embed_dim=EMBED, num_heads=HEADS)
    ff.compile(SGDOptimizer(0.01), "mean_squared_error", [],
               machine_spec=spec)
    return ff


def _step_loss(ff):
    import numpy as np
    rng = np.random.default_rng(0)
    batch = {"q": rng.normal(size=(BATCH, SEQ, EMBED))
             .astype(np.float32),
             "label": rng.normal(size=(BATCH, SEQ, EMBED))
             .astype(np.float32)}
    bm = ff._run_train_step(ff.executor.make_train_step(), batch)
    return float(np.asarray(bm["loss"]))


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    if len(jax.devices()) < 8:
        print("kernel tier smoke: need 8 virtual devices",
              file=sys.stderr)
        return 1

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "strategy.json")

        # -- searched: the tier must adopt a non-default attention impl
        ff = _build(export=path)
        assert ff.dmesh.seq_degree == 4, ff.dmesh.axis_sizes
        attn = [l.name for l in ff.layers
                if l.op_type.name == "OP_MULTIHEAD_ATTENTION"][0]
        impls = dict(getattr(ff.strategy, "kernel_impls", {}) or {})
        chosen = impls.get(attn)
        assert chosen and chosen != "xla", \
            f"searched tier kept the default impl: {impls}"

        # -- audit: calibration-priced searched-vs-forced-XLA delta
        rec = getattr(ff, "_kernel_record", None)
        assert rec and rec["n_nondefault"] >= 1, rec
        op = next(o for o in rec["ops"] if o["name"] == attn)
        assert op["impl"] == chosen and not op["forced"], op
        assert op["forced_xla_s"] >= op["predicted_s"] > 0, op
        delta = op["forced_xla_s"] - op["predicted_s"]

        # -- exported artifact carries the block; verifier accepts it
        import json
        with open(path) as f:
            doc = json.load(f)
        assert doc.get("kernel_impls", {}).get(attn) == chosen, \
            doc.get("kernel_impls")
        from flexflow_tpu.analysis.plan_verifier import \
            verify_strategy_file
        report = verify_strategy_file(path)
        assert report.ok(), [f.format() for f in report.errors]

        loss = _step_loss(ff)
        assert np.isfinite(loss), loss

        # -- import honors the block verbatim, bit-exact replay
        ff_imp = _build(imp=path)
        assert dict(ff_imp.strategy.kernel_impls) == impls, \
            ff_imp.strategy.kernel_impls
        assert ff_imp.executor._kernel_impls.get(attn) == chosen
        loss_imp = _step_loss(ff_imp)
        assert loss_imp == loss, \
            f"import round-trip not bit-exact: {loss_imp} != {loss}"

        # -- forced-xla control on the SAME mesh: same math, different
        #    kernel — numerics agree within kernel tolerance
        def force_xla(cfg):
            cfg.kernel_impls = "attention:xla"
        ff_xla = _build(mutate=force_xla)
        assert ff_xla.strategy.kernel_impls.get(attn) == "xla"
        loss_xla = _step_loss(ff_xla)
        assert np.isfinite(loss_xla)
        assert abs(loss_xla - loss) <= 3e-2 * max(abs(loss_xla), 1.0), \
            (loss, loss_xla)

    print(f"kernel tier smoke OK: searched impl {attn}={chosen} "
          f"(vs forced-xla delta {delta:.3e}s predicted), verified, "
          f"import bit-exact (loss={loss:.6f}), xla control "
          f"loss={loss_xla:.6f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
