"""CI fast-tier smoke: searched reshard plans are BIT-IDENTICAL to the
naive path (ISSUE 6 acceptance).

Two probes, both on the 8-virtual-device CPU mesh:

  1. the raw transition matrix (replicated<->sharded, axis swap,
     split-factor change, sub-mesh moves) applied to one array through
     ``ReshardPlanner.apply`` — searched vs ``FF_NAIVE_RESHARD=1``
     outputs must be exactly equal;
  2. a pipelined MLP (the region entry/exit transitions the planner
     owns in the executor): forward outputs and one train-step loss of
     a searched build vs a naive build from the same seed must be
     exactly equal.

Exits non-zero on any mismatch.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.parallel.machine import (DeviceMesh,  # noqa: E402
                                           MachineSpec)
from flexflow_tpu.parallel.reshard import ReshardPlanner  # noqa: E402

MATRIX = [
    (P(), P("x0", None)),
    (P("x0"), P()),
    (P("x0", "x1"), P("x1", "x0")),
    (P(("x0", "x1"), None), P("x0", None)),
    (P("x0"), P("x2")),
    (P("x0", None), P(None, "x0")),
    (P(("x0", "x1"), "x2"), P("x2", ("x0", "x1"))),
]


def check(name, a, b):
    if not np.array_equal(np.asarray(a), np.asarray(b)):
        print(f"reshard parity smoke: MISMATCH at {name}")
        sys.exit(1)
    print(f"  {name}: bit-exact")


def matrix_probe():
    dmesh = DeviceMesh(MachineSpec(num_devices=8))
    planner = ReshardPlanner(dmesh)
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((8, 8, 4)).astype(np.float32))
    for i, (src, dst) in enumerate(MATRIX):
        searched = jax.jit(lambda a: planner.apply(a, src, dst))(x)
        os.environ["FF_NAIVE_RESHARD"] = "1"
        naive = jax.jit(lambda a: planner.apply(a, src, dst))(x)
        del os.environ["FF_NAIVE_RESHARD"]
        check(f"matrix[{i}] {src} -> {dst}", searched, x)
        check(f"matrix[{i}] searched-vs-naive", searched, naive)


def _build_pipelined():
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.pipeline_stages = 2
    cfg.pipeline_microbatches = 4
    cfg.seed = 11
    ff = FFModel(cfg)
    t = ff.create_tensor((16, 32), name="x")
    h = ff.dense(t, 64, activation="relu")
    for _ in range(3):
        h = ff.dense(h, 64, activation="relu")
    out = ff.softmax(ff.dense(h, 4))
    ff.compile(SGDOptimizer(0.05), "sparse_categorical_crossentropy",
               [], output_tensor=out)
    return ff


def model_probe():
    rng = np.random.default_rng(1)
    xb = rng.standard_normal((16, 32)).astype(np.float32)
    yb = rng.integers(0, 4, size=(16, 1)).astype(np.int32)
    results = {}
    for mode in ("searched", "naive"):
        if mode == "naive":
            os.environ["FF_NAIVE_RESHARD"] = "1"
        try:
            ff = _build_pipelined()
            fwd = np.asarray(ff.executor.make_forward()(
                ff.params, ff.state, {"x": xb}))
            step = ff.executor.make_train_step()
            loss = np.asarray(ff._run_train_step(
                step, {"x": xb, "label": yb})["loss"])
            results[mode] = (fwd, loss)
        finally:
            os.environ.pop("FF_NAIVE_RESHARD", None)
    check("pipelined forward", results["searched"][0],
          results["naive"][0])
    check("pipelined train loss", results["searched"][1],
          results["naive"][1])
    if not np.isfinite(results["searched"][1]):
        print("reshard parity smoke: non-finite loss")
        sys.exit(1)


if __name__ == "__main__":
    matrix_probe()
    model_probe()
    print("reshard parity smoke: OK")
